"""Tenant registry + tiered residency manager (ISSUE 14 tentpole).

The deployment shape this reproduces is the source gem's Redis model —
many small per-tenant filters multiplexed onto one server — at TPU
scale: HBM is treated as an LRU-ish cache over host-RAM blobs over
on-disk checkpoints, the way an OS page cache or a database buffer pool
treats fast memory as a cache over durable storage.

Residency states (per tenant)::

    RESIDENT --evict--> WARM --trim--> COLD
        ^                 |              |
        +----hydrate------+--------------+

* **RESIDENT** — device arrays live; the tenant is in the server's
  ``_filters`` registry and serves at device speed.
* **WARM** — the filter is one ``ckpt.snapshot_blob`` blob in a bounded
  host-RAM pool; hydration is a ``restore_blob`` (host→device copy, no
  disk IO).
* **COLD** — only the durable tier holds it (checkpoint generation
  and/or op-log records); hydration restores the newest checkpoint.

Durability invariants (what makes "SIGKILL during eviction loses
nothing" true):

1. Eviction never creates a new durability obligation — every acked
   write was already op-logged (or checkpoint-covered) before its RPC
   returned. Eviction only ADDS a durable generation: after the blob is
   taken, the tenant's checkpointer is closed with a final checkpoint
   stamped at the evicted ``applied_seq``.
2. The checkpoint-keyed op-log truncation sweep treats paged tenants
   exactly like resident ones: :meth:`TenantStore.truncate_floor`
   reports the lowest seq any paged tenant still needs replayed from
   the log (``None`` = some paged tenant has no durable checkpoint at
   all, so the whole log must stay — the same rule the sweep already
   applies to resident filters without a sink). A SIGKILL at ANY point
   therefore recovers through the ordinary replay path: manifest →
   restore-on-create → op-log tail.
3. The eviction critical section runs under the victim's op lock and
   unpublishes it from the registry before releasing, so no write can
   land on device arrays the blob missed; stragglers that already
   resolved the ``_Managed`` re-check its ``evicted`` flag after
   acquiring the lock (``BloomService._op``) and re-resolve through the
   hydration path.

Quotas + fairness (the PR-2 shed-path plug-in): hydration is the
expensive fault path, so it gets admission control of its own — a
global in-flight cap (``hydration_max_concurrent``) and a per-tenant
token bucket (``tenant_hydrations_per_min``). A request that would
exceed either is shed with ``RESOURCE_EXHAUSTED`` + the server's
adaptive ``retry_after_ms`` hint (the same signal the in-flight cap
emits), so a cold-tenant stampede backs off instead of churning the hot
set — and because eviction ranks by decayed key-traffic heat (the same
load signal the PR-10 per-slot counters follow), one-touch cold tenants
can never out-rank the hot set for residency.

Lock ranks (declared in :mod:`tpubloom.analysis.lock_order`): the
manager's bookkeeping lock is ``storage.state`` and is a LEAF apart
from counter/gauge updates — it is never held across a filter/registry
lock, a device launch, or blob IO. Hydration waiters block on a plain
event holding no locks (``locks.note_blocking("storage.hydrate")``
enforces that at runtime); the eviction path's only nesting is the
pre-existing ``filter.op -> service.registry`` unpublish edge.

Fault points: ``storage.evict`` fires before an eviction takes the
victim's lock (an injected fault aborts the eviction cleanly — the
tenant stays resident and serving); ``storage.hydrate`` fires before a
hydration restores (nothing published — the faulted request errors and
a retry re-hydrates).
"""

from __future__ import annotations

import logging
import math
import threading
import time
from typing import Optional

from tpubloom import checkpoint as ckpt
from tpubloom import faults
from tpubloom.obs import counters as obs_counters
from tpubloom.obs import flight as obs_flight
from tpubloom.obs import trace as obs_trace
from tpubloom.utils import locks

log = logging.getLogger("tpubloom.storage")

#: Residency states (entry.state).
RESIDENT = "resident"
EVICTING = "evicting"
WARM = "warm"
COLD = "cold"
HYDRATING = "hydrating"


class StorageConfig:
    """Residency budget + paging policy knobs.

    ``max_resident_filters`` / ``max_resident_bytes`` cap the RESIDENT
    tier (None = that dimension unbounded; both None disables paging
    pressure but keeps the registry/bookkeeping, which is what the
    server does when the flags are omitted — storage is only attached
    when a budget is set). ``warm_pool_bytes`` bounds the host-RAM blob
    pool: over budget, the coldest WARM tenants whose state is fully
    checkpoint-covered are trimmed to COLD (tenants without a durable
    generation are never trimmed — correctness beats the budget).
    ``hydration_max_concurrent`` + ``tenant_hydrations_per_min`` are
    the shed-path quotas documented in the module docstring.
    ``heat_halflife_s`` is the decay of the key-traffic heat eviction
    ranks by."""

    def __init__(
        self,
        max_resident_filters: Optional[int] = None,
        max_resident_bytes: Optional[int] = None,
        *,
        warm_pool_bytes: int = 256 * 1024 * 1024,
        hydration_max_concurrent: int = 4,
        tenant_hydrations_per_min: int = 0,
        heat_halflife_s: float = 60.0,
    ):
        self.max_resident_filters = (
            int(max_resident_filters) if max_resident_filters else None
        )
        self.max_resident_bytes = (
            int(max_resident_bytes) if max_resident_bytes else None
        )
        self.warm_pool_bytes = int(warm_pool_bytes)
        self.hydration_max_concurrent = int(hydration_max_concurrent)
        self.tenant_hydrations_per_min = int(tenant_hydrations_per_min)
        self.heat_halflife_s = float(heat_halflife_s)


class _Tenant:
    """One tenant's residency bookkeeping (all fields guarded by the
    store's ``storage.state`` lock unless noted)."""

    __slots__ = (
        "name", "state", "create_req", "blob", "blob_bytes",
        "applied_seq", "landed_seq", "device_bytes",
        "heat", "heat_t", "q_tokens", "q_t", "busy_done",
    )

    def __init__(self, name: str):
        self.name = name
        self.state = RESIDENT
        #: CreateFilter-shaped request that rebuilds this filter
        #: (manifest format — what promotion's rebuild_manifest needs
        #: for paged tenants and what a COLD restore parses its config
        #: from)
        self.create_req: Optional[dict] = None
        #: WARM tier: the snapshot blob (host RAM), None when COLD/RESIDENT
        self.blob: Optional[bytes] = None
        self.blob_bytes = 0
        #: newest op-log seq the paged state contains (valid when not
        #: RESIDENT — the resident filter's _Managed.applied_seq wins)
        self.applied_seq = 0
        #: newest seq covered by a DURABLE checkpoint generation; None =
        #: nothing durable beyond the op log, the truncation sweep must
        #: keep this tenant's whole record history
        self.landed_seq: Optional[int] = None
        #: approximate device footprint while resident (budget math)
        self.device_bytes = 0
        #: exponentially-decayed key traffic (the eviction rank) + its
        #: last decay timestamp
        self.heat = 0.0
        self.heat_t = time.monotonic()
        #: per-tenant hydration token bucket (quota satellite)
        self.q_tokens: Optional[float] = None
        self.q_t = time.monotonic()
        #: set while HYDRATING/EVICTING; waiters block on it (holding no
        #: locks) and then re-resolve
        self.busy_done: Optional[threading.Event] = None

    def decayed_heat(self, now: float, halflife: float) -> float:
        if halflife <= 0:
            return self.heat
        return self.heat * (0.5 ** ((now - self.heat_t) / halflife))

    def evict_rank(self, now: float, halflife: float) -> tuple:
        """Eviction order: (log2 heat band, last touch). The band
        protects the hot set — orders-of-magnitude traffic differences
        dominate — while RECENCY breaks ties inside a band. Pure
        min-heat ranking thrashes under concurrent scans: every
        worker's *in-progress* tenant (touched once so far) ranks
        below its *finished* neighbours (touched a few times), so
        concurrent workers keep evicting each other's working set —
        measured at ~20 hydrations per logical op in the smoke before
        banding, ~2 after."""
        band = int(math.log2(self.decayed_heat(now, halflife) + 1.0))
        return (band, self.heat_t)


def _device_bytes(filt) -> int:
    """Approximate device footprint of a live filter — shape math only,
    never a transfer."""
    try:
        if hasattr(filt, "layers"):  # scalable stack
            return int(sum(layer.words.nbytes for layer in filt.layers))
        words = getattr(filt, "words", None)
        if words is not None:
            return int(words.nbytes)
    except Exception:  # noqa: BLE001 — an estimate must never raise
        pass
    cfg = getattr(filt, "config", None) or getattr(filt, "base_config", None)
    return max(1, int(getattr(cfg, "m", 0)) // 8)


class TenantStore:
    """The registry/storage pair's storage half: every tenant the server
    has ever created (resident or paged) has one entry here; the
    server's ``_filters`` dict holds only the RESIDENT tier."""

    def __init__(self, service, config: Optional[StorageConfig] = None):
        self._service = service
        self.config = config or StorageConfig()
        self._lock = locks.named_lock("storage.state")
        self._entries: dict[str, _Tenant] = {}
        self._resident_bytes = 0
        self._warm_bytes = 0
        self._hydrating = 0
        self._update_gauges_locked()

    # -- bookkeeping hooks (called by the service at its commit points) ------

    def note_created(self, name: str) -> None:
        """A filter was just created/attached/installed RESIDENT —
        register (or refresh) its entry. Idempotent."""
        svc = self._service
        mf = svc._filters.get(name)
        if mf is None:
            return
        create_req = svc._manifest_req_for(name, mf.filter)
        nbytes = _device_bytes(mf.filter)
        with self._lock:
            if svc._filters.get(name) is not mf:
                # dropped (or replaced) between the lookup above and
                # this lock — filing now would resurrect a phantom
                # entry for a tenant whose forget already ran
                return
            e = self._entries.get(name)
            if e is None:
                e = self._entries[name] = _Tenant(name)
            if e.state in (EVICTING, HYDRATING):
                # a transition owns the entry's bookkeeping — refresh
                # only the rebuild recipe and let it settle its own
                # state (the evictor re-reads the registry, so it
                # operates on whatever filter is published now)
                e.create_req = create_req
                return
            was = e.device_bytes if e.state == RESIDENT else 0
            e.state = RESIDENT
            e.create_req = create_req
            self._warm_bytes -= e.blob_bytes
            e.blob, e.blob_bytes = None, 0
            e.device_bytes = nbytes
            self._resident_bytes += nbytes - was
            self._update_gauges_locked()

    def forget(self, name: str) -> None:
        """The tenant was dropped (DropFilter / retain_only). EVICTING
        entries reclaim their device bytes HERE: the evictor's filing
        block finds the entry gone and skips its own accounting, so
        skipping it here too would leak phantom resident bytes into the
        budget forever (permanent eviction pressure)."""
        with self._lock:
            e = self._entries.pop(name, None)
            if e is None:
                return
            if e.state in (RESIDENT, EVICTING):
                self._resident_bytes -= e.device_bytes
            self._warm_bytes -= e.blob_bytes
            if e.busy_done is not None:
                # a waiter parked on an in-flight transition must wake
                # NOW and discover the tenant is gone (NOT_FOUND), not
                # stall out its full wait timeout
                e.busy_done.set()
                e.busy_done = None
            self._update_gauges_locked()

    def retain_only(self, names) -> None:
        keep = set(names)
        with self._lock:
            victims = [n for n in self._entries if n not in keep]
        for n in victims:
            self.forget(n)

    def touch(self, name: str, nkeys: int = 1) -> None:
        """Record key traffic against the tenant's heat (the eviction
        rank) — called from the RPC wrapper with the request's batch
        size, so the rank follows the same load signal the PR-10
        per-slot traffic counters expose."""
        now = time.monotonic()
        hl = self.config.heat_halflife_s
        with self._lock:
            e = self._entries.get(name)
            if e is None:
                return
            e.heat = e.decayed_heat(now, hl) + max(1, int(nkeys))
            e.heat_t = now

    # -- views ---------------------------------------------------------------

    def names(self) -> list:
        with self._lock:
            return list(self._entries)

    def has(self, name: str) -> bool:
        with self._lock:
            return name in self._entries

    def max_applied_seq(self) -> int:
        """Highest op-log seq any PAGED tenant's state contains —
        promotion folds this into its adopted-seq computation so a
        bare replica's fresh log never mints seqs below a paged
        tenant's history."""
        with self._lock:
            return max(
                (e.applied_seq for e in self._entries.values()
                 if e.state not in (RESIDENT,)),
                default=0,
            )

    def summary(self) -> dict:
        with self._lock:
            counts = {RESIDENT: 0, EVICTING: 0, WARM: 0, COLD: 0, HYDRATING: 0}
            for e in self._entries.values():
                counts[e.state] += 1
            return {
                "tenants": len(self._entries),
                "resident": counts[RESIDENT] + counts[EVICTING],
                "warm": counts[WARM] + counts[HYDRATING],
                "cold": counts[COLD],
                "resident_bytes": self._resident_bytes,
                "warm_bytes": self._warm_bytes,
                "max_resident_filters": self.config.max_resident_filters,
                "max_resident_bytes": self.config.max_resident_bytes,
            }

    def create_reqs(self) -> dict:
        """name -> manifest-shaped create request for every non-RESIDENT
        tenant (promotion's rebuild_manifest — resident tenants rebuild
        from their live filters; a tenant mid-transition is in neither
        registry snapshot, so its recipe must come from here — the
        caller's setdefault keeps the live version when both exist)."""
        with self._lock:
            return {
                e.name: dict(e.create_req)
                for e in self._entries.values()
                if e.state != RESIDENT and e.create_req
            }

    def truncate_floor(self) -> Optional[int]:
        """Lowest op-log seq a paged tenant still needs from the log
        (invariant 2 in the module docstring). None = some paged tenant
        has no durable checkpoint — keep the whole log."""
        floor = None
        with self._lock:
            for e in self._entries.values():
                if e.state == RESIDENT:
                    continue  # the resident sweep already covers it
                # EVICTING counts as PAGED here, deliberately: the
                # victim is already unpublished from the registry (the
                # resident sweep no longer sees it) but its fresh
                # durable generation has not landed yet — its floor is
                # whatever the PREVIOUS filing recorded, i.e. None for
                # a first eviction, which pins the whole log for the
                # duration of the eviction window. Conservative, and
                # exactly what "SIGKILL at ANY point loses nothing"
                # requires.
                if e.landed_seq is None:
                    return None
                floor = (
                    e.landed_seq if floor is None
                    else min(floor, e.landed_seq)
                )
        return floor if floor is not None else 1 << 62

    def paged_plan_items(self, exclude) -> list:
        """``[(name, loader)]`` for every tenant NOT in ``exclude`` —
        the full-resync plan's paged half: a replica bootstrapping off
        this primary must receive paged tenants too, without forcing
        them resident. Each loader returns ``(blob, applied_seq)`` at
        send time (lazy, one blob in flight — same discipline as the
        resident half)."""
        out = []
        with self._lock:
            for e in self._entries.values():
                if e.name in exclude or e.state in (RESIDENT,):
                    continue
                out.append((e.name, self._make_loader(e.name)))
        return out

    def _make_loader(self, name: str):
        def load():
            return self.peek_blob(name)

        return load

    def peek_blob(self, name: str):
        """``(blob, applied_seq)`` of a paged tenant WITHOUT hydrating:
        WARM answers from the pool; COLD reads the newest checkpoint
        generation's bytes straight off the sink; a tenant that went
        resident since the caller planned snapshots live under its op
        lock, and an in-flight transition is waited out (no forced
        hydration just to stream a blob)."""
        deadline = time.monotonic() + 60.0
        while True:
            wait_ev = None
            with self._lock:
                e = self._entries.get(name)
                if e is None:
                    raise KeyError(name)
                if e.blob is not None:
                    return e.blob, e.applied_seq
                state, applied, create_req = e.state, e.applied_seq, e.create_req
                if state in (HYDRATING, EVICTING):
                    wait_ev = e.busy_done
            if wait_ev is not None:
                if time.monotonic() > deadline:
                    raise RuntimeError(
                        f"tenant {name!r} stuck in transition — cannot "
                        f"stream its blob"
                    )
                locks.note_blocking("storage.hydrate")
                wait_ev.wait(timeout=5.0)
                continue
            if state == COLD:
                return self._sink_blob(name, create_req), applied
            # resident: take a live snapshot under the op lock
            mf = self._service._filters.get(name)
            if mf is None or getattr(mf, "evicted", False):
                # transition raced us (or a retain_only is mid-teardown)
                # — back off briefly and re-read the state instead of
                # hammering the bookkeeping lock
                if time.monotonic() > deadline:
                    raise RuntimeError(
                        f"tenant {name!r} stuck in transition — cannot "
                        f"stream its blob"
                    )
                time.sleep(0.002)
                continue
            with mf.lock:
                if getattr(mf, "evicted", False):
                    continue
                _, _, blob = ckpt.snapshot_blob(
                    mf.filter, extra={"repl_seq": mf.applied_seq}
                )
                return blob, mf.applied_seq

    def _sink_blob(self, name: str, create_req) -> bytes:
        svc = self._service
        config = svc._config_of(create_req or {"name": name})
        sink = svc._sink_factory(config)
        blob = sink.get(name) if sink is not None else None
        if blob is None:
            raise RuntimeError(
                f"cold tenant {name!r} has no readable checkpoint "
                f"generation — cannot stream it"
            )
        return blob

    # -- hydration (the read side of the cache) ------------------------------

    def resolve(self, name: str, *, control_plane: bool = False):
        """The ``_get`` fault path: return the RESIDENT ``_Managed`` for
        ``name``, hydrating (or waiting on an in-flight hydration /
        eviction) as needed; ``None`` for an unknown tenant. May raise
        ``RESOURCE_EXHAUSTED`` when a hydration quota sheds the request
        (never with ``control_plane=True`` — replication/replay/admin
        paths must make progress regardless of data-plane pressure)."""
        from tpubloom.server import protocol

        svc = self._service
        deadline = time.monotonic() + 120.0
        while True:
            mf = svc._filters.get(name)
            if mf is not None and not getattr(mf, "evicted", False):
                return mf
            if time.monotonic() > deadline:
                # a wedged transition must surface, not spin a worker
                # thread forever
                raise protocol.BloomServiceError(
                    "INTERNAL",
                    f"tenant {name!r} stuck in a residency transition",
                )
            wait_ev = None
            start = False
            shed_msg = None
            with self._lock:
                e = self._entries.get(name)
                if e is None:
                    return None
                if e.state in (HYDRATING, EVICTING):
                    wait_ev = e.busy_done
                elif e.state in (WARM, COLD):
                    if control_plane:
                        start = True
                    elif self._hydrating >= self.config.hydration_max_concurrent:
                        shed_msg = (
                            f"hydration concurrency cap "
                            f"{self.config.hydration_max_concurrent} "
                            f"reached — retry with backoff"
                        )
                    elif not self._quota_ok_locked(e):
                        shed_msg = (
                            f"tenant {name!r} exceeded its hydration "
                            f"quota — retry with backoff"
                        )
                    else:
                        start = True
                    if start:
                        e.state = HYDRATING
                        e.busy_done = threading.Event()
                        self._hydrating += 1
                        self._update_gauges_locked()
                # else: state RESIDENT with the registry briefly out of
                # sync (bookkeeping races the publish by a few
                # instructions) — fall through and loop
            if start:
                # the hydration runs on the faulting request's thread —
                # a storage.hydrate child span names exactly where a
                # cold-tenant request spent its time (ISSUE 15; no-op
                # without an armed request context)
                with obs_trace.span("storage.hydrate", tenant=name):
                    return self._hydrate(name)
            if shed_msg is not None:
                # quota shed (PR-2 shed path): the same adaptive
                # retry_after_ms signal the in-flight cap emits, so a
                # cold-tenant stampede paces itself off instead of
                # churning the hot set
                hint = svc.shed_hint()
                obs_counters.incr("storage_hydrations_shed")
                svc.metrics.count("requests_shed")
                obs_flight.note(
                    "shed", source="hydration", tenant=name,
                    retry_after_ms=hint,
                )
                raise protocol.BloomServiceError(
                    "RESOURCE_EXHAUSTED", shed_msg,
                    details={"retry_after_ms": hint, "tenant": name},
                )
            if wait_ev is not None:
                # block holding NO locks (runtime-enforced) until the
                # in-flight transition settles, then re-resolve
                locks.note_blocking("storage.hydrate")
                wait_ev.wait(timeout=60.0)
                continue
            time.sleep(0.001)

    def _quota_ok_locked(self, e: _Tenant) -> bool:
        per_min = self.config.tenant_hydrations_per_min
        if per_min <= 0:
            return True
        now = time.monotonic()
        if e.q_tokens is None:
            e.q_tokens = float(per_min)
        e.q_tokens = min(
            float(per_min), e.q_tokens + per_min * (now - e.q_t) / 60.0
        )
        e.q_t = now
        if e.q_tokens < 1.0:
            return False
        e.q_tokens -= 1.0
        return True

    def _hydrate(self, name: str):
        """Restore one WARM/COLD tenant to RESIDENT (caller claimed the
        HYDRATING state). Publishes the fresh ``_Managed`` into the
        registry, then flips the entry — waiters loop until they see
        the registry entry."""
        svc = self._service
        t0 = time.perf_counter()
        try:
            faults.fire("storage.hydrate")
            with self._lock:
                e = self._entries[name]
                blob, applied, create_req = e.blob, e.applied_seq, e.create_req
            if blob is not None:
                mf = svc._managed_from_blob(blob, applied)
            else:
                mf = svc._managed_from_sink(name, create_req)
            #: durable floor at hydration time — if the tenant is
            #: evicted again WITHOUT advancing past it (read-only
            #: churn), the old generation still covers everything and
            #: the eviction skips its final checkpoint (the thrash
            #: fast path: a query-only residency cycle costs no disk
            #: write)
            mf.hydration_landed_seq = e.landed_seq
            with svc._lock:
                svc._filters[name] = mf
            nbytes = _device_bytes(mf.filter)
            now = time.monotonic()
            with self._lock:
                e = self._entries.get(name)
                if e is not None:
                    e.state = RESIDENT
                    self._warm_bytes -= e.blob_bytes
                    e.blob, e.blob_bytes = None, 0
                    e.device_bytes = nbytes
                    self._resident_bytes += nbytes
                    # a hydration IS an access: bump heat recency so the
                    # follow-on budget pass never picks the tenant it
                    # just paged in (self-eviction would live-lock the
                    # faulting request)
                    e.heat = e.decayed_heat(now, self.config.heat_halflife_s) + 1.0
                    e.heat_t = now
                    self._update_gauges_locked()
            if e is None:
                # the tenant was DELETED (retain_only / a racing drop)
                # while we hydrated: undo the publish — leaving the
                # resurrected filter in the registry would serve a
                # tenant the primary dropped, invisible to the
                # residency manager forever
                with svc._lock:
                    if svc._filters.get(name) is mf:
                        svc._filters.pop(name, None)
                if mf.checkpointer is not None:
                    mf.checkpointer.close(final_checkpoint=False)
                from tpubloom.server import protocol

                raise protocol.BloomServiceError(
                    "NOT_FOUND",
                    f"filter {name!r} was dropped during hydration",
                )
            obs_counters.incr("storage_hydrations_total")
            svc.metrics.observe_hydration(time.perf_counter() - t0)
        except BaseException:
            with self._lock:
                e = self._entries.get(name)
                if e is not None and e.state == HYDRATING:
                    e.state = WARM if e.blob is not None else COLD
            raise
        finally:
            with self._lock:
                self._hydrating -= 1
                e = self._entries.get(name)
                if e is not None and e.busy_done is not None:
                    e.busy_done.set()
                    e.busy_done = None
                self._update_gauges_locked()
        self.ensure_budget(protect=name)
        return mf

    # -- eviction (the write-back side) --------------------------------------

    def ensure_budget(self, protect: Optional[str] = None) -> int:
        """Evict cold-ranked residents until the HBM budget holds;
        returns how many were evicted. Runs on the calling thread,
        OUTSIDE every lock — budget enforcement is synchronous and
        deterministic (the transient overshoot is exactly the tenant
        being hydrated). ``protect`` names a tenant this pass must not
        pick: the hydration path protects the tenant it JUST paged in —
        with a full budget of hotter tenants the newcomer is otherwise
        always the min-rank victim, and the faulting request would
        hydrate/evict in a loop without ever being served. No-op during
        op-log replay (replay pages down ONCE at the end instead of
        thrashing per record)."""
        if self._service._replaying:
            return 0
        evicted = 0
        while True:
            with self._lock:
                victim = self._pick_victim_locked(protect)
                if victim is None:
                    return evicted
                victim.state = EVICTING
                victim.busy_done = threading.Event()
                self._update_gauges_locked()
            try:
                # evictions run on the thread that grew residency — the
                # span shows up under the request that paid for them
                with obs_trace.span("storage.evict", tenant=victim.name):
                    self._evict(victim.name)
                evicted += 1
            except BaseException as exc:  # noqa: BLE001 — eviction must fail soft
                # an aborted eviction (injected storage.evict fault, a
                # transient snapshot error) leaves the tenant RESIDENT
                # and serving — the budget stays over until the next
                # pressure event retries
                log.warning("eviction of %r aborted: %r", victim.name, exc)
                with self._lock:
                    e = self._entries.get(victim.name)
                    if e is not None and e.state == EVICTING:
                        e.state = RESIDENT
                        if e.busy_done is not None:
                            e.busy_done.set()
                            e.busy_done = None
                    self._update_gauges_locked()
                return evicted

    def _over_budget_locked(self) -> bool:
        cfg = self.config
        resident = sum(
            1 for e in self._entries.values()
            if e.state in (RESIDENT, EVICTING)
        )
        if cfg.max_resident_filters and resident > cfg.max_resident_filters:
            return True
        if cfg.max_resident_bytes and self._resident_bytes > cfg.max_resident_bytes:
            return True
        return False

    def _pick_victim_locked(self, protect: Optional[str] = None) -> Optional[_Tenant]:
        if not self._over_budget_locked():
            return None
        now = time.monotonic()
        hl = self.config.heat_halflife_s
        svc = self._service
        candidates = [
            e for e in self._entries.values()
            if e.state == RESIDENT and e.name in svc._filters
            and e.name != protect
        ]
        if not candidates or (protect is None and len(candidates) <= 1):
            # without an explicit protectee, never evict the last
            # resident — the request that faulted it in is about to use
            # it. WITH one (the hydration path), evicting the only
            # other candidate is exactly right (budget-of-one paging).
            return None
        return min(candidates, key=lambda e: e.evict_rank(now, hl))

    def _evict(self, name: str) -> None:
        """One eviction: snapshot under the victim's op lock, unpublish,
        land a final durable checkpoint, file the blob WARM.

        Failure discipline: an exception BEFORE the unpublish aborts
        cleanly (ensure_budget reverts the entry to RESIDENT — the
        tenant keeps serving). From the unpublish on, the eviction is
        COMMITTED: everything after runs best-effort and the blob is
        ALWAYS filed, because a "revert" at that point would strand a
        tenant that is in neither the registry nor the warm pool."""
        svc = self._service
        faults.fire("storage.evict")
        mf = svc._filters.get(name)
        if mf is None:
            raise RuntimeError(f"victim {name!r} vanished before eviction")
        with mf.lock:
            if getattr(mf, "evicted", False):
                raise RuntimeError(f"victim {name!r} already evicted")
            _, _, blob = ckpt.snapshot_blob(
                mf.filter, extra={"repl_seq": mf.applied_seq}
            )
            applied = mf.applied_seq
            mf.evicted = True
            with svc._lock:  # declared: filter.op -> service.registry
                svc._filters.pop(name, None)
        # durable point: close the checkpointer with a final generation
        # stamped at the evicted seq (COLD-tier coverage + the
        # truncation floor). CLEAN fast path: a tenant that never
        # advanced past the durable floor it hydrated from (read-only
        # residency cycle) is already fully covered by the existing
        # generation — skip the write, keep the floor. Failure keeps
        # the WARM blob + the log tail (landed_seq stays at the last
        # generation that DID land).
        landed = None
        clean = (
            getattr(mf, "hydration_landed_seq", None) is not None
            and mf.hydration_landed_seq >= applied
        )
        if mf.checkpointer is not None:
            try:
                with mf.lock:  # exclude stragglers during the final snapshot
                    ok = mf.checkpointer.close(final_checkpoint=not clean)  # lint: allow(blocking-under-lock): the filter is already unpublished + flagged evicted — only stragglers briefly contend, exactly the DropFilter close discipline
            except Exception:  # noqa: BLE001 — eviction is committed
                ok = False
                log.exception("eviction of %r: checkpointer close failed", name)
            if ok:
                landed = applied
            else:
                # best KNOWN durable floor, not just this residency
                # cycle's: a hydrated tenant whose fresh checkpointer
                # never landed still has the generation it hydrated
                # from on disk — regressing to None would pin the whole
                # op log (and the blob WARM) for no reason
                cands = []
                meta = mf.checkpointer.last_landed_meta
                if meta is not None:
                    cands.append(int(meta.get("repl_seq") or 0))
                prior = getattr(mf, "hydration_landed_seq", None)
                if prior is not None:
                    cands.append(int(prior))
                landed = max(cands) if cands else None
                log.warning(
                    "eviction of %r: final checkpoint did not land (%r); "
                    "keeping the op-log tail past seq %s",
                    name, mf.checkpointer.last_error, landed,
                )
        with self._lock:
            e = self._entries.get(name)
            if e is None:
                # dropped concurrently — nothing to file
                return
            self._resident_bytes -= e.device_bytes
            e.device_bytes = 0
            e.applied_seq = applied
            e.landed_seq = landed
            e.blob, e.blob_bytes = blob, len(blob)
            self._warm_bytes += e.blob_bytes
            e.state = WARM
            if e.busy_done is not None:
                e.busy_done.set()
                e.busy_done = None
            self._trim_warm_locked()
            self._update_gauges_locked()
        obs_counters.incr("storage_evictions_total")
        obs_flight.note(
            "eviction", tenant=name, applied_seq=int(applied),
            landed_seq=None if landed is None else int(landed),
        )

    def _trim_warm_locked(self) -> None:
        """Warm-pool budget: demote the coldest fully-checkpoint-covered
        WARM tenants to COLD (drop the blob — the sink rebuilds it).
        Tenants whose durable tier lags their blob are pinned WARM:
        correctness beats the budget, the op log still covers the gap
        but a COLD restore would have to replay it per tenant."""
        budget = self.config.warm_pool_bytes
        if budget <= 0 or self._warm_bytes <= budget:
            return
        now = time.monotonic()
        hl = self.config.heat_halflife_s
        warm = sorted(
            (
                e for e in self._entries.values()
                if e.state == WARM and e.blob is not None
                and e.landed_seq is not None
                and e.landed_seq >= e.applied_seq
            ),
            key=lambda e: e.evict_rank(now, hl),
        )
        for e in warm:
            if self._warm_bytes <= budget:
                return
            self._warm_bytes -= e.blob_bytes
            e.blob, e.blob_bytes = None, 0
            e.state = COLD
            obs_counters.incr("storage_warm_demotions")

    # -- coordination hooks --------------------------------------------------

    def drain_busy(self, timeout: float = 30.0) -> None:
        """Block until no hydration/eviction is in flight — the
        demotion barrier's storage leg (see ``ha.promotion.
        become_replica``): a write that passed the READONLY fence may
        still be WAITING on a hydration, and the take-every-lock
        barrier only covers locks that exist. Poll-based on purpose
        (the caller holds ``service.promote``)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                busy = self._hydrating or any(
                    e.state in (HYDRATING, EVICTING)
                    for e in self._entries.values()
                )
            if not busy:
                return
            time.sleep(0.002)
        log.warning("storage drain_busy: %.0fs deadline hit", timeout)

    def _update_gauges_locked(self) -> None:
        counts = {RESIDENT: 0, EVICTING: 0, WARM: 0, COLD: 0, HYDRATING: 0}
        for e in self._entries.values():
            counts[e.state] += 1
        obs_counters.set_gauge(
            "storage_resident_filters",
            float(counts[RESIDENT] + counts[EVICTING]),
        )
        obs_counters.set_gauge(
            "storage_resident_bytes", float(self._resident_bytes)
        )
        obs_counters.set_gauge(
            "storage_warm_filters",
            float(counts[WARM] + counts[HYDRATING]),
        )
        obs_counters.set_gauge("storage_warm_bytes", float(self._warm_bytes))
        obs_counters.set_gauge("storage_cold_filters", float(counts[COLD]))
