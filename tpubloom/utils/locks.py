"""Named lock wrappers + runtime lock-order / held-while-blocking analysis.

PRs 3-5 made tpubloom genuinely concurrent (op-log appends under filter
locks, commit barriers, ack streams, sentinel elections), and the bug
class that dominated their review was ordering: deadlocks (truncation
sweep re-taking the registry lock), blocking under a lock (barrier
inside the filter lock), and notify-before-log races. Those invariants
were tribal knowledge in CHANGES.md; this module makes them
machine-checked at runtime (the static half lives in
:mod:`tpubloom.analysis.lint`).

Usage — replace bare ``threading`` primitives with NAMED ones::

    self._lock = locks.named_lock("service.registry")
    self._cond = locks.named_condition("repl.oplog")

Names are CLASSES of lock, not instances: every filter's op lock is
``filter.op``. The analysis runs on the name graph, so an ordering
proven between two instances generalizes to all of them — and a
self-edge (``filter.op`` acquired while ``filter.op`` is already held
by the same thread on a *different* instance) is itself a finding: two
threads nesting two filter locks in opposite orders is a deadlock.

Gating: the tracker is armed by the ``TPUBLOOM_LOCK_CHECK`` env var (or
:func:`set_enabled` in tests) **at lock-construction time**. Disarmed —
the normal state — the factories return bare ``threading`` primitives:
the production hot path pays nothing, not even an attribute hop.
Blocking primitives additionally call :func:`note_blocking` at entry;
disarmed that costs one cached-bool check.

What the armed tracker records:

* **acquisition edges** — thread T acquires ``b`` while holding ``a``
  → edge ``a → b`` (with the first acquisition site). A new edge that
  closes a cycle in the name graph is a ``lock-order-cycle`` violation:
  two threads can interleave the two paths into a deadlock.
* **held-while-blocking** — a :meth:`TrackedCondition.wait`/``wait_for``
  while the thread holds any OTHER tracked lock, or a
  :func:`note_blocking` call (gRPC stubs, fsync/checkpoint IO,
  ``wait_acked``) while holding a tracked lock not on the caller's
  ``allow`` list. Allowed holds are recorded as suppressions (with the
  caller's reason) so the report stays auditable.

Reports: :func:`report`/:func:`violations` for in-process asserts (the
chaos suites arm the tracker and assert no violations at teardown); at
process exit a JSON report is written to
``$TPUBLOOM_LOCK_CHECK_DIR/lockcheck-<pid>.json`` (when set) so
subprocess servers in the chaos suites are auditable too, and any
violations are printed to stderr.
"""

from __future__ import annotations

import atexit
import json
import os
import sys
import threading
import traceback
from typing import Iterable, Optional

ENV_VAR = "TPUBLOOM_LOCK_CHECK"
REPORT_DIR_ENV = "TPUBLOOM_LOCK_CHECK_DIR"

_override: Optional[bool] = None
_env_enabled: Optional[bool] = None


def enabled() -> bool:
    """True iff the tracker is armed (env var, or a test override)."""
    global _env_enabled
    if _override is not None:
        return _override
    if _env_enabled is None:
        _env_enabled = os.environ.get(ENV_VAR, "").strip() not in ("", "0")
    return _env_enabled


def set_enabled(value: Optional[bool]) -> None:
    """Test hook: force the tracker on/off (None = back to the env).
    Only locks CONSTRUCTED while enabled are tracked — arm before
    building the service under test."""
    global _override
    _override = value


def _call_site(skip: int = 3) -> str:
    """``file:line`` of the application frame that triggered a tracker
    event (skipping the tracker's own frames)."""
    for frame in traceback.extract_stack()[-skip - 4 : -skip + 1][::-1]:
        if not frame.filename.endswith("locks.py"):
            return f"{frame.filename}:{frame.lineno}"
    return "?"


class _Tracker:
    """Process-global acquisition-graph recorder (thread-safe; its own
    mutex is a bare ``threading.Lock`` and is never held while an
    application lock is being acquired, so it cannot join a cycle)."""

    def __init__(self):
        self._mu = threading.Lock()
        self._tls = threading.local()
        #: (a, b) -> acquisition count for "b acquired while a held"
        self.edges: dict = {}
        #: (a, b) -> "file:line" of the first time the edge was seen
        self.edge_sites: dict = {}
        self.violations: list = []
        self.suppressed: list = []
        self._seen: set = set()

    # -- per-thread hold stack ------------------------------------------------

    def _stack(self) -> list:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def held_names(self) -> list:
        """Names of tracked locks the calling thread currently holds
        (outermost first, deduplicated)."""
        out = []
        for name, _oid, _reentrant in self._stack():
            if name not in out:
                out.append(name)
        return out

    def acquiring(self, name: str, oid: int, reentrant: bool) -> None:
        """Called BEFORE the underlying acquire blocks, so the edges (and
        any cycle they close) are recorded even when the acquisition
        deadlocks for real — the exit report then explains the hang."""
        stack = self._stack()
        if not stack:
            return
        site = _call_site()
        with self._mu:
            for held_name, held_oid, _ in stack:
                if held_name == name:
                    if held_oid == oid and reentrant:
                        continue  # RLock/Condition re-entry: fine
                    self._violation(
                        "lock-order-cycle",
                        f"{name!r} acquired while another {name!r} "
                        f"instance is already held — two threads "
                        f"nesting in opposite orders deadlock",
                        site,
                        cycle=[name, name],
                    )
                    continue
                self._add_edge(held_name, name, site)

    def acquired(self, name: str, oid: int, reentrant: bool) -> None:
        self._stack().append((name, oid, reentrant))

    def released(self, name: str, oid: int) -> None:
        stack = self._stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i][0] == name and stack[i][1] == oid:
                del stack[i]
                return

    # -- graph ---------------------------------------------------------------

    def _add_edge(self, a: str, b: str, site: str) -> None:
        key = (a, b)
        self.edges[key] = self.edges.get(key, 0) + 1
        if key not in self.edge_sites:
            self.edge_sites[key] = site
            cycle = self._find_cycle(b, a)
            if cycle is not None:
                self._violation(
                    "lock-order-cycle",
                    f"acquiring {b!r} while holding {a!r} closes the "
                    f"cycle {' -> '.join(cycle + [cycle[0]])}",
                    site,
                    cycle=cycle,
                )

    def _find_cycle(self, start: str, target: str) -> Optional[list]:
        """Path start -> ... -> target in the edge graph (caller holds
        ``_mu``); the new target->start edge closes it into a cycle."""
        path, seen = [], set()

        def dfs(node: str) -> bool:
            if node == target:
                path.append(node)
                return True
            if node in seen:
                return False
            seen.add(node)
            for (a, b) in self.edges:
                if a == node and dfs(b):
                    path.append(node)
                    return True
            return False

        if dfs(start):
            # path unwinds deepest-first: [target, ..., start] — render
            # the cycle as target -> start -> ... (the new edge closes it)
            return [target] + list(reversed(path))[:-1]
        return None

    # -- blocking checks ------------------------------------------------------

    def waiting(self, name: str, timeout) -> None:
        """A condition named ``name`` is about to wait: holding any OTHER
        tracked lock across the wait is a held-while-blocking violation
        (the wait releases only its own lock). The message carries no
        timeout VALUE: waits in retry loops pass a shrinking remaining
        budget, and a varying repr would defeat the (kind, message)
        dedup and flood the report."""
        others = [h for h in self.held_names() if h != name]
        if others:
            with self._mu:
                self._violation(
                    "held-while-blocking",
                    f"Condition {name!r}.wait() while holding {others}",
                    _call_site(),
                    holding=others,
                )

    def blocking(
        self, op: str, allow: Iterable[str], reason: str
    ) -> None:
        held = self.held_names()
        if not held:
            return
        allow = set(allow)
        bad = [h for h in held if h not in allow]
        with self._mu:
            if bad:
                self._violation(
                    "held-while-blocking",
                    f"blocking op {op!r} while holding {bad}",
                    _call_site(),
                    holding=bad,
                )
            else:
                self.suppressed.append(
                    {
                        "kind": "held-while-blocking",
                        "op": op,
                        "holding": held,
                        "reason": reason,
                        "site": _call_site(),
                    }
                )

    def _violation(self, kind: str, message: str, site: str, **extra) -> None:
        """Record one violation (caller holds ``_mu``), deduplicated by
        (kind, message) so a hot loop reports once, not a million times."""
        key = (kind, message)
        if key in self._seen:
            return
        self._seen.add(key)
        self.violations.append(
            {"kind": kind, "message": message, "site": site, **extra}
        )

    def report(self) -> dict:
        with self._mu:
            return {
                "edges": [
                    {
                        "from": a,
                        "to": b,
                        "count": n,
                        "first_site": self.edge_sites.get((a, b)),
                    }
                    for (a, b), n in sorted(self.edges.items())
                ],
                "violations": list(self.violations),
                "suppressed": list(self.suppressed),
            }

    def reset(self) -> None:
        with self._mu:
            self.edges.clear()
            self.edge_sites.clear()
            self.violations.clear()
            self.suppressed.clear()
            self._seen.clear()


_tracker = _Tracker()
_atexit_registered = False


def _ensure_atexit() -> None:
    global _atexit_registered
    if not _atexit_registered:
        _atexit_registered = True
        atexit.register(_exit_report)


def _exit_report() -> None:
    rep = _tracker.report()
    out_dir = os.environ.get(REPORT_DIR_ENV, "").strip()
    if out_dir:
        try:
            os.makedirs(out_dir, exist_ok=True)
            path = os.path.join(out_dir, f"lockcheck-{os.getpid()}.json")
            with open(path, "w") as f:
                json.dump(rep, f, indent=2)
        except OSError:
            pass
    if rep["violations"]:
        print(
            f"[tpubloom.locks] {len(rep['violations'])} lock-check "
            f"violation(s):",
            file=sys.stderr,
        )
        for v in rep["violations"]:
            print(f"  {v['kind']}: {v['message']} @ {v['site']}", file=sys.stderr)


# -- wrappers -----------------------------------------------------------------


class TrackedLock:
    """Named non-reentrant mutex feeding the acquisition graph."""

    __slots__ = ("name", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        _ensure_atexit()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        _tracker.acquiring(self.name, id(self), reentrant=False)
        got = self._lock.acquire(blocking, timeout)
        if got:
            _tracker.acquired(self.name, id(self), reentrant=False)
        return got

    def release(self) -> None:
        _tracker.released(self.name, id(self))
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> "TrackedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class TrackedRLock:
    """Named re-entrant mutex (same-instance re-entry is not an edge)."""

    __slots__ = ("name", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.RLock()
        _ensure_atexit()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        _tracker.acquiring(self.name, id(self), reentrant=True)
        got = self._lock.acquire(blocking, timeout)
        if got:
            _tracker.acquired(self.name, id(self), reentrant=True)
        return got

    def release(self) -> None:
        _tracker.released(self.name, id(self))
        self._lock.release()

    def __enter__(self) -> "TrackedRLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    # threading.Condition(lock=...) compatibility
    def _is_owned(self):
        return self._lock._is_owned()

    def _acquire_restore(self, state):
        self._lock._acquire_restore(state)
        _tracker.acquired(self.name, id(self), reentrant=True)

    def _release_save(self):
        _tracker.released(self.name, id(self))
        return self._lock._release_save()


class TrackedCondition(threading.Condition):
    """Named condition variable: entry/exit feed the graph, and a wait
    while holding any other tracked lock is a held-while-blocking
    violation (the wait releases only this condition's own lock)."""

    def __init__(self, name: str, lock=None):
        super().__init__(lock)
        self.name = name
        #: per-thread wait_for re-entry depth: the stock wait_for loops
        #: over self.wait(), which dispatches back to the override — the
        #: inner waits must not re-report what wait_for already checked
        self._in_wait_for = threading.local()
        _ensure_atexit()

    def __enter__(self):
        _tracker.acquiring(self.name, id(self), reentrant=True)
        result = super().__enter__()
        _tracker.acquired(self.name, id(self), reentrant=True)
        return result

    def __exit__(self, *exc):
        _tracker.released(self.name, id(self))
        return super().__exit__(*exc)

    def acquire(self, *args):
        _tracker.acquiring(self.name, id(self), reentrant=True)
        got = super().acquire(*args)
        if got:
            _tracker.acquired(self.name, id(self), reentrant=True)
        return got

    def release(self):
        _tracker.released(self.name, id(self))
        super().release()

    def wait(self, timeout: Optional[float] = None):
        if not getattr(self._in_wait_for, "depth", 0):
            _tracker.waiting(self.name, timeout)
        return super().wait(timeout)

    def wait_for(self, predicate, timeout: Optional[float] = None):
        _tracker.waiting(self.name, timeout)
        # the stock wait_for loops over self.wait() — flag the thread so
        # those inner dispatches skip the (already done) check
        tls = self._in_wait_for
        tls.depth = getattr(tls, "depth", 0) + 1
        try:
            return super().wait_for(predicate, timeout)
        finally:
            tls.depth -= 1


# -- factories (the public construction API) ----------------------------------


def named_lock(name: str):
    """A mutex named for the analysis; a bare ``threading.Lock`` when
    the tracker is disarmed (zero overhead)."""
    return TrackedLock(name) if enabled() else threading.Lock()


def named_rlock(name: str):
    return TrackedRLock(name) if enabled() else threading.RLock()


def named_condition(name: str, lock=None):
    """A condition variable named for the analysis; bare
    ``threading.Condition`` when disarmed."""
    if enabled():
        return TrackedCondition(name, lock)
    return threading.Condition(lock)


def note_blocking(
    op: str, allow: Iterable[str] = (), reason: str = ""
) -> None:
    """Blocking primitives (quorum waits, checkpoint flush/restore IO,
    RPC stubs) call this at entry: armed, it records a
    held-while-blocking violation when the calling thread holds any
    tracked lock not in ``allow``; holds that ARE allowed must come with
    a non-empty ``reason`` and land in the report's suppressions.
    Disarmed it costs one cached-bool check."""
    if not enabled():
        return
    if allow and not reason:
        raise ValueError(f"note_blocking({op!r}): an allow list needs a reason")
    _tracker.blocking(op, allow, reason)


# -- reporting API ------------------------------------------------------------


def report() -> dict:
    """Edges + violations + suppressions recorded so far."""
    return _tracker.report()


def violations() -> list:
    return list(_tracker.violations)


def reset() -> None:
    """Drop all recorded state (test isolation). Does not detach locks
    already constructed — they keep feeding the (now empty) graph."""
    _tracker.reset()
