"""Kernel tracing/profiling helpers — the SURVEY.md §5 "Tracing/profiling"
subsystem.

Parity: the reference gem has no tracing; operators use Redis
SLOWLOG/MONITOR. The TPU-native equivalent pinned by SURVEY.md §5 is
``jax.profiler`` traces (viewable in Perfetto / XProf / TensorBoard)
around the insert/query kernels, plus named annotations so individual
batches show up in the trace timeline.

Usage::

    from tpubloom.utils import tracing

    with tracing.trace("/tmp/tpubloom-trace"):     # whole-session trace
        with tracing.annotate("insert_batch", batch=len(keys)):
            f.insert_batch(keys)

    # or one-shot around a callable:
    result, trace_dir = tracing.profile_call(fn, *args)

The gRPC server wires ``request_span`` around every request so
per-request spans appear in device traces (tpubloom/server/service.py)
carrying the client-generated request id — the same id the slowlog entry
records (``tpubloom.obs.slowlog``), so "find slowlog entry rid=X, open
the trace, search rid=X" is the triage loop.
"""

from __future__ import annotations

import contextlib
import os
import tempfile
from typing import Any, Callable, Iterator

import jax


@contextlib.contextmanager
def trace(log_dir: str, *, create: bool = True) -> Iterator[str]:
    """Capture a jax.profiler device+host trace into ``log_dir``.

    The resulting ``plugins/profile/**/*.trace.json.gz`` /
    ``*.xplane.pb`` files open in Perfetto (ui.perfetto.dev) or
    TensorBoard's profile plugin.
    """
    if create:
        os.makedirs(log_dir, exist_ok=True)
    jax.profiler.start_trace(log_dir)
    try:
        yield log_dir
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def annotate(name: str, **attrs: Any) -> Iterator[None]:
    """Named span in the profiler timeline (TraceAnnotation).

    ``attrs`` are folded into the span name (TraceAnnotation carries no
    structured payload) — keep them short, e.g. ``batch=4096``.
    """
    if attrs:
        name = name + "[" + ",".join(f"{k}={v}" for k, v in attrs.items()) + "]"
    with jax.profiler.TraceAnnotation(name):
        yield


def request_span(
    name: str, *, batch: int | None = None, rid: str | None = None
) -> Iterator[None]:
    """Request-correlated :func:`annotate` span: folds the batch size and
    request id into the span name, silently dropping absent attrs (a
    library call without an active RPC has no rid)."""
    attrs: dict[str, Any] = {}
    if batch is not None:
        attrs["batch"] = batch
    if rid:
        attrs["rid"] = rid
    return annotate(name, **attrs)


def profile_call(
    fn: Callable[..., Any], *args: Any, log_dir: str | None = None, **kwargs: Any
) -> tuple[Any, str]:
    """Run ``fn(*args, **kwargs)`` under a one-shot trace.

    Returns ``(result, trace_dir)``. Blocks on the result so device work
    lands inside the captured window.
    """
    if log_dir is None:
        log_dir = tempfile.mkdtemp(prefix="tpubloom-trace-")
    with trace(log_dir):
        result = fn(*args, **kwargs)
        jax.block_until_ready(result)
    return result, log_dir
