"""Host-side key packing and Redis-bitmap byte-order conversion.

Key packing turns variable-length byte-string keys into the fixed-shape
``uint8[B, L]`` + ``int32[B]`` arrays the device hash kernels consume
(TPU/XLA want static shapes — SURVEY.md §7 "Hard parts").

Redis-bitmap conversion keeps the reference's storage format: the reference
persists the filter as a Redis string bitmap written via SETBIT, where bit
``n`` lives in byte ``n >> 3`` at bit ``7 - (n & 7)`` (MSB-first within the
byte). Our packed ``uint32`` layout puts bit ``n`` in word ``n >> 5`` at
``1 << (n & 31)`` (LSB-first). Little-endian word serialization makes the
*byte* index agree (``(n >> 5)*4 + ((n >> 3) & 3) == n >> 3``), so the
formats differ only by within-byte bit order — a 256-entry bit-reversal
table converts in one vectorized pass. This is what lets a ``:ruby``-driver
filter read a ``:jax``-built checkpoint and vice versa (SURVEY.md §5
"Checkpoint/resume").
"""

from __future__ import annotations

import hashlib
from typing import Sequence

import numpy as np

_BIT_REVERSE = np.array(
    [int(f"{i:08b}"[::-1], 2) for i in range(256)], dtype=np.uint8
)


def pack_keys(
    keys: Sequence[bytes | str],
    key_len: int,
    *,
    key_policy: str = "error",
) -> tuple[np.ndarray, np.ndarray]:
    """Pack keys into zero-padded ``uint8[B, key_len]`` + ``int32[B]`` lengths.

    str keys are UTF-8 encoded. Keys longer than ``key_len`` either raise
    (``key_policy='error'``) or are replaced by their 16-byte BLAKE2b digest
    (``key_policy='digest'`` — requires ``key_len >= 16``); the digest is
    deterministic, so filter semantics are preserved up to digest collisions.
    """
    if key_policy == "digest" and key_len < 16:
        raise ValueError("key_policy='digest' requires key_len >= 16")
    B = len(keys)
    # C++ fast path for the common case (all-bytes keys within key_len):
    # one join + one native scatter instead of a per-key Python loop —
    # this is the host ingest hot loop (SURVEY.md §7 native key packing)
    if B and all(type(k) is bytes for k in keys):
        from tpubloom import native

        if native.available():
            lens = np.fromiter(
                (len(k) for k in keys), dtype=np.int32, count=B
            )
            if int(lens.max()) <= key_len:
                return native.pack_joined(b"".join(keys), lens, key_len), lens
    out = np.zeros((B, key_len), dtype=np.uint8)
    lens = np.zeros((B,), dtype=np.int32)
    for i, key in enumerate(keys):
        if isinstance(key, str):
            key = key.encode("utf-8")
        elif not isinstance(key, (bytes, bytearray, memoryview)):
            raise TypeError(f"key {i} must be bytes or str, got {type(key)}")
        kb = bytes(key)
        if len(kb) > key_len:
            if key_policy == "error":
                raise ValueError(
                    f"key {i} is {len(kb)} bytes > key_len={key_len}; "
                    "use key_policy='digest' or raise key_len"
                )
            kb = hashlib.blake2b(kb, digest_size=16).digest()
        out[i, : len(kb)] = np.frombuffer(kb, dtype=np.uint8)
        lens[i] = len(kb)
    return out, lens


def pack_keys_dense(keys: np.ndarray, lengths: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Validate an already-packed (keys, lengths) pair and zero the padding.

    Accepts ``uint8[B, L]`` + integer lengths; returns arrays with every byte
    at position >= length forced to zero (the hash-kernel contract).
    """
    keys = np.ascontiguousarray(keys, dtype=np.uint8)
    lengths = np.asarray(lengths, dtype=np.int32)
    if keys.ndim != 2 or lengths.shape != (keys.shape[0],):
        raise ValueError(f"bad shapes: keys {keys.shape}, lengths {lengths.shape}")
    mask = np.arange(keys.shape[1], dtype=np.int32)[None, :] < lengths[:, None]
    return np.where(mask, keys, 0).astype(np.uint8), lengths


def words_to_redis_bitmap(words: np.ndarray, m: int) -> bytes:
    """Serialize a packed ``uint32`` bit array to Redis SETBIT byte order.

    The output is exactly the Redis string value the reference's ``:ruby``
    driver would have produced by SETBIT-ing the same positions, truncated
    to ``ceil(m / 8)`` bytes.
    """
    words = np.ascontiguousarray(words, dtype=np.uint32)
    le = words.view(np.uint8) if words.dtype.byteorder in ("<", "=") else None
    if le is None or not _is_little_endian():
        le = words.astype("<u4").view(np.uint8)
    rev = _BIT_REVERSE[le]
    nbytes = (m + 7) // 8
    return rev[:nbytes].tobytes()


def redis_bitmap_to_words(data: bytes, m: int) -> np.ndarray:
    """Parse a Redis string bitmap back into our packed ``uint32`` array."""
    n_words = (m + 31) // 32
    buf = np.zeros(n_words * 4, dtype=np.uint8)
    nbytes = min(len(data), (m + 7) // 8)
    buf[:nbytes] = np.frombuffer(data, dtype=np.uint8, count=nbytes)
    rev = _BIT_REVERSE[buf]
    return rev.view("<u4").astype(np.uint32, copy=False)


def _is_little_endian() -> bool:
    import sys

    return sys.byteorder == "little"
