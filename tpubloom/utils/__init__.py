"""Host-side utilities: key packing, Redis-bitmap byte-order conversion."""
