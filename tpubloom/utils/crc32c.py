"""CRC32C (Castagnoli) for checkpoint integrity framing.

Why Castagnoli and not ``zlib.crc32``: CRC32C is the storage-world
convention (iSCSI, ext4, gRPC) with better burst-error detection than
the IEEE polynomial, and checkpoint v2 declares ``crc32c`` in its
header — the checksum is part of the on-disk contract, so it must not
silently depend on which Python extension happens to be installed.

The environment bakes in no ``crc32c``/``google-crc32c`` wheel, so the
portable path is table-driven **slicing-by-8**: CRC is GF(2)-linear, so
each 8-byte block's contribution splits into a data term (all eight
table lookups, vectorized across every block at once with NumPy) and a
4-lookup carry of the running state (the only serial part — a short
scalar loop over blocks, not bytes). That keeps a multi-MB payload
checksum in the tens of milliseconds, and it runs on the async
checkpoint writer thread, off the insert path. When a C accelerator
*is* importable it is used instead — same polynomial, same answer,
pinned by published test vectors in ``tests/test_faults.py``.
"""

from __future__ import annotations

import numpy as np

_POLY = 0x82F63B78  # reflected CRC32C polynomial


def _make_tables(n: int = 8) -> np.ndarray:
    """Slicing tables: ``T[0]`` is the classic byte table; ``T[j]``
    advances a byte through ``j`` further zero bytes."""
    tables = np.zeros((n, 256), dtype=np.uint32)
    for b in range(256):
        crc = b
        for _ in range(8):
            crc = (crc >> 1) ^ (_POLY if crc & 1 else 0)
        tables[0, b] = crc
    for j in range(1, n):
        for b in range(256):
            prev = int(tables[j - 1, b])
            tables[j, b] = (prev >> 8) ^ int(tables[0, prev & 0xFF])
    return tables


_T = _make_tables()


def _crc32c_numpy(data: bytes, crc: int = 0) -> int:
    state = (crc ^ 0xFFFFFFFF) & 0xFFFFFFFF
    buf = np.frombuffer(data, dtype=np.uint8)
    n8 = len(buf) // 8
    if n8:
        blocks = buf[: n8 * 8].reshape(n8, 8)
        # data term of every block at once: byte j goes through T[7-j]
        nc = _T[7][blocks[:, 0]]
        for j in range(1, 8):
            nc = nc ^ _T[7 - j][blocks[:, j]]
        # carry chain: state_{i+1} = nc[i] ^ advance8(state_i); the
        # incoming state overlaps only the first 4 byte lanes, so its
        # advance uses T[7]..T[4]
        t7, t6, t5, t4 = (
            _T[7].tolist(), _T[6].tolist(), _T[5].tolist(), _T[4].tolist()
        )
        for term in nc.tolist():
            state = (
                term
                ^ t7[state & 0xFF]
                ^ t6[(state >> 8) & 0xFF]
                ^ t5[(state >> 16) & 0xFF]
                ^ t4[state >> 24]
            )
    t0 = _T[0]
    for b in buf[n8 * 8 :]:
        state = (state >> 8) ^ int(t0[(state ^ int(b)) & 0xFF])
    return (state ^ 0xFFFFFFFF) & 0xFFFFFFFF


try:  # a real C extension, when present, is authoritative
    from crc32c import crc32c as _crc32c_accel  # type: ignore
except ImportError:
    _crc32c_accel = None


def crc32c(data: bytes, crc: int = 0) -> int:
    """CRC32C of ``data`` (optionally continuing from ``crc``)."""
    if _crc32c_accel is not None:
        return _crc32c_accel(bytes(data), crc)
    return _crc32c_numpy(data, crc)
