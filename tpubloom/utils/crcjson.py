"""Tiny CRC32C-checked JSON files (ISSUE 4).

The HA subsystem persists several one-record facts (the topology epoch,
a replica's replication cursor) whose corruption must read as "absent"
— never as a crash, and never as a bogus value: a torn epoch fences the
node harder (safe), a torn cursor costs a full resync (safe). This is
the one shared implementation of that contract: the payload is
canonical JSON (sorted keys), the stored file adds a ``crc`` field over
those canonical bytes, writes go through tmp + ``os.replace``, and any
read problem (missing file, torn JSON, CRC mismatch, wrong shape)
returns None.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Optional

from tpubloom.utils.crc32c import crc32c

log = logging.getLogger("tpubloom.utils")


def _canonical(payload: dict) -> bytes:
    return json.dumps(payload, sort_keys=True).encode()


def store(path: str, payload: dict) -> None:
    """Atomically write ``payload`` (a flat JSON-able dict) + its CRC."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({**payload, "crc": crc32c(_canonical(payload))}, f)
    os.replace(tmp, path)


def load(path: str, fields: tuple) -> Optional[dict]:
    """Read back the dict ``store`` wrote, keeping only ``fields`` (the
    caller's schema — also what the CRC is recomputed over). None on
    any problem, with corruption logged."""
    try:
        with open(path) as f:
            data = json.load(f)
        payload = {k: data[k] for k in fields}
        if int(data["crc"]) != crc32c(_canonical(payload)):
            log.warning("%s failed its CRC check; treating as absent", path)
            return None
        return payload
    except (OSError, ValueError, KeyError, TypeError):
        return None
