"""Streaming ingest plane (ISSUE 18): persistent bidi RPCs feeding the
coalescer.

The device sweeps tens of millions of keys per second, but every
data-plane call used to be one unary RPC — per-call HTTP/2 stream
setup, header parse, thread-pool hop. ``InsertStream``/``QueryStream``
amortize the transport the way the ingest coalescer (ISSUE 10)
amortizes device launches: one long-lived stream carries seq-stamped
``keys_fixed`` frames straight into the coalescer's parked queues, and
pipelined ack frames return per-frame verdicts (presence slices, hits,
``repl_seq``, quorum results from the one-barrier-per-flush path).
Wire shapes are specified on :data:`tpubloom.server.protocol.
BIDI_STREAM_METHODS`.

Threading model (per stream): the gRPC handler thread is the ACK
PUMP — it drains a per-stream outbound queue of encoded ack frames
(yielding each to gRPC) until a sentinel arrives. A spawned RECEIVER
thread consumes the request iterator: each data frame passes the exact
unary-wrapper semantic gates (READONLY, LOG_WRITE_FAILED, STALE_EPOCH,
cluster MOVED/ASK — in that order), then parks into the coalescer via
:meth:`IngestCoalescer.submit_nowait`; the flush's completion callback
(dispatcher/completer thread, outside every lock) builds the ack and
enqueues it. Frames the coalescer cannot take (migration forwards,
coalescer stopped, no keys) run the direct path inline on the receiver
thread — handler + commit barrier + dual-write forward, exactly the
unary order. Acks are therefore NOT necessarily in frame order; each
echoes its frame's ``seq``.

Flow control: admission's in-flight cap never sees stream frames —
credit is the stream-shaped replacement. Every ack carries a fresh
``credit`` grant derived from the coalescer's parked-key headroom
(:meth:`IngestCoalescer.parked_budget_left`, the signal behind the
``ingest_parked_current`` gauge), floored at 1 so the window can
always drain (a zero grant with no outstanding frame would have no ack
to ride back on). An over-budget server PARKS the stream — the
receiver thread blocks in the coalescer's bounded-park backpressure,
gRPC/TCP flow control pushes back on the sender — instead of shedding
admitted work.

Exactly-once replay: a client whose stream died mid-flight reconnects
and re-sends only its unacked frames under their ORIGINAL rids. The
rid→response dedup cache (ISSUE 2/3) answers any frame whose first
flight already applied; the coalesced merged records' ``parts``
(ISSUE 18, :meth:`IngestCoalescer._log_parts`) re-seed that cache on
crash replay, so the guarantee holds across a SIGKILL — chaos-proven
on a counting filter in ``tests/test_streams.py``.

Fault points: ``stream.recv`` fires in the receiver per data frame
(before any effect — a killed stream replays safely); ``stream.ack``
fires in the ack pump per ack frame (after the effect — the case the
rid-dedup replay contract must absorb).
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Optional

from tpubloom import faults
from tpubloom.cluster import migrate as cluster_migrate
from tpubloom.cluster import node as cluster_node
from tpubloom.obs import context as obs
from tpubloom.obs import counters as obs_counters
from tpubloom.obs import flight as obs_flight
from tpubloom.obs import trace as obs_trace
from tpubloom.server import protocol
from tpubloom.utils import locks

log = logging.getLogger("tpubloom.server")

#: stream method -> the unary method whose semantics each frame carries
FRAME_METHODS = {
    "InsertStream": "InsertBatch",
    "QueryStream": "QueryBatch",
}

#: largest credit window any ack grants: bounds per-stream server-side
#: state (unacked frames a replay may re-send) and keeps one stream
#: from monopolizing the parked-key budget
MAX_WINDOW = 32

#: process-wide connected-stream count behind the
#: ``stream_connected_current`` gauge (updated OUTSIDE the lock — the
#: registry lock stays a leaf with no declared edges)
_registry_lock = locks.named_lock("stream.registry")
_connected = 0


def _track_connected(delta: int) -> None:
    global _connected
    with _registry_lock:
        _connected += delta
        n = _connected
    obs_counters.set_gauge("stream_connected_current", n)


def credit_grant(service) -> int:
    """Fresh per-ack credit: the coalescer's parked-key headroom in
    flush-quantum units, capped at :data:`MAX_WINDOW`, floored at 1
    (the stream must always be able to drain — backpressure is the
    bounded park, not a dead window)."""
    co = service._coalescer
    if co is None or not co.running:
        return MAX_WINDOW
    quantum = max(1, co.config.max_keys // 8)
    grant = co.parked_budget_left() // quantum
    if grant < MAX_WINDOW:
        obs_counters.incr("stream_credit_throttles")
    return max(1, min(MAX_WINDOW, grant))


class _Stream:
    """State of one live bidi stream: the outbound ack queue the
    handler thread pumps, and the count of frames parked in the
    coalescer whose completion callbacks have not fired yet."""

    def __init__(self, service, method: str):
        self.service = service
        self.method = method  # the unary frame method
        self.outq: "queue.Queue" = queue.Queue()
        self.cond = locks.named_condition("stream.state")
        self.pending = 0
        #: last credit grant sent on any outbound frame — the baseline
        #: the idle pump compares against before pushing a server-
        #: initiated shrink frame (benign cross-thread race: a stale
        #: read only costs one redundant frame or skips one)
        self.last_credit = MAX_WINDOW

    def enqueue_ack(self, seq, resp: dict) -> None:
        """Build + encode one ack OUTSIDE every lock (credit reads the
        coalescer's queue condition) and hand it to the ack pump."""
        grant = credit_grant(self.service)
        self.last_credit = grant
        frame = {
            "kind": "ack",
            "seq": seq,
            "credit": grant,
            "resp": resp,
        }
        self.outq.put(protocol.encode(frame))

    def frame_done(self, seq, resp: dict) -> None:
        self.enqueue_ack(seq, resp)
        with self.cond:
            self.pending -= 1
            self.cond.notify_all()

    def drain_pending(self, timeout: float = 120.0) -> None:
        """Receiver-side: input exhausted — wait for every parked
        frame's callback before the pump's sentinel goes out."""
        deadline = time.monotonic() + timeout
        with self.cond:
            while self.pending > 0 and time.monotonic() < deadline:
                self.cond.wait(timeout=0.1)
            if self.pending > 0:
                log.error(
                    "stream drain: %d frame(s) still parked after %.0fs",
                    self.pending, timeout,
                )


def _error_resp(e: protocol.BloomServiceError) -> dict:
    return protocol.error_response(e.code, e.message, e.details)


def _check_frame(service, method: str, req: dict) -> Optional[dict]:
    """The unary wrapper's pre-handler gates, per frame and in the
    same order (READONLY → LOG_WRITE_FAILED → STALE_EPOCH → cluster
    slot check). Admission shed is deliberately ABSENT: frames are
    credit-governed, and an admitted stream parks instead of shedding.
    Returns an error response to ack, or None to proceed."""
    if service.read_only and method in protocol.MUTATING_METHODS:
        service.metrics.count("readonly_rejected")
        return protocol.error_response(
            "READONLY",
            f"{method} rejected: this server is a read-only replica — "
            f"send writes to the primary",
            details=(
                {"primary": service.primary_address}
                if service.primary_address
                else None
            ),
        )
    if (
        service.oplog_error is not None
        and method in protocol.MUTATING_METHODS
    ):
        service.metrics.count("log_failstop_rejected")
        return protocol.error_response(
            "LOG_WRITE_FAILED",
            f"{method} rejected: op log append failed "
            f"({service.oplog_error}); writes are stopped until the log "
            f"is writable and the server restarts",
        )
    req_epoch = req.get("epoch")
    if (
        req_epoch is not None
        and method in protocol.MUTATING_METHODS
        and int(req_epoch) < service.epoch
    ):
        service.metrics.count("stale_epoch_rejected")
        return protocol.error_response(
            "STALE_EPOCH",
            f"request epoch {req_epoch} predates the current topology "
            f"epoch {service.epoch} — refresh your topology",
            details={"epoch": service.epoch},
        )
    name = req.get("name")
    if (
        service.cluster is not None
        and isinstance(name, str)
        and method in cluster_node.KEYED_METHODS
    ):
        try:
            service.cluster.check(
                name,
                asking=bool(req.get("asking")),
                exists=service.has_filter(name),
                primary_address=(
                    service.primary_address if service.read_only else None
                ),
            )
        except protocol.BloomServiceError as e:
            return _error_resp(e)
    return None


def _direct_frame(service, method: str, req: dict) -> dict:
    """The unary post-handler path for frames the coalescer cannot
    park (stopped, migration forward, keyless): handler + commit
    barrier + dual-write forward, on the receiver thread."""
    handler = getattr(service, method)
    try:
        resp = handler(req)
        coalesced_done = isinstance(resp, dict) and bool(
            resp.pop("_coalesced", False)
        )
        if (
            not coalesced_done
            and method in protocol.MUTATING_METHODS
            and resp.get("ok")
        ):
            with obs_trace.span("barrier.wait"):
                resp = service.commit_barrier(req, resp)
            if service.cluster is not None:
                resp = cluster_migrate.forward_op(service, method, req, resp)
        return resp
    except protocol.BloomServiceError as e:
        return _error_resp(e)
    except Exception as e:  # noqa: BLE001 — surface, don't kill the stream
        log.exception("stream frame %s failed", method)
        return protocol.error_response(
            "INTERNAL", f"{type(e).__name__}: {e}"
        )


def _handle_frame(service, stream: _Stream, req: dict) -> None:
    """Process one decoded data frame on the receiver thread: gates,
    dedup, then park-or-direct. Always produces exactly one ack
    (immediately, or from the park's completion callback)."""
    method = stream.method
    seq = req.get("seq")
    rid = req.get("rid")
    if not isinstance(rid, str) or not rid:
        rid = obs.new_rid()
        req["rid"] = rid
    service.metrics.count("stream_frames_total")
    err = _check_frame(service, method, req)
    if err is not None:
        stream.enqueue_ack(seq, err)
        return
    # the frame's own request context (ISSUE 15): arms capture when
    # the client forced it (or the server-side sample hits), so the
    # flush span LINKS this frame's root and `_log_op` on the direct
    # path stamps the record with the frame rid
    with obs.request(method, rid=rid) as rctx:
        tmeta = req.get("trace")
        if not isinstance(tmeta, dict):
            tmeta = None
        obs_trace.arm_request(
            rctx,
            forced=bool(tmeta and tmeta.get("forced")),
            parent=tmeta.get("span") if tmeta else None,
        )
        w0, t0 = time.time(), time.perf_counter()
        parked = False
        try:
            replay_unsafe = False
            if method == "InsertBatch":
                mf = service._get(req["name"])
                replay_unsafe = service._insert_replay_unsafe(
                    mf, bool(req.get("return_presence"))
                )
            if replay_unsafe:
                cached = service._dedup_get(rid)
                if cached is not None:
                    # replayed frame whose first flight applied: answer
                    # from cache, re-waiting the barrier on the SAME
                    # record (direct-path dedup parity)
                    service.metrics.count("stream_frame_dedup_hits")
                    obs_flight.note(
                        "stream", phase="replay", method=method,
                        rid=rid, seq=int(seq) if seq is not None else -1,
                    )
                    try:
                        resp = service.commit_barrier(req, dict(cached))
                        if service.cluster is not None and resp.get("ok"):
                            resp = cluster_migrate.forward_op(
                                service, method, req, resp
                            )
                        stream.enqueue_ack(seq, resp)
                    except protocol.BloomServiceError as e:
                        stream.enqueue_ack(seq, _error_resp(e))
                    return
            if service._coalesce_eligible(req, method):
                with stream.cond:
                    stream.pending += 1
                co = service._coalescer
                parked = co.submit_nowait(
                    method, req, replay_unsafe=replay_unsafe,
                    callback=lambda entry, s=seq: _entry_ack(
                        stream, s, entry
                    ),
                )
                if not parked:
                    with stream.cond:
                        stream.pending -= 1
            if not parked:
                stream.enqueue_ack(seq, _direct_frame(service, method, req))
        except protocol.BloomServiceError as e:
            stream.enqueue_ack(seq, _error_resp(e))
        finally:
            if rctx.trace_armed:
                obs_trace.record_span(
                    "ingest.stream_recv",
                    rid=rid,
                    span=rctx.trace_span,
                    parent=rctx.trace_parent,
                    start=w0,
                    duration_s=time.perf_counter() - t0,
                    attrs={
                        "method": method,
                        "seq": int(seq) if seq is not None else -1,
                        "parked": parked,
                    },
                )


def _entry_ack(stream: _Stream, seq, entry) -> None:
    """Completion callback of a parked frame (dispatcher/completer
    thread, outside every coalescer/filter lock): demuxed verdict →
    ack frame."""
    if entry.error is not None:
        e = entry.error
        if isinstance(e, protocol.BloomServiceError):
            resp = _error_resp(e)
        else:
            resp = protocol.error_response(
                "INTERNAL", f"{type(e).__name__}: {e}"
            )
    else:
        resp = dict(entry.resp)
        resp.pop("_coalesced", None)
    stream.frame_done(seq, resp)


def _receiver(service, stream: _Stream, request_iterator,
              failure: list) -> None:
    """Consume the stream's data frames until the client half-closes
    (drain + sentinel) or the transport/fault path breaks (record the
    error, sentinel — the pump re-raises it to fail the RPC so the
    client reconnects and replays)."""
    try:
        for raw in request_iterator:
            faults.fire("stream.recv")
            try:
                req = protocol.decode(raw)
            except Exception:  # noqa: BLE001 — one bad frame, one error ack
                stream.enqueue_ack(None, protocol.error_response(
                    "INVALID_ARGUMENT", "undecodable stream frame"
                ))
                continue
            _handle_frame(service, stream, req)
        stream.drain_pending()
    except BaseException as e:  # noqa: BLE001 — the pump must wake
        log.debug("stream receiver ended: %r", e)
        failure.append(e)
    finally:
        stream.outq.put(None)


#: how long the ack pump idles on an empty outbound queue before
#: re-reading the coalescer's headroom — bounds how stale a client's
#: credit window can get while it sends nothing
IDLE_CREDIT_POLL_S = 0.25


def _run_stream(service, method_name: str, request_iterator, context):
    """One bidi stream's lifetime: hello (initial credit), receiver
    thread, ack pump, teardown accounting.

    The ack pump doubles as the idle credit refresher (ISSUE 19
    satellite): acks piggyback fresh grants, but an IDLE stream has no
    ack to ride — its client would happily burst a stale fat window
    into a coalescer other streams have since filled. So when the
    outbound queue stays empty for :data:`IDLE_CREDIT_POLL_S`, the pump
    re-reads :func:`credit_grant` and pushes a server-initiated
    ``{"kind": "credit"}`` frame IF the grant shrank (grow-only changes
    wait for the next ack — only shrinks are urgent)."""
    stream = _Stream(service, FRAME_METHODS[method_name])
    _track_connected(+1)
    obs_flight.note("stream", phase="connect", method=method_name)
    failure: list = []
    receiver = threading.Thread(
        target=_receiver,
        args=(service, stream, request_iterator, failure),
        name=f"tpubloom-{method_name}",
        daemon=True,
    )
    try:
        stream.last_credit = credit_grant(service)
        yield protocol.encode(
            {"kind": "hello", "credit": stream.last_credit}
        )
        receiver.start()
        while True:
            try:
                item = stream.outq.get(timeout=IDLE_CREDIT_POLL_S)
            except queue.Empty:
                fresh = credit_grant(service)
                if fresh < stream.last_credit:
                    stream.last_credit = fresh
                    obs_counters.incr("stream_credit_shrinks")
                    yield protocol.encode(
                        {"kind": "credit", "credit": fresh}
                    )
                continue
            if item is None:
                break
            faults.fire("stream.ack")
            service.metrics.count("stream_acks_total")
            yield item
        if failure:
            obs_flight.note(
                "stream", phase="kill", method=method_name,
                error=repr(failure[0]),
            )
            raise failure[0]
    finally:
        _track_connected(-1)


def insert_stream(service, request_iterator, context):
    """``InsertStream`` behavior: InsertBatch-semantics frames (presence
    fusion, durability quorums, counting/scalable dedup) over one
    persistent stream."""
    yield from _run_stream(service, "InsertStream", request_iterator, context)


def query_stream(service, request_iterator, context):
    """``QueryStream`` behavior: QueryBatch-semantics frames — reads
    ride the same coalesced flush path, acks carry packed hit bitmaps."""
    yield from _run_stream(service, "QueryStream", request_iterator, context)
