"""``python -m tpubloom.server [port] [checkpoint_dir] [--metrics-port N]``

``--metrics-port`` starts the background Prometheus exposition thread
(``GET /metrics``; ``tpubloom.obs``) next to the gRPC listener.
"""

from tpubloom.server.service import main

if __name__ == "__main__":
    main()
