"""``python -m tpubloom.server [port] [checkpoint_dir]``"""

from tpubloom.server.service import main

if __name__ == "__main__":
    main()
