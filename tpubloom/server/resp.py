"""Minimal RESP2 (Redis Serialization Protocol) client — zero dependencies.

Parity: the reference's transport layer is the redis-rb gem speaking RESP
over TCP/unix socket (SURVEY.md §1 L4). Here Redis is demoted to an async
checkpoint sink (BASELINE: "Redis persistence degrades to an async
checkpoint of the device bit-array"), and this hand-rolled client covers
exactly the commands the checkpoint path needs (PING/SET/GET/DEL/EXISTS) —
the environment has no redis-py, and a full client would be scope creep.

The wire format written by SET is the reference's own storage format: the
Redis string bitmap under ``key_name`` (see ``utils.packing``), so a stock
redis-server populated by this sink is readable by the reference's ``:ruby``
driver and vice versa.
"""

from __future__ import annotations

import socket
from typing import Optional


class RespError(RuntimeError):
    """Server-side -ERR reply."""


class RespClient:
    """Blocking RESP2 client over TCP (or unix socket path)."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 6379,
        *,
        unix_path: Optional[str] = None,
        timeout: float = 10.0,
    ):
        if unix_path:
            self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            self._sock.settimeout(timeout)
            self._sock.connect(unix_path)
        else:
            self._sock = socket.create_connection((host, port), timeout=timeout)
        self._buf = b""

    # -- wire format --------------------------------------------------------

    def _encode(self, *args: bytes | str | int) -> bytes:
        out = [b"*%d\r\n" % len(args)]
        for a in args:
            if isinstance(a, str):
                a = a.encode()
            elif isinstance(a, int):
                a = str(a).encode()
            out.append(b"$%d\r\n%s\r\n" % (len(a), a))
        return b"".join(out)

    def _read_line(self) -> bytes:
        while b"\r\n" not in self._buf:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise ConnectionError("redis connection closed")
            self._buf += chunk
        line, self._buf = self._buf.split(b"\r\n", 1)
        return line

    def _read_exact(self, n: int) -> bytes:
        while len(self._buf) < n:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise ConnectionError("redis connection closed")
            self._buf += chunk
        data, self._buf = self._buf[:n], self._buf[n:]
        return data

    def _read_reply(self):
        line = self._read_line()
        kind, rest = line[:1], line[1:]
        if kind == b"+":
            return rest
        if kind == b"-":
            raise RespError(rest.decode(errors="replace"))
        if kind == b":":
            return int(rest)
        if kind == b"$":
            n = int(rest)
            if n == -1:
                return None
            data = self._read_exact(n)
            self._read_exact(2)  # trailing \r\n
            return data
        if kind == b"*":
            n = int(rest)
            if n == -1:
                return None
            return [self._read_reply() for _ in range(n)]
        raise RespError(f"unexpected RESP type byte {kind!r}")

    def command(self, *args):
        self._sock.sendall(self._encode(*args))
        return self._read_reply()

    # -- the commands the checkpoint sink needs -----------------------------

    def ping(self) -> bool:
        return self.command("PING") == b"PONG"

    def set(self, key: str, value: bytes) -> bool:
        return self.command("SET", key, value) == b"OK"

    def get(self, key: str) -> Optional[bytes]:
        return self.command("GET", key)

    def delete(self, key: str) -> int:
        return self.command("DEL", key)

    def exists(self, key: str) -> int:
        return self.command("EXISTS", key)

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
