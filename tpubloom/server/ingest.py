"""Cross-connection micro-batching ingestion scheduler (ISSUE 10).

The device sweeps tens of millions of keys per second, but the host
front-end feeds it one gRPC request at a time: per-request decode, lock,
jit dispatch and — under synchronous replication — one commit barrier
per write. This module closes that gap with the Redis-pipelining move
applied server-side: concurrent ``InsertBatch``/``QueryBatch``/
``DeleteBatch``/``Clear`` RPCs (deletes and clears since ISSUE 12)
**park** in a bounded per-(filter, op) coalescing queue, a single
dispatcher thread flushes each queue on size/bytes/deadline
(``--coalesce-max-keys`` / ``--coalesce-max-wait-us``), runs the fused
kernel ONCE over the merged keys, and demultiplexes per-request results
(presence slices, ``repl_seq``) back to the parked handler threads.

What one flush amortizes:

* **one device launch** over the merged batch instead of N jit
  dispatches (and the merged batch hits the kernels' throughput regime
  instead of their fixed-overhead regime);
* **one op-log append** — the flush commits as a single merged record,
  so crash replay and replica streaming see one apply;
* **one commit barrier** — ``wait_acked`` runs once on the flush's seq
  at the STRONGEST quorum any parked request demanded; per-request
  verdicts are then read off the achieved count (a request that asked
  for less durability than the flush achieved succeeds even when a
  stricter sibling times out). N quorum writes, one WAIT — exactly the
  PR-5 pipelining follow-up.

Semantics preserved (regression-tested in ``tests/test_ingest.py``):

* READONLY / STALE_EPOCH / MOVED / ASK / shed all run in the RPC
  wrapper BEFORE the handler parks anything — coalescing never bypasses
  an admission or routing decision;
* per-request **rid-dedup**: replay-unsafe inserts check the dedup
  cache before parking and every parked request's demuxed response is
  cached under its own rid (seq-stamped), so client retries replay from
  cache exactly as on the direct path;
* **migration windows fall back to the direct path**: a flush checks
  the dual-write forward target under the filter's op lock (the same
  lock ``MigrateSlot`` arms forwards under) and, when armed, re-drives
  each parked request through the ordinary per-request handler + its
  own barrier + forward — a merged record would make N requests share
  one ``src_seq`` and the target's exactly-once gate would drop all but
  the first forward. Requests already carrying ``asking``/``src_seq``
  (forwards themselves) never park at all.

Double buffering (ISSUE 10, with :class:`tpubloom.ops.sweep.InFlight`):
an insert flush is launched UNFENCED under the op lock; while its
kernel runs, the dispatcher stages the next flush's host_prep/H2D, then
fences the previous flush and completes its waiters — the host feed and
the device overlap instead of ping-ponging.

Fault points: ``ingest.coalesce`` fires in ``submit`` before a request
parks (nothing applied — safe to retry); ``ingest.flush`` fires in the
dispatcher before a flush applies (ditto).

Lock ranks (declared in :mod:`tpubloom.analysis.lock_order`): the queue
condition is ``ingest.queue`` and is a LEAF apart from gauge updates —
the dispatcher drops it before touching any filter/registry/log lock.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Optional

import numpy as np

from tpubloom import faults
from tpubloom.obs import context as obs
from tpubloom.obs import counters as obs_counters
from tpubloom.obs import trace as obs_trace
from tpubloom.ops.sweep import InFlight
from tpubloom.sketch import registry as sketch_registry
from tpubloom.utils import locks

log = logging.getLogger("tpubloom.server")


class _EvictedRace(Exception):
    """The flush's resolved ``_Managed`` was paged out between lookup
    and lock (ISSUE 14) — the dispatcher re-resolves (hydrating if
    needed) and retries the flush against the live filter."""


def _check_live(mf) -> None:
    """First statement under every flush's op lock: a flag set means
    the storage tier evicted this object — mutating it would write to
    detached device arrays the eviction blob missed."""
    if getattr(mf, "evicted", False):
        raise _EvictedRace


class CoalesceConfig:
    """Flush policy knobs. A group flushes when its parked keys reach
    ``max_keys``, its parked payload reaches ``max_bytes``, or its
    oldest request has waited ``max_wait_us`` — whichever first.
    ``max_parked_keys`` bounds the queue: submitters block (bounded,
    natural backpressure — the caller thread was going to wait for its
    response anyway) until the dispatcher drains."""

    def __init__(
        self,
        max_keys: int = 8192,
        max_wait_us: int = 500,
        max_bytes: int = 8 * 1024 * 1024,
        max_parked_keys: Optional[int] = None,
    ):
        self.max_keys = int(max_keys)
        self.max_wait_us = int(max_wait_us)
        self.max_bytes = int(max_bytes)
        self.max_parked_keys = int(
            max_parked_keys if max_parked_keys is not None else 8 * max_keys
        )


class _Entry:
    __slots__ = (
        "req", "rid", "nkeys", "nbytes", "rows", "keys",
        "want_presence", "replay_unsafe", "min_replicas",
        "timeout_ms", "enq_t", "event", "resp", "error", "trace",
        "callback",
    )

    def __init__(self, req: dict, *, rows, keys, replay_unsafe: bool):
        self.req = req
        self.rid = req.get("rid")
        #: (rid, root span id) when the parking request is traced —
        #: what the flush span LINKS so N-to-1 batching stays
        #: explainable (ISSUE 15); None on the untraced hot path
        self.trace = obs_trace.request_ref()
        self.rows = rows          # np.uint8[n, width] (fixed encoding) or None
        self.keys = keys          # list of key bytes/str, or None
        self.nkeys = int(rows.shape[0]) if rows is not None else len(keys)
        self.nbytes = (
            int(rows.nbytes) if rows is not None
            else sum(len(k) for k in keys)
        )
        self.want_presence = bool(req.get("return_presence"))
        self.replay_unsafe = replay_unsafe
        self.min_replicas = int(req.get("min_replicas") or 0)
        self.timeout_ms = req.get("min_replicas_timeout_ms")
        self.enq_t = time.monotonic()
        self.event = threading.Event()
        self.resp: Optional[dict] = None
        self.error: Optional[BaseException] = None
        #: streaming ingest (ISSUE 18): set by :meth:`submit_nowait` —
        #: fires on the completing thread (dispatcher/completer, always
        #: OUTSIDE coalescer and filter locks) instead of a parked
        #: handler thread waking on the event
        self.callback = None

    def complete(self, resp: Optional[dict] = None,
                 error: Optional[BaseException] = None) -> None:
        self.resp, self.error = resp, error
        self.event.set()
        cb = self.callback
        if cb is not None:
            try:
                cb(self)
            except Exception:  # noqa: BLE001 — a bad ack sink must not
                # fail the flush's OTHER waiters (the stream may have
                # disconnected between park and completion)
                log.exception("ingest completion callback failed")


class IngestCoalescer:
    """Per-filter request coalescing + the single dispatcher thread."""

    def __init__(self, service, config: Optional[CoalesceConfig] = None):
        self._service = service
        self.config = config or CoalesceConfig()
        #: (filter name, "insert"|"query") -> [entries]
        self._groups: dict = {}
        self._parked_keys = 0
        self._cond = locks.named_condition("ingest.queue")
        self._stop = False
        self._flushing = 0
        self._urgent = 0
        self._thread: Optional[threading.Thread] = None
        self._in_dispatch = threading.local()
        self._inflight = InFlight()
        #: barrier-bearing finalizes run HERE, not on the dispatcher: a
        #: quorum wait can block up to its budget, and head-of-line
        #: blocking every other filter's flushes (including pure reads)
        #: behind one filter's replication round trip would undo the
        #: scheduler's point. Barrier-less finalizes (the common async
        #: case) stay inline — they are just demux.
        import queue

        self._completions: "queue.Queue" = queue.Queue(maxsize=4)
        self._completing = 0
        self._completer: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "IngestCoalescer":
        self._thread = threading.Thread(
            target=self._run, name="tpubloom-ingest", daemon=True
        )
        self._thread.start()
        self._completer = threading.Thread(
            target=self._completion_loop,
            name="tpubloom-ingest-complete",
            daemon=True,
        )
        self._completer.start()
        return self

    @property
    def running(self) -> bool:
        return self._thread is not None and not self._stop

    def in_dispatcher(self) -> bool:
        """True on the dispatcher thread — the migration-window fallback
        re-enters the ordinary handlers and must not park again."""
        return bool(getattr(self._in_dispatch, "active", False))

    def close(self, timeout: float = 30.0) -> None:
        """Flush everything parked, stop the dispatcher + completer,
        join both. Parked requests complete normally (drain semantics —
        their clients were admitted before the drain began)."""
        thread = self._thread
        if thread is None:
            return
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        thread.join(timeout=timeout)
        self._thread = None
        completer = self._completer
        if completer is not None:
            self._completions.put(None)  # sentinel after the last flush
            completer.join(timeout=timeout)
            self._completer = None

    def drain_parked(self, timeout: float = 30.0) -> None:
        """Block until every currently-parked request has completed —
        the demotion barrier's coalescer leg (see
        :func:`tpubloom.ha.promotion.become_replica`: parked writes
        passed the READONLY fence but hold NO filter lock, so the
        take-every-lock-once barrier alone would not wait for them).
        Polls rather than waiting on the condition: the caller holds
        ``service.promote``, and a condition wait under a foreign lock
        is exactly what the lock tracker flags."""
        deadline = time.monotonic() + timeout
        with self._cond:
            self._urgent += 1
            self._cond.notify_all()
        try:
            while time.monotonic() < deadline:
                with self._cond:
                    if (
                        not self._groups
                        and not self._flushing
                        and not self._completing
                        and not self._inflight.pending
                    ):
                        return
                time.sleep(0.002)
            log.warning("ingest drain_parked: %.0fs deadline hit", timeout)
        finally:
            with self._cond:
                self._urgent -= 1

    # -- producer side -------------------------------------------------------

    #: method -> per-filter queue kind: each kind flushes as its own
    #: op-pure launch + merged log record (queries/inserts since ISSUE
    #: 10; deletes and clears since ISSUE 12 — the named PR-10 seam)
    _KINDS = {
        "InsertBatch": "insert",
        "QueryBatch": "query",
        "DeleteBatch": "delete",
        "Clear": "clear",
    }

    def _make_entry(self, method: str, req: dict,
                    replay_unsafe: bool) -> _Entry:
        from tpubloom.server import protocol

        rows = keys = None
        kind = self._KINDS[method]
        fx = protocol.fixed_keys(req)
        if fx is not None:
            data, width, n = fx
            rows = np.frombuffer(data, np.uint8).reshape(n, width)
        else:
            # Clear carries no keys — it parks as an empty entry and the
            # flush applies ONE clear for the whole parked run
            keys = req.get("keys") if kind != "clear" else []
            if keys is None:
                keys = []
        return _Entry(req, rows=rows, keys=keys, replay_unsafe=replay_unsafe)

    def _park(self, entry: _Entry, name: str, kind: str) -> bool:
        """Queue one entry under the bounded-park budget; False when
        the coalescer is stopped/stopping."""
        with self._cond:
            if self._stop:
                return False
            # bounded queue: block (briefly, repeatedly) until there is
            # room — the dispatcher drains continuously, so this is
            # backpressure, not a deadlock risk (and the timeout keeps
            # the wait bounded for the runtime lock tracker)
            while (
                self._parked_keys + entry.nkeys > self.config.max_parked_keys
                and self._parked_keys > 0
                and not self._stop
            ):
                self._cond.wait(timeout=0.05)
            if self._stop:
                return False
            self._groups.setdefault((name, kind), []).append(entry)
            self._parked_keys += entry.nkeys
            obs_counters.set_gauge("ingest_parked_current", self._parked_keys)
            self._cond.notify_all()
        return True

    def parked_budget_left(self) -> int:
        """Headroom under ``max_parked_keys`` right now — the signal
        the streaming plane's credit grants follow (ISSUE 18)."""
        with self._cond:
            return max(0, self.config.max_parked_keys - self._parked_keys)

    def submit(self, method: str, req: dict, *,
               replay_unsafe: bool = False) -> Optional[dict]:
        """Park one request until its flush completes; returns the
        demuxed response (or raises its error). Returns **None** when
        the coalescer is stopped/stopping — the handler falls back to
        the direct path instead of parking on a dead queue."""
        from tpubloom.server import protocol

        faults.fire("ingest.coalesce")
        kind = self._KINDS[method]
        entry = self._make_entry(method, req, replay_unsafe)
        name = req["name"]
        if not self._park(entry, name, kind):
            return None
        budget = self._entry_budget(entry)
        with obs_trace.span("ingest.park", filter=name, op=kind):
            done = entry.event.wait(timeout=budget)
        if not done:
            raise protocol.BloomServiceError(
                "INTERNAL",
                f"coalesced {method} did not complete within {budget:.0f}s",
            )
        if entry.error is not None:
            raise entry.error
        return entry.resp

    def submit_nowait(self, method: str, req: dict, *,
                      replay_unsafe: bool = False, callback) -> bool:
        """Park one request WITHOUT waiting for its flush (the
        streaming ingest plane, ISSUE 18): ``callback(entry)`` fires on
        the completing thread — outside every coalescer/filter lock —
        once the flush demuxed this entry's verdict into ``entry.resp``
        / ``entry.error``. Returns False when the coalescer is
        stopped/stopping (the caller drives the direct path instead).

        The bounded-park backpressure still applies to the CALLING
        thread: a stream's receiver blocking here until the dispatcher
        drains is exactly how an over-budget server parks the stream
        (gRPC/TCP flow control pushes back on the sender) instead of
        shedding it."""
        faults.fire("ingest.coalesce")
        kind = self._KINDS[method]
        entry = self._make_entry(method, req, replay_unsafe)
        entry.callback = callback
        return self._park(entry, req["name"], kind)

    def _entry_budget(self, entry: _Entry) -> float:
        """Generous completion budget: flush deadline + the longest
        barrier the flush could run + margin. A hang past this is a bug
        (the dispatcher completes entries even on flush errors)."""
        barrier_ms = max(
            int(entry.timeout_ms or 0),
            self._service.min_replicas_max_lag_ms or 0,
            1000,
        )
        return self.config.max_wait_us / 1e6 + barrier_ms / 1000.0 + 60.0

    # -- dispatcher ----------------------------------------------------------

    def _run(self) -> None:
        self._in_dispatch.active = True
        stopping = False
        while not stopping:
            with self._cond:
                batch = self._pop_ripe_locked()
                if batch is None:
                    if self._stop and not self._groups:
                        stopping = True
                    elif not self._inflight.pending:
                        # nothing ripe and nothing in flight: sleep
                        # until the oldest entry's deadline or a submit
                        timeout = self._wait_locked()
                        self._cond.wait(
                            timeout=1.0 if timeout is None
                            else max(timeout, 0.0005)
                        )
                        batch = self._pop_ripe_locked()
                if batch is not None:
                    self._flushing += 1
            if batch is None:
                # the gap gave the in-flight kernel its overlap window —
                # fence it and complete its waiters (outside all locks)
                self.flush_inflight()
                continue
            (name, kind), entries = batch
            try:
                self._flush(name, kind, entries)
            except BaseException as e:  # noqa: BLE001 — waiters must wake
                from tpubloom.server import protocol

                log.exception("ingest flush for %r failed", name)
                err = (
                    e if isinstance(e, protocol.BloomServiceError)
                    else protocol.BloomServiceError(
                        "INTERNAL", f"ingest flush failed: {e!r}"
                    )
                )
                for entry in entries:
                    if not entry.event.is_set():
                        entry.complete(error=err)
            finally:
                with self._cond:
                    self._flushing -= 1
                    self._cond.notify_all()
        self.flush_inflight()

    def _wait_locked(self) -> Optional[float]:
        """Seconds until the oldest parked entry ripens (None = idle)."""
        if not self._groups:
            return None
        oldest = min(
            entries[0].enq_t for entries in self._groups.values() if entries
        )
        return max(
            0.0, oldest + self.config.max_wait_us / 1e6 - time.monotonic()
        )

    def _pop_ripe_locked(self):
        """Pop the ripest group (size/bytes/deadline), or None."""
        now = time.monotonic()
        ripe_key = None
        for key, entries in self._groups.items():
            if not entries:
                continue
            nkeys = sum(e.nkeys for e in entries)
            nbytes = sum(e.nbytes for e in entries)
            if (
                self._urgent
                or self._stop
                or nkeys >= self.config.max_keys
                or nbytes >= self.config.max_bytes
                or now - entries[0].enq_t >= self.config.max_wait_us / 1e6
            ):
                ripe_key = key
                break
        if ripe_key is None:
            return None
        entries = self._groups.pop(ripe_key)
        self._parked_keys -= sum(e.nkeys for e in entries)
        obs_counters.set_gauge("ingest_parked_current", self._parked_keys)
        return ripe_key, entries

    # -- flush ---------------------------------------------------------------

    def _flush(self, name: str, kind: str, entries: list) -> None:
        """One flush, optionally traced (ISSUE 15): when any parked
        request is captured, the flush runs under ITS OWN trace id —
        the ``ingest.flush`` root span LINKS every traced request's
        root span, the request context it opens turns the kernel
        phases (host_prep/h2d/kernel) into the flush span's children,
        and the merged op-log record is minted under the flush rid
        (``_log_op`` reads ``obs.current_rid()``), so replica applies
        of the merged record join the same trace. Untraced flushes
        take the exact pre-ISSUE-15 path."""
        refs = [e.trace for e in entries if e.trace is not None]
        if not (obs_trace.enabled() and refs):
            return self._flush_inner(name, kind, entries, None)
        frid = obs.new_rid()
        froot = obs_trace.new_span_id()
        with obs.request(f"ingest.{kind}", rid=frid) as rctx:
            rctx.trace_armed = True
            rctx.trace_span = froot
            try:
                return self._flush_inner(name, kind, entries, (frid, froot))
            finally:
                obs_trace.record_span(
                    "ingest.flush",
                    rid=frid,
                    span=froot,
                    start=rctx.started_at,
                    duration_s=max(0.0, time.time() - rctx.started_at),
                    attrs={
                        "filter": name,
                        "op": kind,
                        "requests": len(entries),
                        "keys": int(sum(e.nkeys for e in entries)),
                    },
                    links=[{"rid": r, "span": s} for r, s in refs],
                )
                obs_trace.commit_children(rctx, froot)

    def _flush_inner(
        self, name: str, kind: str, entries: list, ftrace
    ) -> None:
        from tpubloom.server import protocol

        service = self._service
        faults.fire("ingest.flush")
        try:
            mf = service._get(name)
        except protocol.BloomServiceError as e:
            for entry in entries:
                entry.complete(error=e)
            return
        service.metrics.count("ingest_flushes")
        service.metrics.count("ingest_requests_coalesced", len(entries))
        total_keys = sum(e.nkeys for e in entries)
        service.metrics.count("ingest_keys_coalesced", total_keys)
        if kind in ("query", "delete", "clear"):
            if kind == "query":
                service.metrics.count("ingest_query_flushes")
            elif kind == "delete":
                service.metrics.count("ingest_delete_flushes")
            else:
                service.metrics.count("ingest_clear_flushes")
            self._retry_evicted(name, mf, {
                "query": lambda m: self._flush_query(m, entries),
                "delete": lambda m: self._flush_delete(
                    name, m, entries, ftrace
                ),
                "clear": lambda m: self._flush_clear(
                    name, m, entries, ftrace
                ),
            }[kind])
            return
        # op-sorted flushes (ISSUE 11 satellite): ONE presence-wanting
        # request used to drag every flush-mate through the fused
        # test-and-insert kernel (BENCH r05: fused sweeps 45.9M keys/s
        # vs 67.7M insert-only). Sort the parked run instead — plain
        # inserts ride the insert-only launch, presence requests ride
        # the fused one. Two launches + two merged log records, but
        # each at its op's best rate; the mix counters say how often
        # the split actually pays.
        plain = [e for e in entries if not e.want_presence]
        pres = [e for e in entries if e.want_presence]
        # the launch-mix counters: plain + fused launches sum to all
        # insert launches, split counts the parked runs that got sorted
        # into both — so the op-sort lever's reach is derivable
        if plain and pres:
            service.metrics.count("ingest_split_flushes")
        if plain:
            service.metrics.count("ingest_plain_flushes")
        if pres:
            service.metrics.count("ingest_fused_flushes")
        for part in (plain, pres):
            if not part:
                continue
            # error containment PER PART: by the time the second part
            # runs, the first part's writes may already be applied,
            # logged, and parked on the completer awaiting their
            # barrier verdict — letting a second-part failure propagate
            # to the run loop's catch would error-complete THOSE
            # entries too (a generic INTERNAL on an applied+logged
            # write invites a fresh-rid client retry = double apply).
            # Each part owns exactly its own waiters.
            try:
                self._retry_evicted(
                    name, mf,
                    lambda m: self._flush_insert(name, m, part, ftrace),
                )
            except BaseException as e:  # noqa: BLE001 — waiters must wake
                log.exception("ingest flush part for %r failed", name)
                err = (
                    e if isinstance(e, protocol.BloomServiceError)
                    else protocol.BloomServiceError(
                        "INTERNAL", f"ingest flush failed: {e!r}"
                    )
                )
                for entry in part:
                    if not entry.event.is_set():
                        entry.complete(error=err)

    def _retry_evicted(self, name: str, mf, fn):
        """Run one flush body, re-resolving across eviction races
        (ISSUE 14): ``_check_live`` raises FIRST under every flush's op
        lock, before anything applies, so the retry is clean — the
        re-resolve hydrates the live filter and the body re-runs."""
        from tpubloom.server import protocol

        for _ in range(4):
            try:
                return fn(mf)
            except _EvictedRace:
                mf = self._service._get(name)
        raise protocol.BloomServiceError(
            "INTERNAL",
            f"flush for {name!r} kept racing evictions — giving up",
        )

    @staticmethod
    def _log_parts(logged: dict, entries: list) -> None:
        """Stamp the merged record with its replay-unsafe constituents
        (ISSUE 18): ``parts = [[rid, nkeys], ...]``. A merged record
        used to carry only the FLUSH rid, so a restart (or a promoted
        replica) could not answer a parked request's own rid from the
        dedup cache — a client replaying an applied-but-unacked
        counting insert after a crash would double-apply. Replaying the
        record now re-seeds one dedup entry per part
        (:meth:`BloomService.apply_record`)."""
        parts = [
            [e.rid, e.nkeys]
            for e in entries if e.replay_unsafe and e.rid
        ]
        if parts:
            logged["parts"] = parts

    @staticmethod
    def _demote_wide_rows(mf, rows, keys):
        """Fixed-width keys WIDER than the filter's key_len cannot take
        the packed path — materialize the list so ``key_policy``
        applies (digest/error), exactly as on the direct path's
        ``_packed_ok`` fallback."""
        if rows is None:
            return rows, keys
        key_len = getattr(getattr(mf.filter, "config", None), "key_len", None)
        if key_len is not None and rows.shape[1] > key_len:
            return None, _rows_to_list(rows)
        return rows, keys

    @staticmethod
    def _merge(entries: list):
        """Merged keys for one flush: ``(rows, keys)`` — a single
        ``uint8[N, W]`` array when every entry shipped fixed-width keys
        of one width (zero-copy concat), else one materialized list."""
        widths = {
            e.rows.shape[1] for e in entries if e.rows is not None
        }
        if len(widths) == 1 and all(e.rows is not None for e in entries):
            if len(entries) == 1:
                return entries[0].rows, None
            return np.concatenate([e.rows for e in entries]), None
        merged: list = []
        for e in entries:
            merged.extend(_keys_of(e))
        return None, merged

    def _flush_query(self, mf, entries: list) -> None:
        rows, keys = self._demote_wide_rows(mf, *self._merge(entries))
        # stage OUTSIDE the op lock where the filter supports it — the
        # host prep/H2D of this flush overlaps the previous flush's
        # in-flight kernel (double buffering, ISSUE 10)
        staged = None
        if self._service._staged_ok(mf):
            staged = mf.filter.stage_batch(keys, rows=rows)
        with mf.lock:
            _check_live(mf)
            if staged is not None:
                hits_dev, _ = mf.filter.launch_query(staged)
                hits = np.asarray(hits_dev)  # fence + D2H
            else:
                hits = np.asarray(
                    mf.filter.include_batch(
                        keys if keys is not None else _rows_to_list(rows)
                    )
                )
        self._service.metrics.count("keys_queried", sum(e.nkeys for e in entries))
        off = 0
        for entry in entries:
            span = hits[off: off + entry.nkeys]
            off += entry.nkeys
            entry.complete(resp={
                "ok": True,
                "hits": np.packbits(span).tobytes(),
                "n": entry.nkeys,
                "_coalesced": True,
            })

    def _flush_insert(self, name: str, mf, entries: list, ftrace=None) -> None:
        service = self._service
        rows, keys = self._demote_wide_rows(mf, *self._merge(entries))
        want_presence = any(e.want_presence for e in entries)
        supports_staged = not want_presence and service._staged_ok(mf)
        staged = (
            mf.filter.stage_batch(keys, rows=rows) if supports_staged else None
        )
        # fence + settle the PREVIOUS flush before this one's (donating)
        # launch — its kernel had our whole staging window to run, and a
        # barrier-bearing completion hops to the completer thread, so
        # neither blocks the dispatcher.
        self._settle(*self._inflight.take())
        presence = None
        with mf.lock:
            _check_live(mf)
            if service.cluster is not None and (
                service.cluster.forward_target(name) is not None
            ):
                # dual-write window: a merged record would make N
                # requests share ONE src_seq and the target's gate would
                # drop every forward but the first — fall back to the
                # per-request direct path (checked under the SAME lock
                # MigrateSlot arms forwards under, so a snapshot taken
                # after this hold covers everything we would apply)
                fallback = True
            else:
                fallback = False
                if staged is not None:
                    out = mf.filter.launch_insert(staged)
                elif want_presence:
                    klist = keys if keys is not None else _rows_to_list(rows)
                    if mf.supports_presence:
                        presence = mf.filter.insert_batch(
                            klist, return_presence=True
                        )
                    else:
                        presence = mf.filter.include_batch(klist)
                        mf.filter.insert_batch(klist)
                    out = None
                else:
                    klist = keys if keys is not None else _rows_to_list(rows)
                    mf.filter.insert_batch(klist)
                    out = None
                # honest-FULL verdicts (ISSUE 19): cuckoo inserts can
                # reject; collect the per-key flags under the lock —
                # they are per-launch state the NEXT flush would clobber
                # (for a staged launch this fences it early; cuckoo's
                # kick chain is sequential anyway, and honesty beats
                # overlap). Rejected keys still ride the logged record:
                # the kernels are deterministic, so a replica / crash
                # replay rejects the exact same keys.
                full = None
                taker = getattr(mf.filter, "take_insert_flags", None)
                if taker is not None:
                    flags = taker()
                    if flags is not None and not flags.all():
                        full = ~np.asarray(flags, dtype=bool)
                        if out is not None:
                            out = None  # already fenced by the flag read
                # ONE op-log append covers the whole flush (log before
                # notify — the PR-3 ordering rule)
                logged: dict = {"name": name}
                if rows is not None:
                    logged["keys_fixed"] = {
                        "data": rows.tobytes(),
                        "width": int(rows.shape[1]),
                        "n": int(rows.shape[0]),
                    }
                else:
                    logged["keys"] = keys
                self._log_parts(logged, entries)
                seq = service._log_op("InsertBatch", logged, mf)
                if mf.checkpointer:
                    mf.checkpointer.notify_inserts(
                        sum(e.nkeys for e in entries)
                    )
        if fallback:
            self._fallback_direct(entries)
            return
        service.metrics.count(
            "keys_inserted", sum(e.nkeys for e in entries)
        )
        if presence is not None:
            presence = np.asarray(presence)  # fence + D2H, outside the lock

        def finalize():
            self._finalize_insert(entries, seq, presence, ftrace, full=full)

        payload = (entries, finalize, self._needs_barrier(entries, seq))
        if out is not None:
            # double buffering: park the launched (unfenced) kernel;
            # the NEXT flush's staging (or the run loop's idle check)
            # overlaps it, then settles us
            self._inflight.put(out, payload)
        else:
            self._settle(payload, None)

    def _flush_delete(self, name: str, mf, entries: list, ftrace=None) -> None:
        """Delete-only flush (ISSUE 12 satellite — the PR-10 seam): ONE
        ``delete_batch`` launch over the merged keys + ONE op-log append
        + ONE commit barrier, demuxed per request exactly like inserts.
        Deletes are always replay-unsafe (a replayed decrement double-
        applies), so every entry's demuxed response is dedup-cached
        under its rid by the shared finalize."""
        service = self._service
        rows, keys = self._demote_wide_rows(mf, *self._merge(entries))
        # fence + settle any in-flight insert flush BEFORE the (donating)
        # delete launch consumes its output buffer — a real kernel error
        # must fail the INSERT's waiters, not surface as this delete's
        self._settle(*self._inflight.take())
        with mf.lock:
            _check_live(mf)
            if service.cluster is not None and (
                service.cluster.forward_target(name) is not None
            ):
                # dual-write window: per-request seqs keep the target's
                # exactly-once gate sound — same fallback as inserts
                fallback = True
            else:
                fallback = False
                klist = keys if keys is not None else _rows_to_list(rows)
                dout = mf.filter.delete_batch(klist)
                deleted = None
                if dout is not None and sketch_registry.is_sketch(
                    mf.filter.config
                ):
                    # cuckoo per-key "a stored copy existed" verdicts,
                    # demuxed back to each parked request like presence
                    deleted = np.asarray(dout, dtype=bool)
                logged: dict = {"name": name}
                if rows is not None:
                    logged["keys_fixed"] = {
                        "data": rows.tobytes(),
                        "width": int(rows.shape[1]),
                        "n": int(rows.shape[0]),
                    }
                else:
                    logged["keys"] = keys
                self._log_parts(logged, entries)
                seq = service._log_op("DeleteBatch", logged, mf)
        if fallback:
            self._fallback_direct(entries, method="DeleteBatch")
            return
        service.metrics.count("keys_deleted", sum(e.nkeys for e in entries))

        def finalize():
            self._finalize_insert(entries, seq, None, ftrace, deleted=deleted)

        self._settle((entries, finalize, self._needs_barrier(entries, seq)), None)

    def _flush_clear(self, name: str, mf, entries: list, ftrace=None) -> None:
        """Clear-only flush: the whole parked run collapses to ONE
        ``clear()`` + ONE op-log append + ONE barrier (clears are
        idempotent, so N concurrent clears ARE one clear — no dedup
        caching needed and no per-request payload to demux)."""
        service = self._service
        self._settle(*self._inflight.take())  # see _flush_delete
        with mf.lock:
            _check_live(mf)
            if service.cluster is not None and (
                service.cluster.forward_target(name) is not None
            ):
                fallback = True
            else:
                fallback = False
                mf.filter.clear()
                seq = service._log_op("Clear", {"name": name}, mf)
        if fallback:
            self._fallback_direct(entries, method="Clear")
            return

        def finalize():
            self._finalize_insert(entries, seq, None, ftrace)

        self._settle((entries, finalize, self._needs_barrier(entries, seq)), None)

    def _needs_barrier(self, entries, seq) -> bool:
        if seq is None:
            return False
        return max(
            [self._service.min_replicas_to_write]
            + [e.min_replicas for e in entries]
        ) > 0

    def _settle(self, payload, fence_err) -> None:
        """Complete one fenced flush. A REAL fence error (device/kernel
        failure — the benign donated-buffer case is filtered by
        :meth:`InFlight.take`) fails every waiter instead of acking
        writes that never landed. Otherwise the finalize runs inline
        when it is pure demux, and hops to the completer thread when it
        carries a commit barrier — a quorum wait must never head-of-
        line-block other filters' flushes on the dispatcher."""
        if payload is None:
            return
        entries, finalize, barrier = payload
        if fence_err is not None:
            from tpubloom.server import protocol

            log.error("ingest flush kernel failed: %r", fence_err)
            err = protocol.BloomServiceError(
                "INTERNAL", f"coalesced flush kernel failed: {fence_err!r}"
            )
            for entry in entries:
                if not entry.event.is_set():
                    entry.complete(error=err)
            return
        if barrier:
            with self._cond:
                self._completing += 1
            self._completions.put(finalize)  # bounded — backpressure
        else:
            finalize()

    def _completion_loop(self) -> None:
        while True:
            fn = self._completions.get()
            if fn is None:
                return
            try:
                fn()  # _finalize_insert is self-protective
            finally:
                with self._cond:
                    self._completing -= 1
                    self._cond.notify_all()

    def flush_inflight(self) -> None:
        """Fence + settle any parked double-buffered flush (dispatcher
        thread only — the run loop calls this when the queues go idle)."""
        payload, err = self._inflight.take()
        if payload is None:
            return
        self._settle(payload, err)
        with self._cond:
            self._cond.notify_all()

    def _finalize_insert(
        self, entries, seq, presence, ftrace=None, full=None, deleted=None
    ) -> None:
        """Demux one applied flush back to its parked requests: dedup
        caching, presence/full/deleted slices, and ONE commit barrier
        whose achieved count settles every request's own quorum.
        Self-protective: any unexpected error completes EVERY
        still-parked entry (a finalize may run from the double-buffer
        path, outside the run loop's per-flush catch — waiters must
        never hang)."""
        from tpubloom.server import protocol

        try:
            self._finalize_insert_inner(
                entries, seq, presence, ftrace, full=full, deleted=deleted
            )
        except BaseException as e:  # noqa: BLE001 — waiters must wake
            log.exception("ingest finalize failed")
            err = (
                e if isinstance(e, protocol.BloomServiceError)
                else protocol.BloomServiceError(
                    "INTERNAL", f"ingest finalize failed: {e!r}"
                )
            )
            for entry in entries:
                if not entry.event.is_set():
                    entry.complete(error=err)

    def _finalize_insert_inner(
        self, entries, seq, presence, ftrace=None, full=None, deleted=None
    ) -> None:
        from tpubloom.server import protocol

        service = self._service
        acked, barrier_error = self._flush_barrier(entries, seq, ftrace)
        off = 0
        for entry in entries:
            resp: dict = {"ok": True, "n": entry.nkeys}
            if seq is not None:
                resp["repl_seq"] = seq
            if entry.want_presence and presence is not None:
                span = presence[off: off + entry.nkeys]
                resp["presence"] = np.packbits(span).tobytes()
            if full is not None:
                span = full[off: off + entry.nkeys]
                if span.any():  # same shape as the direct path: "full"
                    # is present iff this request had rejected keys
                    resp["full"] = np.packbits(span).tobytes()
            if deleted is not None:
                resp["deleted"] = np.packbits(
                    deleted[off: off + entry.nkeys]
                ).tobytes()
            off += entry.nkeys
            if entry.replay_unsafe:
                # cache the CLEAN response (no barrier verdict): a
                # same-rid retry replays it through the wrapper, which
                # re-waits on the same record — direct-path parity
                service._dedup_put(entry.rid, dict(resp))
            needed = max(service.min_replicas_to_write, entry.min_replicas)
            if needed > 0:
                if seq is None and service.oplog is None:
                    entry.complete(error=protocol.BloomServiceError(
                        "NOT_ENOUGH_REPLICAS",
                        f"min_replicas={needed} requires replication "
                        f"(start the server with --repl-log-dir)",
                        details={"acked": 0, "needed": needed,
                                 "applied": True},
                    ))
                    continue
                if seq is not None and acked < needed:
                    details = {
                        "acked": acked, "needed": needed, "seq": seq,
                        "applied": True, "coalesced": len(entries),
                    }
                    if barrier_error is not None:
                        details.setdefault(
                            "timeout_ms",
                            barrier_error.details.get("timeout_ms"),
                        )
                    entry.complete(error=protocol.BloomServiceError(
                        "NOT_ENOUGH_REPLICAS",
                        f"only {acked}/{needed} replica(s) acked seq "
                        f"{seq} for this coalesced flush — the write "
                        f"applied, only its quorum ack is missing",
                        details=details,
                    ))
                    continue
                resp["acked_replicas"] = acked
            resp["_coalesced"] = True
            entry.complete(resp=resp)

    def _flush_barrier(self, entries, seq, ftrace=None):
        """ONE ``wait_acked`` for the whole flush, at the strongest
        quorum any entry demanded and the longest budget any entry
        brought; returns ``(achieved ack count, barrier error or
        None)``. With the flush traced, the barrier records its own
        ``barrier.wait`` span under the flush root (it runs on the
        completer thread, after the flush context is gone)."""
        from tpubloom.server import protocol

        service = self._service
        needed = max(
            [service.min_replicas_to_write]
            + [e.min_replicas for e in entries]
        )
        if needed <= 0 or seq is None:
            return 0, None
        budgets = [int(e.timeout_ms) for e in entries
                   if e.timeout_ms is not None]
        barrier_req: dict = {"min_replicas": needed}
        if budgets:
            barrier_req["min_replicas_timeout_ms"] = max(budgets)
        w0, t0 = time.time(), time.perf_counter()
        try:
            try:
                resp = service.commit_barrier(barrier_req, {"repl_seq": seq})
                return int(resp.get("acked_replicas") or 0), None
            except protocol.BloomServiceError as e:
                if e.code != "NOT_ENOUGH_REPLICAS":
                    raise
                acked = int(e.details.get("acked") or 0)
                # the fail-fast (fewer connected than the max quorum)
                # path reports 0 — weaker per-entry quorums may still
                # be met
                max_age = (service.min_replicas_max_lag_ms or 0) / 1000.0
                acked = max(
                    acked,
                    service.repl_sessions.count_acked(seq, max_age=max_age),
                )
                return acked, e
        finally:
            if ftrace is not None:
                obs_trace.record_span(
                    "barrier.wait",
                    rid=ftrace[0],
                    parent=ftrace[1],
                    start=w0,
                    duration_s=time.perf_counter() - t0,
                    attrs={"seq": int(seq), "needed": int(needed)},
                )

    def _fallback_direct(self, entries: list, method: str = "InsertBatch") -> None:
        """Migration-window fallback: re-drive each parked request
        through the ordinary handler + its OWN barrier and dual-write
        forward — per-request seqs keep the target's exactly-once gate
        sound. Rare (only while a slot is mid-handoff), so the lost
        amortization is acceptable."""
        from tpubloom.cluster import migrate as cluster_migrate
        from tpubloom.server import protocol

        service = self._service
        handler = getattr(service, method)
        service.metrics.count("ingest_fallback_direct", len(entries))
        for entry in entries:
            try:
                resp = handler(entry.req)
                if resp.get("ok"):
                    resp = service.commit_barrier(entry.req, resp)
                    resp = cluster_migrate.forward_op(
                        service, method, entry.req, resp
                    )
                resp = dict(resp)
                resp["_coalesced"] = True
                entry.complete(resp=resp)
            except protocol.BloomServiceError as e:
                entry.complete(error=e)
            except BaseException as e:  # noqa: BLE001 — waiter must wake
                entry.complete(error=protocol.BloomServiceError(
                    "INTERNAL", f"ingest fallback failed: {e!r}"
                ))


def _keys_of(entry: _Entry) -> list:
    if entry.keys is not None:
        return list(entry.keys)
    return _rows_to_list(entry.rows)


def _rows_to_list(rows: np.ndarray) -> list:
    return [rows[i].tobytes() for i in range(rows.shape[0])]
