"""The tpubloom gRPC server — the L5 "storage/server runtime" replacement.

Parity: where the reference's bottom layer is a Redis server holding the
bitmap and running Lua scripts (SURVEY.md §1 L5), this process holds the
bit arrays in TPU HBM and runs the jit-compiled kernels. The Ruby front-end
talks to it through the ``:jax`` driver (clients/ruby) exactly as it talked
RESP to Redis; Python clients use :mod:`tpubloom.server.client`.

Runtime properties:

* one lock per filter — ALL ops on a filter serialize, mirroring the
  single-threaded Redis command loop that gave the reference its race
  freedom (SURVEY.md §5 race-detection row). This is load-bearing, not
  just parity: inserts jit with ``donate_argnums=0``, which recycles the
  previous HBM buffer in place, so a lock-free concurrent query could
  gather from a donated (deleted or mid-update) buffer. Cross-filter
  parallelism is unaffected;
* per-filter async checkpointing with bounded lag (``checkpoint_every``);
* health + stats RPCs (gRPC health-check parity, SURVEY.md §5 failure row);
* graceful restart: on startup every configured filter restores its newest
  checkpoint.

Robustness (ISSUE 2):

* **overload shedding** — ``max_in_flight`` caps concurrently-executing
  data-plane RPCs; excess requests are rejected *before decode* with
  ``RESOURCE_EXHAUSTED`` + ``retry_after_ms`` instead of queueing toward
  OOM. ``Health`` (and the other cheap control-plane reads) never sheds,
  so the overload state stays observable;
* **health states** — ``Health`` reports ``SERVING`` / ``DEGRADED``
  (checkpoint write errors, corrupt checkpoint seen at restore, recent
  shedding) / ``DRAINING``, with machine-readable reasons;
* **graceful drain** — on SIGTERM the server stops admitting work
  (``DRAINING`` sheds), lets in-flight RPCs finish, takes a final
  checkpoint of every dirty filter, then exits;
* **retryable DeleteBatch** — a bounded rid→response dedup cache answers
  a replayed counting-filter delete from cache instead of
  double-decrementing (client retries reuse the logical call's rid);
* **fault points** — ``rpc.pre_handle`` / ``rpc.post_handle``
  (:mod:`tpubloom.faults`) let the chaos suite simulate handler crashes
  and response-lost-after-apply without patching internals.

Replication (ISSUE 3 — :mod:`tpubloom.repl`):

* **op log** — with an :class:`tpubloom.repl.OpLog` attached
  (``--repl-log-dir``), every mutating RPC appends one CRC32C-framed
  record at its commit point (under the filter's op lock, so log order
  equals apply order per filter). Startup replays the log over the
  restored checkpoints — per-filter ``repl_seq`` stamps in checkpoint
  headers gate the replay, so acked writes survive a crash even when
  the checkpoint lagged (AOF parity), and nothing applies twice.
  Checkpoint-keyed truncation keeps only the tail the checkpoints do
  not yet cover (bounded additionally by the slowest connected
  replica's cursor).
* **primary→replica streaming** — the ``ReplStream`` RPC
  (:mod:`tpubloom.repl.primary`) serves full resyncs (live-filter
  snapshot blobs + log tail) and partial resyncs (cursor still in the
  log), PSYNC-style; connected replicas and their lag are gauges.
* **read replicas** — ``read_only=True`` (``--replica-of host:port``)
  rejects every mutating RPC with ``READONLY`` (Redis parity) while a
  :class:`tpubloom.repl.ReplicaApplier` keeps local state in sync;
  reads/health/stats serve normally.
* **MONITOR parity** — the ``Monitor`` streaming RPC tails every
  finished request (optionally filtered per filter name) off the same
  commit points, via :class:`tpubloom.repl.MonitorHub`.
* **adaptive retry hints** — shed responses carry a ``retry_after_ms``
  that grows with the observed shed rate (the measurable queue-pressure
  signal once the in-flight cap is pegged) and decays back to the
  configured base when the burst passes.

High availability (ISSUE 4 — :mod:`tpubloom.ha`):

* **promotion / demotion** — the ``Promote`` RPC (``REPLICAOF NO ONE``
  parity; also ``python -m tpubloom.server promote host:port``) flips a
  replica to primary by adopting the op log and bumping the persisted
  **topology epoch**; ``ReplicaOf`` re-points (or demotes) a node. Both
  are epoch-stamped — stale epochs answer ``STALE_EPOCH`` (Raft term
  discipline), which is also how a restarted pre-failover primary gets
  fenced by a sentinel.
* **chained replicas** — ``--replica-of`` + ``--repl-log-dir`` together:
  applied records re-append to the local log in the upstream's seq
  space (:meth:`BloomService.reappend_record`), so this node serves
  ``ReplStream`` downstream and promotes in place.
* **epoch fencing on the data plane** — a mutating request stamped with
  an older topology epoch than this server's is rejected with
  ``STALE_EPOCH`` so topology-aware clients refresh instead of writing
  under a stale view.
* **replica durability** — with a state dir, the replication cursor
  (``repl_cursor.json``) and creation manifest persist; a replica
  restart restores filters from local checkpoints and PARTIAL-resyncs.

Synchronous replication (ISSUE 5 — ``WAIT`` / ``min-replicas-to-write``
parity):

* **replica acks** — replicas report their applied cursor back on a
  client-streaming ``ReplAck`` RPC (:func:`tpubloom.repl.primary.
  repl_ack`); :class:`ReplicaSessions` tracks per-replica acked seqs
  (gauge ``repl_acked_seq{replica}``).
* **commit barrier** — with ``--min-replicas-to-write N`` (or a
  per-request ``min_replicas``), each mutating RPC blocks AFTER its
  op-log append, outside all locks, until N replicas acked the record
  (:meth:`BloomService.commit_barrier`); timeout →
  ``NOT_ENOUGH_REPLICAS`` (+ Health ``DEGRADED``), the local apply
  stands (Redis semantics — WAIT never rolls back).
* **Wait RPC** — Redis ``WAIT numreplicas timeout`` parity, keyed to
  the caller's last-write ``repl_seq``; returns the achieved count.
* a quorum-acked write is by construction on the most-caught-up
  replica, which is exactly the sentinel's promotion pick — so it
  survives a primary SIGKILL *without* the client rid re-drive.

Cluster mode (ISSUE 9 — :mod:`tpubloom.cluster`, Redis Cluster parity):

* **slot ownership on every keyed RPC** — with ``--cluster`` a
  :class:`tpubloom.cluster.ClusterState` is attached and the wrapper
  checks ``key_slot(req["name"])`` before the handler: unowned slots
  answer ``MOVED <slot> <addr>``, migrating slots answer ``ASK`` for
  filters already handed off, importing slots serve only
  ``asking``-flagged requests, unassigned slots answer ``CLUSTERDOWN``;
* **live slot migration** — ``MigrateSlot`` streams each filter's
  snapshot blob + op-log tail to the new owner (the PR-3/5 resync
  machinery node→node) with a dual-write window: after the snapshot,
  every committed mutating RPC on a migrating filter forwards to the
  target (original rid + source seq) BEFORE the client is acked, and
  the target's seq gate + rid dedup make re-deliveries exactly-once;
* **map admin** — ``ClusterSlots`` (client bootstrap), ``ClusterSetSlot``
  (marks + config-epoch-guarded ownership flips), driven by
  ``python -m tpubloom.cluster`` (init / migrate / rebalance).
"""

from __future__ import annotations

import logging
import math
import threading
import time
from collections import OrderedDict
from concurrent import futures
from contextlib import contextmanager
from typing import Optional

import grpc
import numpy as np

from tpubloom import checkpoint as ckpt
from tpubloom import faults
from tpubloom.obs import counters as obs_counters
from tpubloom.config import FilterConfig, IDENTITY_FIELDS, identity_mismatch
from tpubloom.filter import BloomFilter, CountingBloomFilter
from tpubloom.obs import context as obs
from tpubloom.obs import blackbox as obs_blackbox
from tpubloom.obs import flight as obs_flight
from tpubloom.obs import trace as obs_trace
from tpubloom.obs.slowlog import Slowlog, summarize_request
from tpubloom.params import round_up_pow2
from tpubloom.cluster import migrate as cluster_migrate
from tpubloom.cluster import node as cluster_node
from tpubloom.cluster import slots as cluster_slots
from tpubloom.repl import monitor as repl_monitor
from tpubloom.repl import primary as repl_primary
from tpubloom.repl.replica import FullResyncNeeded
from tpubloom.server import protocol
from tpubloom.server import streams as server_streams
from tpubloom.sketch import registry as sketch_registry
from tpubloom.server.metrics import Metrics
from tpubloom.utils import locks, tracing

log = logging.getLogger("tpubloom.server")


class _Managed:
    def __init__(self, filt, sink, checkpoint_every: int):
        import inspect

        self.filter = filt
        self.lock = locks.named_lock("filter.op")
        #: set (under ``lock``) when the storage tier evicted this
        #: filter out of the registry (ISSUE 14): a straggler that
        #: resolved the object before the eviction re-checks this flag
        #: after acquiring the lock (``BloomService._op``) and
        #: re-resolves through the hydration path instead of writing to
        #: detached device arrays
        self.evicted = False
        #: durable floor this filter hydrated from (set by the storage
        #: tier): lets a read-only residency cycle evict WITHOUT a
        #: fresh final checkpoint — see TenantStore._evict
        self.hydration_landed_seq = None
        #: newest op-log seq whose effect this filter's state contains —
        #: advanced at every logged commit, persisted into checkpoint
        #: headers (``repl_seq``), and used to gate replay/stream apply
        #: to exactly-once semantics
        self.applied_seq = 0
        # fused test-and-insert capability is a static property of the
        # filter class — probe once, not per InsertBatch request
        self.supports_presence = (
            "return_presence" in inspect.signature(filt.insert_batch).parameters
        )
        self.checkpointer = (
            ckpt.AsyncCheckpointer(
                filt,
                sink,
                every_n_inserts=checkpoint_every,
                meta_fn=lambda: {"repl_seq": self.applied_seq},
            )
            if sink is not None
            else None
        )


#: RPCs that are never shed: Health must answer DURING overload or the
#: operator flies blind, the reads are cheap in-memory control-plane
#: lookups holding no device buffers, and the HA verbs (Promote /
#: ReplicaOf) must land on an overloaded cluster — a failover that can
#: be shed is not a failover.
#: Wait is deliberately NOT here: it parks a worker thread for up to its
#: timeout, so under overload it must count against --max-in-flight and
#: shed like any data-plane call (Redis WAIT is a normal command too) —
#: unsheddable Waits could exhaust the whole pool and starve Health.
#: The cluster verbs (ISSUE 9) are control plane like the HA verbs: a
#: shed ClusterSlots blinds clients mid-redirect storm, and a shed
#: migration hop wedges a rebalance exactly when load made it urgent.
#: TraceGet (ISSUE 15) joins the unsheddable control plane for the same
#: reason as Health: the trace of a slow request is most needed exactly
#: while the node is overloaded, and the lookup is a cheap in-memory
#: ring read holding no device buffers.
UNSHEDDABLE = frozenset(
    {"Health", "ListFilters", "SlowlogGet", "SlowlogReset", "TraceGet",
     "Promote", "ReplicaOf",
     "ClusterSlots", "ClusterSetSlot", "MigrateSlot", "MigrateInstall"}
)

#: How long after the last shed Health keeps reporting the "shedding"
#: degraded reason — long enough for a scraper/prober to catch a burst.
SHED_DEGRADED_WINDOW_S = 5.0

#: Adaptive retry_after_ms (ISSUE 3 satellite): the shed-pressure term
#: decays with this time constant, and the hint never exceeds
#: base * RETRY_AFTER_CAP_FACTOR.
PRESSURE_DECAY_S = 1.0
RETRY_AFTER_CAP_FACTOR = 32

#: Commit-point appends between checkpoint-keyed log-truncation sweeps.
TRUNCATE_EVERY_APPENDS = 64


class _TenantPagedRace(Exception):
    """A create/drop hydrated its tenant first, but an eviction paged it
    back out before the registry lock was taken (ISSUE 14). The caller
    re-hydrates and retries — building a FRESH filter (or answering
    ``existed: False``) over paged state would silently lose it."""

#: Default commit-barrier / Wait budget when neither the server flag nor
#: the request provides one (ms).
DEFAULT_MIN_REPLICAS_MAX_LAG_MS = 1000

#: A Wait RPC with timeout_ms<=0 would block a worker thread forever
#: (Redis WAIT 0 semantics); clamp to this ceiling instead.
WAIT_TIMEOUT_CAP_S = 60.0


class BloomService:
    """Method handlers; state = {name: _Managed}."""

    def __init__(
        self,
        sink_factory=None,
        *,
        slowlog_capacity: int = 128,
        max_in_flight: Optional[int] = None,
        retry_after_ms: int = 50,
        dedup_capacity: int = 1024,
        oplog=None,
        read_only: bool = False,
        epoch: Optional[int] = None,
        repl_batch_bytes: Optional[int] = None,
        listen_address: Optional[str] = None,
        min_replicas_to_write: int = 0,
        min_replicas_max_lag_ms: int = DEFAULT_MIN_REPLICAS_MAX_LAG_MS,
        cluster=None,
        coalesce=None,
        storage=None,
        trace_sample=None,
    ):
        """``sink_factory(config) -> sink|None`` decides where each filter
        checkpoints (None disables persistence for that filter).
        ``max_in_flight`` caps concurrently-executing sheddable RPCs
        (None/0 = unbounded); shed responses carry a ``retry_after_ms``
        hint that starts at the configured base and grows with the shed
        rate. ``dedup_capacity`` bounds the rid→response replay cache
        that makes DeleteBatch (and non-idempotent InsertBatch) safely
        retryable (0 disables it). ``oplog`` attaches a
        :class:`tpubloom.repl.OpLog` (this process becomes a replication
        primary + AOF-durable); ``read_only=True`` makes it a replica
        (mutating RPCs answer ``READONLY``).

        ``min_replicas_to_write`` (ISSUE 5, Redis ``min-replicas-to-
        write`` parity) gates every mutating RPC behind a durability
        quorum: after the op-log append the handler blocks until that
        many replicas have ACKED the record's seq, for at most
        ``min_replicas_max_lag_ms`` — timeout answers
        ``NOT_ENOUGH_REPLICAS`` (Redis ``NOREPLICAS``). Requests may
        demand a STRONGER per-call quorum via ``min_replicas``."""
        #: distributed tracing (ISSUE 15): a float arms the process
        #: trace ring at that deterministic per-rid sample rate (0.0 =
        #: only forced / slowlog-worthy requests); None (the default)
        #: keeps tracing fully off — no wire fields, no per-request
        #: buffering, no measurable overhead
        if trace_sample is not None:
            obs_trace.configure(sample=float(trace_sample))
        #: last Health status answered — the flight recorder dumps on
        #: the SERVING -> DEGRADED flip (ISSUE 15)
        self._last_health_status = "SERVING"
        self._filters: dict[str, _Managed] = {}
        self._lock = locks.named_lock("service.registry")
        self._sink_factory = sink_factory or (lambda config: None)
        self.metrics = Metrics()
        self.slowlog = Slowlog(capacity=slowlog_capacity)
        self.max_in_flight = max_in_flight
        self.retry_after_ms = retry_after_ms
        self._in_flight = 0
        self._admit_lock = locks.named_lock("service.admit")
        self._draining = False
        self._last_shed_time = 0.0
        #: decaying shed-rate pressure (events, half-life ~PRESSURE_DECAY_S)
        #: — the adaptive component of retry_after_ms
        self._shed_pressure = 0.0
        self._pressure_updated = time.monotonic()
        self._dedup_capacity = dedup_capacity
        self._dedup: "OrderedDict[str, dict]" = OrderedDict()
        self._dedup_lock = locks.named_lock("service.dedup")
        #: filter name -> time a corrupt checkpoint was detected during its
        #: restore; cleared once a good checkpoint lands after that moment
        self._ckpt_corrupt_seen: dict[str, float] = {}
        # -- replication (ISSUE 3) --
        self.oplog = oplog
        self.read_only = read_only
        self.repl_sessions = repl_primary.ReplicaSessions()
        # -- synchronous replication (ISSUE 5) --
        #: server-wide durability quorum for mutating RPCs (0 = async,
        #: the pre-ISSUE-5 behavior); per-request ``min_replicas`` can
        #: only strengthen it
        self.min_replicas_to_write = int(min_replicas_to_write or 0)
        #: how long the commit barrier (and a default Wait) blocks for
        #: the quorum before giving up
        self.min_replicas_max_lag_ms = int(min_replicas_max_lag_ms)
        #: last time a commit barrier timed out — Health reports
        #: DEGRADED ("not_enough_replicas") for a window after
        self._last_quorum_fail_time = 0.0
        self.monitor_hub = repl_monitor.MonitorHub()
        #: set by ReplicaApplier when this process follows a primary
        self.replica_applier = None
        self.primary_address: Optional[str] = None
        #: True while replay_oplog runs — replayed ops must not re-append
        self._replaying = False
        #: per-thread record-seq hint for handlers invoked via
        #: apply_record (replay / replica stream apply): ``_log_op``
        #: returns None there, but the response a handler caches in the
        #: rid-dedup MUST still carry the record's original ``repl_seq``
        #: — a dedup-replayed answer without it would e.g. forward a
        #: migration dual-write WITHOUT its ``src_seq``, bypassing the
        #: target's exactly-once gate (a real double-apply, found by the
        #: SIGKILL chaos test)
        self._apply_seq_hint = threading.local()
        self._appends_since_truncate = 0
        # -- high availability (ISSUE 4) --
        #: topology epoch (Raft-term discipline): bumped+persisted at
        #: every promotion; stale Promote/ReplicaOf/epoch-stamped writes
        #: are rejected with STALE_EPOCH
        from tpubloom.ha.topology import EpochStore

        self._epoch_store = (
            EpochStore(oplog.directory) if oplog is not None else None
        )
        self.epoch = (
            int(epoch)
            if epoch is not None
            else (self._epoch_store.load() if self._epoch_store else 0)
        )
        obs_counters.set_gauge("ha_epoch", float(self.epoch))
        obs_counters.set_gauge("ha_role", 1.0 if read_only else 0.0)
        # crash-forensics black box (ISSUE 16): stamp the node identity
        # into the mapped ring (a no-op record when the box is
        # disarmed) — every record written after this carries the
        # current topology epoch, the fleet merge's primary sort key
        obs_blackbox.set_node_meta(
            epoch=self.epoch,
            role="replica" if read_only else "primary",
        )
        #: serializes role transitions (Promote / ReplicaOf)
        self._promote_lock = locks.named_lock("service.promote")
        #: where the creation manifest lives (the op log dir on nodes
        #: with a log; a replica's durable state dir otherwise)
        self._manifest_dir: Optional[str] = (
            oplog.directory if oplog is not None else None
        )
        #: coalesce ReplStream records up to this many raw bytes per
        #: zlib frame for replicas that negotiated the capability
        self.repl_batch_bytes = repl_batch_bytes
        #: this server's announced address (sentinel/replica discovery)
        self.listen_address = listen_address
        #: replica-side cursor persistence (set by main()/become_replica)
        self.replica_state_store = None
        #: True while the local op log is fed by a ReplicaApplier
        #: (reappend_record preserves the upstream seq space) — handler-
        #: side appends are suppressed then, or they would mint
        #: conflicting seqs. Deliberately NOT the read_only flag: an
        #: in-flight write that raced a demotion past the READONLY check
        #: must still log (become_replica drains those before attaching
        #: the applier), or its ack silently vanishes from the log.
        self._stream_fed = read_only
        #: cluster mode (ISSUE 9): a
        #: :class:`tpubloom.cluster.ClusterState` — slot map, ownership
        #: checks, migration forwards. None = single-shard (the
        #: pre-cluster behavior, no per-request overhead).
        self.cluster = cluster
        #: set (repr of the exception) when an op-log append fails AFTER
        #: its op applied in memory — state is now ahead of the log, so
        #: further writes are fail-stopped (Redis aborts writes on AOF
        #: write errors the same way) until an operator restarts
        self.oplog_error: Optional[str] = None
        #: ingestion coalescer (ISSUE 10): with a
        #: :class:`tpubloom.server.ingest.CoalesceConfig` attached,
        #: concurrent InsertBatch/QueryBatch RPCs park in per-filter
        #: queues and flush as ONE device launch + ONE op-log append +
        #: ONE commit barrier. None = the pre-ISSUE-10 direct path.
        self._coalescer = None
        if coalesce is not None:
            from tpubloom.server.ingest import IngestCoalescer

            self._coalescer = IngestCoalescer(self, coalesce).start()
        #: tiered residency manager (ISSUE 14): with a
        #: :class:`tpubloom.storage.StorageConfig` attached, the flat
        #: registry becomes a registry/storage pair — ``_filters`` holds
        #: only the RESIDENT tier, cold-ranked filters are evicted under
        #: the HBM budget into host-RAM blobs / checkpoints, and
        #: :meth:`_get` lazily re-hydrates on first RPC. None = every
        #: filter resident for the process lifetime (the pre-ISSUE-14
        #: behavior, no per-request overhead).
        self.storage = None
        if storage is not None:
            from tpubloom.storage import TenantStore

            self.storage = TenantStore(self, storage)

    @property
    def draining(self) -> bool:
        return self._draining

    # -- helpers -------------------------------------------------------------

    def _get(self, name: str) -> _Managed:
        mf = self._filters.get(name)
        if mf is not None:
            return mf
        if self.storage is not None:
            # paging fault (ISSUE 14): a WARM/COLD tenant hydrates here
            # — the caller blocks on the hydration future, so the RPC
            # wrapper and the ingest coalescer's flush path both see
            # either the whole filter or NOT_FOUND, never a torn one.
            # On the replay/stream-apply path the resolve is CONTROL
            # plane: a handler dispatched by apply_record must never be
            # quota-shed (replication progress beats data-plane
            # pressure), including its _op re-resolve after an eviction
            # race.
            mf = self.storage.resolve(name, control_plane=self._applying())
            if mf is not None:
                return mf
        raise protocol.BloomServiceError(
            "NOT_FOUND", f"filter {name!r} does not exist"
        )

    def _resident(self, name: str) -> Optional[_Managed]:
        """Registry lookup for apply/replay/admin paths: hydrates paged
        tenants on the CONTROL plane (no quota sheds — replication and
        replay must make progress regardless of data-plane pressure);
        None for unknown names."""
        mf = self._filters.get(name)
        if mf is None and self.storage is not None:
            mf = self.storage.resolve(name, control_plane=True)
        return mf

    def has_filter(self, name: str) -> bool:
        """Tenant existence across BOTH tiers (resident + paged) — what
        the cluster wrapper's ASK decision and ListFilters must see:
        an evicted tenant still exists."""
        return name in self._filters or (
            self.storage is not None and self.storage.has(name)
        )

    def _applying(self) -> bool:
        """True on the op-log replay / stream-apply path."""
        return self._replaying or (
            getattr(self._apply_seq_hint, "seq", None) is not None
        )

    @contextmanager
    def _op(self, name: str, *, write: bool = False):
        """Resolve + lock one filter, healing the lookup→evict race
        (ISSUE 14): a handler that resolved its ``_Managed`` before a
        concurrent eviction unpublished it would otherwise mutate
        detached device arrays the eviction blob missed — an acked
        write that silently vanishes. After acquiring the op lock the
        ``evicted`` flag is re-checked and a stale object re-resolves
        through the hydration path. ``write=True`` additionally
        re-checks the replica write fence UNDER the lock: a write that
        passed the wrapper's READONLY check but then waited out a
        hydration must not apply after a demotion flipped the role
        (the take-every-lock barrier only covers locks that exist)."""
        while True:
            mf = self._get(name)
            with mf.lock:
                if mf.evicted:
                    continue
                if write and self.read_only and not self._applying():
                    raise protocol.BloomServiceError(
                        "READONLY",
                        f"write to {name!r} rejected: this server became "
                        f"a read-only replica — send writes to the primary",
                        details=(
                            {"primary": self.primary_address}
                            if self.primary_address
                            else None
                        ),
                    )
                yield mf
                return

    def shed_hint(self) -> int:
        """Adaptive retry_after_ms for shed decisions taken OUTSIDE the
        admission gate (the storage tier's hydration quotas, ISSUE 14)
        — same pressure signal, same Health "shedding" window."""
        with self._admit_lock:
            self._last_shed_time = time.time()
            return self._bump_shed_pressure()

    # -- admission control (overload shedding + drain) -----------------------

    def admit(self, method: str) -> Optional[dict]:
        """Admission decision for one RPC, taken BEFORE the request is even
        decoded (a shed must cost microseconds, not a msgpack parse).

        Returns None when admitted — the caller MUST pair it with
        :meth:`release` — or a ready-to-encode error response when the
        request is shed (draining, or the in-flight cap is hit)."""
        if method in UNSHEDDABLE:
            return None
        with self._admit_lock:
            if self._draining:
                shed_code, shed_msg = "DRAINING", "server is draining"
            elif self.max_in_flight and self._in_flight >= self.max_in_flight:
                shed_code = "RESOURCE_EXHAUSTED"
                shed_msg = (
                    f"in-flight cap {self.max_in_flight} reached; retry with "
                    f"backoff"
                )
            else:
                self._in_flight += 1
                return None
            self._last_shed_time = time.time()
            retry_ms = self._bump_shed_pressure()
        self.metrics.count("requests_shed")
        # flight recorder (ISSUE 15): sheds are the first lifecycle
        # signal a post-mortem wants — noted outside the admit lock
        obs_flight.note(
            "shed", method=method, code=shed_code, retry_after_ms=retry_ms
        )
        return protocol.error_response(
            shed_code, shed_msg, details={"retry_after_ms": retry_ms}
        )

    def _bump_shed_pressure(self) -> int:
        """Adaptive retry hint (caller holds ``_admit_lock``): the first
        shed of a burst answers the configured base; each further shed
        while the pressure has not decayed grows the hint, so a thundering
        herd spreads itself out instead of re-colliding — with the
        in-flight cap pegged, the shed rate IS the queue-depth signal."""
        now = time.monotonic()
        self._shed_pressure *= math.exp(
            -(now - self._pressure_updated) / PRESSURE_DECAY_S
        )
        self._pressure_updated = now
        hint = self.retry_after_ms * (1.0 + self._shed_pressure)
        self._shed_pressure += 1.0
        hint = int(min(hint, self.retry_after_ms * RETRY_AFTER_CAP_FACTOR))
        obs_counters.set_gauge("retry_after_ms_current", hint)
        return hint

    def release(self, method: str) -> None:
        if method in UNSHEDDABLE:
            return
        with self._admit_lock:
            self._in_flight -= 1

    def begin_drain(self) -> None:
        """Stop admitting data-plane work (Health keeps answering, now
        reporting DRAINING); in-flight requests run to completion."""
        with self._admit_lock:
            self._draining = True

    # -- synchronous replication: commit barrier + Wait (ISSUE 5) ------------

    def commit_barrier(self, req: dict, resp: dict) -> dict:
        """Durability gate for one mutating RPC, run by the wrapper AFTER
        the handler returned (so no filter/registry lock is held while
        blocking). The quorum target is the server's
        ``min_replicas_to_write`` or the request's ``min_replicas``,
        whichever is STRONGER; 0 (the default) is a no-op.

        The write has already applied and its record is in the op log —
        ``resp["repl_seq"]`` names it. Block until the quorum acked that
        seq; on timeout raise ``NOT_ENOUGH_REPLICAS`` (Redis
        ``NOREPLICAS``) with ``details={acked, needed, seq, applied:
        True}``: the op is NOT rolled back (Redis WAIT semantics — the
        local apply stands), the caller just knows it is not yet
        quorum-durable. A retry under the same rid answers from the
        dedup cache / seq gates and RE-WAITS on the same record instead
        of double-applying."""
        needed = max(
            self.min_replicas_to_write, int(req.get("min_replicas") or 0)
        )
        if needed <= 0:
            return resp
        seq = resp.get("repl_seq")
        if seq is None:
            if self.oplog is None:
                # without an op log there is no record a replica could
                # ever ack — refuse loudly rather than return a
                # durability ack the topology cannot honor
                raise protocol.BloomServiceError(
                    "NOT_ENOUGH_REPLICAS",
                    f"min_replicas={needed} requires replication (start "
                    f"the server with --repl-log-dir)",
                    details={"acked": 0, "needed": needed, "applied": True},
                )
            # logged nothing because the call was a NO-OP (exist_ok
            # create of an existing filter, drop of a missing one):
            # there is no new record to make durable, so the quorum has
            # nothing to say about it
            return resp
        timeout_ms = req.get("min_replicas_timeout_ms")
        if timeout_ms is None:  # explicit 0 = probe: fail unless already acked
            # the lag budget doubles as the default wait budget — but a
            # budget of 0 means the freshness gate is DISABLED (Redis
            # min-replicas-max-lag 0), not "probe every write": fall
            # back to the stock budget so quorum writes still wait
            timeout_ms = (
                self.min_replicas_max_lag_ms or DEFAULT_MIN_REPLICAS_MAX_LAG_MS
            )
        timeout_ms = int(timeout_ms)
        connected = self.repl_sessions.count()
        if connected < needed:
            # Redis min-replicas-to-write parity: with fewer replicas
            # even CONNECTED than the quorum needs, waiting is futile —
            # fail fast so an isolated primary rejects writes in
            # microseconds, not after every barrier timeout
            self._quorum_failed(needed, 0)
            raise protocol.BloomServiceError(
                "NOT_ENOUGH_REPLICAS",
                f"durability quorum needs {needed} replica(s), only "
                f"{connected} connected",
                details={"acked": 0, "needed": needed, "seq": seq,
                         "connected": connected, "applied": True},
            )
        t0 = time.perf_counter()
        # freshness gate (ISSUE 6, Redis min-replicas-max-lag parity):
        # a replica only counts toward the quorum while its last ack
        # FRAME is within the lag budget — an acked-then-silent replica
        # is history, not durability. The barrier runs outside every
        # lock (note_blocking in wait_acked enforces that at runtime).
        max_age_s = self.min_replicas_max_lag_ms / 1000.0
        acked = self.repl_sessions.wait_acked(
            seq, needed, timeout_ms / 1000.0, require_connected=needed,
            max_age=max_age_s,
        )
        self.metrics.observe_wait(time.perf_counter() - t0)
        if acked < needed:
            self._quorum_failed(needed, acked)
            details = {"acked": acked, "needed": needed, "seq": seq,
                       "timeout_ms": timeout_ms, "applied": True}
            stale = self.repl_sessions.count_acked(seq) - acked
            if stale > 0:
                # the seq IS acked somewhere, just not freshly — name
                # the distinction so operators chase the silent replica,
                # not a replication gap
                self.metrics.count("quorum_stale_acks", stale)
                details["stale_acks"] = stale
            raise protocol.BloomServiceError(
                "NOT_ENOUGH_REPLICAS",
                f"only {acked}/{needed} replica(s) freshly acked seq {seq} "
                f"within {timeout_ms}ms",
                details=details,
            )
        self.metrics.count("quorum_writes_acked")
        resp["acked_replicas"] = acked
        return resp

    def _quorum_failed(self, needed: int, acked: int) -> None:
        self._last_quorum_fail_time = time.time()
        self.metrics.count("quorum_write_failures")
        log.warning(
            "commit barrier: %d/%d replica ack(s) — write applied "
            "locally but is not quorum-durable", acked, needed,
        )

    def Wait(self, req: dict) -> dict:
        """Redis ``WAIT numreplicas timeout`` parity: block until
        ``numreplicas`` replicas have acknowledged every record up to
        ``seq`` (the caller's last write — clients send the ``repl_seq``
        their last mutating response carried; default: the current log
        head), then answer ``{nreplicas}`` — the count actually acked,
        even when short of the target (WAIT reports, it does not
        error). ``numreplicas=0`` answers immediately with the current
        count — the cheap durability probe."""
        if self.read_only:
            raise protocol.BloomServiceError(
                "UNSUPPORTED",
                "WAIT is a primary-side command (this server is a "
                "replica)",
            )
        seq = req.get("seq")
        if seq is None:
            seq = self.oplog.last_seq if self.oplog is not None else 0
        numreplicas = int(req.get("numreplicas") or 0)
        timeout_ms = req.get("timeout_ms")
        if timeout_ms is None:
            timeout_ms = self.min_replicas_max_lag_ms
        timeout_ms = int(timeout_ms)
        timeout_s = (
            WAIT_TIMEOUT_CAP_S
            if timeout_ms <= 0  # Redis WAIT-0 "forever", capped
            else min(WAIT_TIMEOUT_CAP_S, timeout_ms / 1000.0)
        )
        t0 = time.perf_counter()
        acked = self.repl_sessions.wait_acked(int(seq), numreplicas, timeout_s)
        if numreplicas > 0:
            self.metrics.observe_wait(time.perf_counter() - t0)
        return {
            "ok": True,
            "nreplicas": acked,
            "seq": int(seq),
            "epoch": self.epoch,
        }

    # -- high availability: epoch + chained re-append (ISSUE 4) --------------

    def adopt_epoch(self, epoch: int) -> None:
        """Advance (never rewind) the topology epoch, persisting when a
        store is attached. Raft's term rule: whoever has seen the higher
        epoch is right about the topology."""
        if epoch <= self.epoch:
            return
        self.epoch = int(epoch)
        if self._epoch_store is not None:
            try:
                self._epoch_store.store(self.epoch)
            except OSError:
                log.exception("epoch persist failed (non-fatal)")
        obs_counters.set_gauge("ha_epoch", float(self.epoch))
        # keep the black box's epoch stamp current (ISSUE 16) — the
        # post-mortem timeline orders by epoch before wall clock
        obs_blackbox.set_node_meta(epoch=self.epoch)

    def reappend_record(self, rec: dict) -> None:
        """Chained replica: re-append one upstream record VERBATIM to the
        local op log (same seq space — what makes mid-chain promotion
        cheap and lets this node serve ``ReplStream`` downstream).
        Raises ValueError on a seq gap (caller full-resyncs)."""
        if self.oplog is None or self._replaying:
            return
        faults.fire("repl.reappend")
        if self.oplog.append_record(rec):
            obs_counters.incr("repl_records_reappended")
            # checkpoint-keyed truncation must run here too — on a
            # replica, _log_op (the primary-side sweep driver) never
            # fires, and an unswept chained log grows without bound
            self._appends_since_truncate += 1
            if self._appends_since_truncate >= TRUNCATE_EVERY_APPENDS:
                self._appends_since_truncate = 0
                self._maybe_truncate_log()

    def Promote(self, req: dict) -> dict:
        """Replica→primary promotion RPC (``REPLICAOF NO ONE`` parity):
        adopt the op log, bump+persist the topology epoch, start taking
        writes and serving ``ReplStream``. Idempotent on a primary;
        ``epoch`` (optional) pins the sentinel-agreed epoch and stale
        values are rejected with ``STALE_EPOCH``."""
        from tpubloom.ha import promotion

        return promotion.promote_to_primary(
            self,
            repl_log_dir=req.get("repl_log_dir"),
            epoch=req.get("epoch"),
        )

    def ReplicaOf(self, req: dict) -> dict:
        """Redis ``REPLICAOF`` parity: ``{"primary": "host:port"}``
        re-points (or demotes) this server to replicate from the given
        primary; ``primary`` absent/``"NO ONE"`` promotes instead.
        Epoch-stamped like Promote."""
        from tpubloom.ha import promotion

        primary = req.get("primary")
        if primary is None or (
            isinstance(primary, str)
            and primary.strip().upper() in ("", "NO ONE")
        ):
            return promotion.promote_to_primary(
                self,
                repl_log_dir=req.get("repl_log_dir"),
                epoch=req.get("epoch"),
            )
        return promotion.become_replica(self, primary, epoch=req.get("epoch"))

    # -- cluster mode: slot map, migration (ISSUE 9) -------------------------

    def _require_cluster(self):
        if self.cluster is None:
            raise protocol.BloomServiceError(
                "CLUSTER_DISABLED",
                "this server is not running in cluster mode (start it "
                "with --cluster)",
            )
        return self.cluster

    def ClusterSlots(self, req: dict) -> dict:
        """Redis ``CLUSTER SLOTS`` parity: the node's slot-map view —
        what cluster clients build their slot→shard cache from. A
        non-cluster server answers ``enabled: false`` so mixed fleets
        stay probeable."""
        if self.cluster is None:
            return {"ok": True, "enabled": False, "epoch": 0, "ranges": []}
        return {"ok": True, "enabled": True, **self.cluster.describe()}

    def ClusterSetSlot(self, req: dict) -> dict:
        """Redis ``CLUSTER SETSLOT`` parity plus the bulk ``assign``
        form (see :meth:`tpubloom.cluster.ClusterState.set_slot`)."""
        return self._require_cluster().set_slot(req)

    def MigrateSlot(self, req: dict) -> dict:
        """Drive the live migration of one slot to ``target`` (source
        side; synchronous like Redis ``MIGRATE``)."""
        self._require_cluster()
        if self.read_only:
            raise protocol.BloomServiceError(
                "READONLY", "MigrateSlot must run on the shard primary"
            )
        return cluster_migrate.migrate_slot(
            self, int(req["slot"]), req.get("target")
        )

    def MigrateInstall(self, req: dict) -> dict:
        """Target side of a slot migration: adopt one filter's snapshot
        blob for an importing slot (or answer a resume probe). The
        ``src_seq`` stamp seeds the exactly-once import gate the
        dual-write forwards are checked against."""
        cluster = self._require_cluster()
        if self.read_only:
            raise protocol.BloomServiceError(
                "READONLY", "MigrateInstall must run on the shard primary"
            )
        faults.fire("cluster.migrate_apply")
        name = req["name"]
        slot = cluster_slots.key_slot(name)
        if not cluster.is_importing(slot):
            raise protocol.BloomServiceError(
                "NOT_IMPORTING",
                f"slot {slot} is not importing on this node — mark it "
                f"with ClusterSetSlot first",
                details={"slot": slot},
            )
        if req.get("probe"):
            base = cluster.gate_base(name)
            have = base if (name in self._filters and base is not None) else None
            return {"ok": True, "have": have}
        src_seq = int(req["src_seq"])
        self.install_migrated(name, req["blob"])
        cluster.seed_gate(name, src_seq)
        self.metrics.count("cluster_migrate_installs")
        return {"ok": True, "name": name, "src_seq": src_seq}

    def install_migrated(self, name: str, blob: bytes) -> None:
        """Adopt a migrating filter's snapshot on the new owner. Unlike
        the replica-side :meth:`install_snapshot`, this runs on a
        PRIMARY: the create is op-logged with a ``restored_seq`` marker
        — this shard's replicas cannot rebuild the blob's bytes from
        records, so applying that record full-resyncs them (the PR-3
        machinery), which carries the installed state."""
        mf = self._managed_from_blob(blob)
        create_req = self._manifest_req_for(name, mf.filter)
        with self._lock:
            old = self._filters.pop(name, None)
            # log BEFORE publishing (same rule as CreateFilter): a
            # concurrent forward on the new filter must not log below
            # the create record's seq
            self._log_op(
                "CreateFilter",
                {**create_req, "exist_ok": True, "restored_seq": -1},
                mf,
                may_truncate=False,
            )
            self._filters[name] = mf
            self._manifest_put(name, create_req)
        if old is not None and old.checkpointer:
            old.checkpointer.close(final_checkpoint=False)
        if mf.checkpointer:
            # seed a durable generation NOW: this node's restart replay
            # can only rebuild the filter from a local checkpoint — the
            # blob's bytes exist in no record stream
            with mf.lock:
                mf.checkpointer.trigger()
        if self.storage is not None:
            self.storage.note_created(name)
            self.storage.ensure_budget()

    # -- replication: op log, apply, snapshots (ISSUE 3) ---------------------

    def _log_op(
        self,
        method: str,
        req: dict,
        mf: Optional[_Managed] = None,
        *,
        may_truncate: bool = True,
    ) -> Optional[int]:
        """Append one committed mutating op to the op log (no-op without
        a log, during replay, and on replicas — a chained replica's log
        is fed by :meth:`reappend_record`, which preserves the upstream
        seq space; handler-side appends would mint conflicting seqs).
        MUST be called while still holding the lock the op committed
        under — log order is apply order. ``may_truncate=False`` for
        callers holding ``self._lock`` (Create/Drop): the truncation
        sweep re-takes it and the lock is not re-entrant — their sweep
        runs on a later data-plane append. Returns the record's seq
        (``None`` when nothing was logged) — what the commit barrier
        blocks on and what mutating responses echo as ``repl_seq``."""
        if self.oplog is None or self._replaying or self._stream_fed:
            hint = getattr(self._apply_seq_hint, "seq", None)
            if mf is not None and hint is not None:
                # apply path (replay / stream apply): advance the
                # filter's seq stamp HERE, under the op lock the commit
                # runs under — a checkpoint triggered by this record's
                # own notify_inserts must stamp it, and an eviction
                # serialized after this lock section snapshots state
                # that truly CONTAINS the record. (Review fix, ISSUE
                # 14: apply_record's old lock-free pre-advance let a
                # concurrent eviction stamp a seq whose effect was
                # absent — a SIGKILL after that checkpoint landed
                # would gate the record out of replay: acked write
                # durably lost.)
                mf.applied_seq = max(mf.applied_seq, hint)
            return None
        tref = obs_trace.request_ref()
        if tref is not None:
            # trace propagation through the log (ISSUE 15): replicas
            # and migration tail-replays capture this record's apply
            # regardless of their own sample rate, parented under the
            # committing request's (or flush's) root span. Handlers
            # ignore the extra key on replay; the copy keeps the
            # caller's dict untouched.
            req = {**req, "trace": {"forced": True, "span": tref[1]}}
        try:
            seq = self.oplog.append(method, req, rid=obs.current_rid())
        except Exception as e:
            # the op ALREADY applied in memory: this process is now ahead
            # of its own log. Fail-stop further writes (reads keep
            # serving) — silently continuing would diverge replicas and
            # crash-replay state with no signal.
            self.oplog_error = repr(e)
            obs_counters.incr("repl_log_append_errors")
            log.exception(
                "op log append failed for %s — write path fail-stopped",
                method,
            )
            # the "fatal" flight-recorder case (ISSUE 15): the process
            # is about to stop accepting writes — dump the lifecycle
            # ring NOW, best-effort (note touches only the declared
            # filter.op -> obs.counters edge; the dump's file IO is
            # acceptable here — this path already does log IO under
            # the same lock, and it runs once, on the way down)
            obs_flight.note("oplog_failstop", method=method, error=repr(e))
            obs_flight.dump("fatal")
            # msync the black box too (ISSUE 16): SIGKILL-safety needs
            # nothing, but a fail-stop may precede a machine going down
            obs_blackbox.sync()
            # and freeze the rings (ISSUE 19 satellite): the ring is an
            # overwrite buffer — if the process limps on serving reads,
            # healthy traffic would lap the lead-up to the fail-stop
            obs_blackbox.snapshot_rings("oplog-failstop")
            raise
        if mf is not None:
            mf.applied_seq = seq
        self._appends_since_truncate += 1
        if may_truncate and self._appends_since_truncate >= TRUNCATE_EVERY_APPENDS:
            self._appends_since_truncate = 0
            self._maybe_truncate_log()
        return seq

    def _maybe_truncate_log(self) -> None:
        """Checkpoint-keyed log GC: records every filter's newest LANDED
        checkpoint already covers are replayable from checkpoints alone
        and can go — bounded by the slowest connected replica's cursor so
        a live stream never loses its tail (backlog parity)."""
        oplog = self.oplog
        if oplog is None:
            return
        with self._lock:
            mfs = list(self._filters.values())
        safe = oplog.last_seq  # no filters: empty state replays from nothing
        for mf in mfs:
            if mf.checkpointer is None:
                return  # unpersisted filter: its whole history must stay
            meta = mf.checkpointer.last_landed_meta
            if meta is None:
                return  # nothing landed yet for this filter
            safe = min(safe, int(meta.get("repl_seq") or 0))
        if self.storage is not None:
            # paged tenants (ISSUE 14) bound GC exactly like resident
            # ones: a WARM/COLD tenant's records past its durable
            # checkpoint must survive a SIGKILL (its host-RAM blob does
            # not), and one with NO durable generation pins the whole
            # log — the same rule as an unpersisted resident filter
            paged_floor = self.storage.truncate_floor()
            if paged_floor is None:
                return
            safe = min(safe, paged_floor)
        replica_floor = self.repl_sessions.min_cursor()
        if replica_floor is not None:
            safe = min(safe, replica_floor)
        if oplog.truncate_to(safe):
            self.metrics.count("repl_log_truncations")

    def apply_record(self, rec: dict) -> bool:
        """Apply one op-log record (startup replay on a primary, stream
        apply on a replica); True iff it changed state, False when the
        per-filter seq gate proved the effect already present. Exactly
        the idempotence the acceptance test pins: killing a stream
        mid-batch and replaying the records cannot double-apply."""
        faults.fire("repl.apply")
        method, seq = rec["method"], rec["seq"]
        req = dict(rec["req"])
        if rec.get("rid"):
            req["rid"] = rec["rid"]
        name = req.get("name")
        if method == "CreateFilter":
            restored_seq = req.pop("restored_seq", None)
            mf = self._filters.get(name)
            if mf is not None and mf.applied_seq >= seq:
                return False
            if self.read_only:
                if restored_seq is not None:
                    # the primary bootstrapped this filter from a
                    # checkpoint generation the replica does not have —
                    # no sequence of records reproduces those bytes
                    raise FullResyncNeeded(name)
                # a FRESH create on the primary must be fresh here too:
                # restore-on-create would resurrect the replica's own
                # stale local checkpoint of a previous same-name filter
                req["restore"] = False
            self.CreateFilter({**req, "exist_ok": True})
            mf = self._filters.get(name)
            if mf is not None:
                mf.applied_seq = max(mf.applied_seq, seq)
            return True
        if method == "DropFilter":
            # hydrate-first (ISSUE 14): the NEWER-than-this-drop seq
            # gate below must judge the real filter, not skip because
            # the tenant happens to be paged out
            mf = self._resident(name)
            if mf is not None and mf.applied_seq >= seq:
                # the live filter is NEWER than this drop (a full-resync
                # snapshot installed the re-created filter): dropping it
                # would delete state the later records cannot rebuild
                return False
            return bool(self.DropFilter(req).get("existed"))
        # storage-aware lookup (ISSUE 14): a record for an EVICTED
        # tenant hydrates it first — on a replica, stream apply must
        # land on the real state, not skip as "unknown filter"
        mf = self._resident(name)
        if mf is None:
            log.warning(
                "op-log record seq %d (%s) names unknown filter %r; skipped",
                seq, method, name,
            )
            return False
        if mf.applied_seq >= seq:
            return False
        # the seq stamp advances inside the handler's _log_op call,
        # UNDER the op lock (see there) — before notify_inserts, so a
        # checkpoint the handler triggers stamps THIS record's seq, and
        # an eviction serialized against the same lock can never
        # snapshot the stamp before the record's effect is applied
        prev = mf.applied_seq
        self._apply_seq_hint.seq = seq
        try:
            getattr(self, method)(req)
        except Exception:
            mf.applied_seq = prev
            raise
        finally:
            self._apply_seq_hint.seq = None
        # exactly-once across restarts for COALESCED replay-unsafe
        # writes (ISSUE 18): a merged record logs under the FLUSH rid,
        # so replaying it used to leave the parked requests' own rids
        # out of the dedup cache — a client re-driving an applied-but-
        # unacked frame after a crash would double-apply. The record's
        # ``parts`` name each constituent; re-seed one cached response
        # per part so a same-rid replay answers from cache. (On a
        # promoted replica this protects post-failover re-drives too.)
        for part in req.get("parts") or ():
            try:
                part_rid, part_n = part[0], int(part[1])
            except (TypeError, ValueError, IndexError):
                continue
            if part_rid:
                self._dedup_put(
                    part_rid, {"ok": True, "n": part_n, "repl_seq": seq}
                )
        return True

    def replay_oplog(self) -> dict:
        """Startup replay (primary with ``--repl-log-dir``): re-drive
        every logged op over the checkpoint-restored state. The
        per-filter ``repl_seq`` gates make this idempotent — AOF parity:
        acked writes newer than the last checkpoint come back."""
        if self.oplog is None:
            return {"applied": 0, "skipped": 0, "failed": 0}
        applied = skipped = failed = 0
        restored_from_manifest = 0
        self._replaying = True
        try:
            # manifest first: filters whose CreateFilter record was
            # truncated away (covered by a landed checkpoint) come back
            # via restore-on-create before the record tail replays
            for name, create_req in (self._manifest_read() or {}).items():
                try:
                    self.CreateFilter(
                        {**create_req, "exist_ok": True, "restore": True}
                    )
                    restored_from_manifest += 1
                except Exception:
                    log.exception(
                        "op-log manifest: re-creating filter %r failed", name
                    )
                    failed += 1
            for rec in self.oplog.read_from(0):
                try:
                    if self.apply_record(rec):
                        applied += 1
                    else:
                        skipped += 1
                except Exception:
                    log.exception(
                        "op-log replay: record seq %d (%s) failed",
                        rec.get("seq"), rec.get("method"),
                    )
                    failed += 1
        finally:
            self._replaying = False
        if self.storage is not None:
            # replay forced every manifest tenant resident (records can
            # only apply to live filters); page back down to the HBM
            # budget ONCE now instead of thrashing per record
            self.storage.ensure_budget()
        self.metrics.count("repl_replay_applied", applied)
        return {
            "applied": applied,
            "skipped": skipped,
            "failed": failed,
            "restored_from_manifest": restored_from_manifest,
        }

    def snapshot_plan(self):
        """Full-resync payload: ``(names, iterator, plan_seq)`` from ONE
        registry snapshot — the iterator lazily yields ``(name, blob,
        applied_seq)`` per filter, each snapshot taken under its op lock
        so the blob and its seq stamp are consistent. Lazy on purpose: a
        blob can be filter-sized, so only one is in flight at a time
        (the stream sends it before the next is built).

        ``plan_seq`` is the log head read under the registry lock —
        creates commit (log + publish) under that same lock, so every
        record for a filter OUTSIDE ``names`` has ``seq > plan_seq``.
        The resync tail cursor must be clamped to it: per-filter
        ``applied_seq`` stamps taken later can run ahead of the plan and
        would otherwise skip those creates."""
        with self._lock:
            items = list(self._filters.items())
            plan_seq = self.oplog.last_seq if self.oplog is not None else 0
        # paged tenants (ISSUE 14) stream too — a bootstrapping replica
        # must receive the WHOLE tenant set, and paging them in just to
        # stream them out would churn the hot set; their loaders answer
        # from the warm pool / the sink at send time
        paged = (
            self.storage.paged_plan_items(exclude={n for n, _ in items})
            if self.storage is not None
            else []
        )

        def gen():
            for name, mf in items:
                with mf.lock:
                    # an mf evicted between plan and send still works:
                    # the object is a consistent snapshot of its state
                    # at eviction, and every later record streams from
                    # the log tail — same story as any other filter
                    _, _, blob = ckpt.snapshot_blob(mf.filter)
                    applied_seq = mf.applied_seq
                yield name, blob, applied_seq
            for name, load in paged:
                blob, applied_seq = load()
                yield name, blob, applied_seq

        names = [name for name, _ in items] + [name for name, _ in paged]
        return names, gen(), plan_seq

    def install_snapshot(self, name: str, blob: bytes, applied_seq: int) -> None:
        """Replica bootstrap: adopt a primary's filter snapshot wholesale
        (config comes from the blob header — the primary's config IS the
        truth), replacing any local filter of that name."""
        mf = self._managed_from_blob(blob, applied_seq)
        with self._lock:
            old = self._filters.pop(name, None)
            self._filters[name] = mf
            # a replica with durable state (cursor-persistence satellite)
            # must be able to restore this filter at restart too
            self._manifest_put(name, self._manifest_req_for(name, mf.filter))
        if old is not None and old.checkpointer:
            old.checkpointer.close(final_checkpoint=False)
        if self.storage is not None:
            self.storage.note_created(name)
            self.storage.ensure_budget()
        self.metrics.count("repl_snapshots_installed")

    def retain_only(self, names) -> None:
        """Post-full-resync: a resync is a state reset, so filters the
        primary no longer has must go (their checkpoints stay in the
        local sink untouched)."""
        keep = set(names)
        with self._lock:
            victims = [
                (n, mf) for n, mf in self._filters.items() if n not in keep
            ]
            for n, _ in victims:
                del self._filters[n]
                self._manifest_remove(n)
        for n, mf in victims:
            if mf.checkpointer:
                mf.checkpointer.close(final_checkpoint=False)
        if self.storage is not None:
            # paged tenants the primary no longer has must go too
            self.storage.retain_only(names)

    # -- storage tier: hydration builders (ISSUE 14) -------------------------

    def _config_of(self, create_req: dict) -> FilterConfig:
        """The (base) FilterConfig a manifest-shaped create request
        describes — what the storage tier keys sinks by."""
        req = dict(create_req)
        name = req["name"]
        if req.get("scalable"):
            base, _ = self._parse_scalable(req, name)
            return base
        return self._parse_config(req, name)

    def _managed_from_blob(self, blob: bytes, applied_seq=0) -> _Managed:
        """Rebuild a ``_Managed`` from one snapshot blob — the blob's
        stored config is the truth. The single recipe behind WARM
        hydration (ISSUE 14), replica snapshot installs, and migration
        installs."""
        filt = ckpt.restore_blob(blob)
        config = filt.base_config if hasattr(filt, "layers") else filt.config
        sink = self._sink_factory(config)
        mf = _Managed(filt, sink, getattr(config, "checkpoint_every", 0))
        mf.applied_seq = int(applied_seq or 0)
        return mf

    def _managed_from_sink(self, name: str, create_req) -> _Managed:
        """COLD hydration: restore the newest durable checkpoint
        generation (the eviction path landed one stamped at the evicted
        ``applied_seq``, so no op-log tail needs replaying here — every
        later write hydrated first by construction)."""
        req = dict(create_req or {})
        req["name"] = name
        if req.get("scalable"):
            base, policy = self._parse_scalable(req, name)
            sink = self._sink_factory(base)
            restored = (
                self._tracked_restore(
                    name, base, sink,
                    scalable_expect=policy, expect_scalable=True,
                )
                if sink is not None
                else None
            )
            config = base
        else:
            config = self._parse_config(req, name)
            sink = self._sink_factory(config)
            restored = (
                self._tracked_restore(name, config, sink, expect_scalable=False)
                if sink is not None
                else None
            )
        if restored is None:
            raise protocol.BloomServiceError(
                "INTERNAL",
                f"cold tenant {name!r} has no restorable checkpoint "
                f"generation — hydration impossible (durable tier lost?)",
            )
        mf = _Managed(restored, sink, config.checkpoint_every)
        mf.applied_seq = int(
            getattr(restored, "_restored_meta", {}).get("repl_seq", 0) or 0
        )
        return mf

    # -- RPC handlers (dict in, dict out) ------------------------------------

    def _health_reasons(self) -> list:
        """Machine-readable degraded reasons (empty = healthy)."""
        reasons = []
        with self._lock:
            filters = list(self._filters.items())
        for name, mf in filters:
            if mf.checkpointer is None:
                self._ckpt_corrupt_seen.pop(name, None)
                continue
            if mf.checkpointer.last_error is not None:
                reasons.append(f"checkpoint_error:{name}")
            seen = self._ckpt_corrupt_seen.get(name)
            if seen is not None:
                landed = mf.checkpointer.last_checkpoint_time
                if landed is not None and landed > seen:
                    # a good generation has been written since the corrupt
                    # one was quarantined — the degradation is over
                    self._ckpt_corrupt_seen.pop(name, None)
                else:
                    reasons.append(f"checkpoint_corrupt:{name}")
        if time.time() - self._last_shed_time < SHED_DEGRADED_WINDOW_S:
            reasons.append("shedding")
        if self.min_replicas_to_write > 0:
            connected = self.repl_sessions.count()
            if connected < self.min_replicas_to_write:
                # an isolated primary under min-replicas-to-write is
                # refusing writes RIGHT NOW — the operator must see why
                reasons.append(
                    f"min_replicas:{connected}/{self.min_replicas_to_write}"
                )
        if time.time() - self._last_quorum_fail_time < SHED_DEGRADED_WINDOW_S:
            reasons.append("not_enough_replicas")
        ra = self.replica_applier
        if ra is not None and ra.link not in ("connected", "syncing"):
            # a replica serving reads off a dead link is serving stale
            # data — say so, machine-readably
            reasons.append(f"replication_link:{ra.link}")
        if self.oplog_error is not None:
            reasons.append("oplog_append_error")
        return reasons

    def Health(self, req: dict) -> dict:
        import jax

        reasons = self._health_reasons()
        if self._draining:
            status = "DRAINING"
        elif reasons:
            status = "DEGRADED"
        else:
            status = "SERVING"
        # flight recorder (ISSUE 15): health flips are lifecycle
        # events, and the SERVING -> DEGRADED flip is one of the
        # moments a post-mortem needs the ring ON DISK — the process
        # may be about to get killed by its orchestrator. The flip
        # check-and-set runs under the admit lock (taken right below
        # anyway) so concurrent Health probes agree on ONE flip — one
        # note, one dump; the note/dump themselves run outside it.
        with self._admit_lock:
            in_flight = self._in_flight
            prev = self._last_health_status
            flipped = status != prev
            self._last_health_status = status
        if flipped:
            obs_flight.note(
                "health", status=status, previous=prev,
                reasons=list(reasons),
            )
            if status == "DEGRADED":
                obs_flight.dump("degraded")
                obs_blackbox.sync()
                # snapshot the rings too (ISSUE 18 satellite): the live
                # rings keep overwriting oldest-first, so the history
                # LEADING UP to this incident would be gone by the time
                # anyone looks — freeze a copy next to them (bounded)
                obs_blackbox.snapshot_rings("degraded")
        resp = {
            "ok": True,
            "status": status,
            "reasons": reasons,
            "backend": jax.default_backend(),
            "devices": [str(d) for d in jax.devices()],
            "filters": len(self._filters),
            "in_flight": in_flight,
            "max_in_flight": self.max_in_flight,
            "role": "replica" if self.read_only else "primary",
            "epoch": self.epoch,
            # wire-encoding capability advertisement (ISSUE 10): clients
            # negotiate the zero-copy `fixed` key encoding off this
            "encodings": list(protocol.ENCODINGS),
        }
        if self.listen_address:
            resp["listen"] = self.listen_address
        if self.storage is not None:
            resp["storage"] = self.storage.summary()
        if self.cluster is not None:
            resp["cluster"] = self.cluster.summary()
        if self.replica_applier is not None and self.read_only:
            resp["replication"] = self.replica_applier.status()
            if self.oplog is not None:  # chained: serves downstream too
                resp["replication"]["log"] = self.oplog.stats()
                resp["replication"]["replicas"] = (
                    self.repl_sessions.describe()
                )
        elif self.oplog is not None:
            resp["replication"] = {
                "log": self.oplog.stats(),
                "replicas": self.repl_sessions.describe(),
            }
        return resp

    @staticmethod
    def _parse_config(req: dict, name: str) -> FilterConfig:
        if "config" in req:
            return FilterConfig.from_dict({**req["config"], "key_name": name})
        return FilterConfig.from_capacity(
            req["capacity"], req["error_rate"], key_name=name,
            **req.get("options", {}),
        )

    @staticmethod
    def _parse_scalable(req: dict, name: str):
        """``req["scalable"]`` (truthy; optionally ``{"growth", "tightening"}``)
        -> (base template FilterConfig, growth-policy dict)."""
        sc = req.get("scalable")
        sc = sc if isinstance(sc, dict) else {}
        if req.get("capacity") is None or req.get("error_rate") is None:
            raise protocol.BloomServiceError(
                "INVALID_ARGUMENT",
                "scalable filters are sized by capacity + error_rate",
            )
        opts = dict(req.get("options", {}))
        # template m is a placeholder (layers derive their own) but must
        # satisfy config validation for blocked layouts
        m0 = max(64, int(opts.get("block_bits") or 0))
        base = FilterConfig(m=m0, k=1, key_name=name, **opts)
        policy = {
            "capacity": int(req["capacity"]),
            "error_rate": float(req["error_rate"]),
            "growth": int(sc.get("growth", 2)),
            "tightening": float(sc.get("tightening", 0.5)),
        }
        return base, policy

    @staticmethod
    def _policy_of(filt) -> dict:
        """Growth-policy dict of a live scalable filter (response echo +
        exist_ok comparison)."""
        return {
            "capacity": filt.capacity,
            "error_rate": filt.error_rate,
            "growth": filt.growth,
            "tightening": filt.tightening,
        }

    def _tracked_restore(self, name: str, config, sink, **kwargs):
        """checkpoint.restore, but remember when the walk had to skip
        corrupt generations for this filter — Health reports the filter
        DEGRADED until a good checkpoint lands after that moment."""
        before = obs_counters.get("ckpt_corrupt_detected")
        restored = ckpt.restore(config, sink, **kwargs)
        if obs_counters.get("ckpt_corrupt_detected") > before:
            self._ckpt_corrupt_seen[name] = time.time()
            self.metrics.count("restores_with_corrupt_generations")
        return restored

    def CreateFilter(self, req: dict) -> dict:  # lint: allow(replay-safety): replay converges on state (a retried create finds the filter registered and never double-builds); exist_ok attaches idempotently, a bare-create retry answers EXISTS — loud, not corrupting. No per-request device state to cache
        for _ in range(4):
            if self.storage is not None:
                # page a WARM/COLD tenant in FIRST (ISSUE 14): exist_ok
                # attaches and config-mismatch checks must compare
                # against the real filter — a bare-create over paged
                # state would otherwise silently rebuild it empty
                self.storage.resolve(req["name"], control_plane=True)
            try:
                resp = self._create(req)
            except _TenantPagedRace:
                continue  # evicted between hydrate and registry lock
            if self.storage is not None and resp.get("ok"):
                self.storage.note_created(req["name"])
                self.storage.ensure_budget()
            return resp
        raise protocol.BloomServiceError(
            "INTERNAL",
            f"create of {req['name']!r} kept racing evictions — retry",
        )

    def _create(self, req: dict) -> dict:
        name = req["name"]
        want_scalable = bool(req.get("scalable"))
        with self._lock:
            if name in self._filters:
                existing_filt = self._filters[name].filter
                existing = existing_filt.config
                existing_scalable = hasattr(existing_filt, "layers")
                if req.get("exist_ok", False):
                    # Attaching to an existing filter must mean the SAME
                    # filter — a silent mismatch would e.g. pour 1e8 keys
                    # into a 1e3-capacity array while the caller believes
                    # it requested 1% FPR. A bare attach (no config/capacity
                    # given) adopts the existing config as-is.
                    has_params = "config" in req or req.get("capacity") is not None
                    if (want_scalable or has_params) and (
                        want_scalable != existing_scalable
                    ):
                        raise protocol.BloomServiceError(
                            "CONFIG_MISMATCH",
                            f"filter {name!r} exists as "
                            f"{'scalable' if existing_scalable else 'fixed-size'}, "
                            f"requested {'scalable' if want_scalable else 'fixed-size'}",
                        )
                    if want_scalable:
                        # verify every parameter the request actually
                        # carries (a bare attach carries none; the stock
                        # client always transmits growth/tightening, so
                        # a changed default is caught even w/o capacity)
                        sc = req.get("scalable")
                        sc = sc if isinstance(sc, dict) else {}
                        requested = {}
                        if req.get("capacity") is not None:
                            requested["capacity"] = int(req["capacity"])
                        if req.get("error_rate") is not None:
                            requested["error_rate"] = float(req["error_rate"])
                        if "growth" in sc:
                            requested["growth"] = int(sc["growth"])
                        if "tightening" in sc:
                            requested["tightening"] = float(sc["tightening"])
                        live = self._policy_of(existing_filt)
                        field = next(
                            (f for f, v in requested.items() if live[f] != v),
                            None,
                        )
                        if field is None and req.get("options"):
                            opts = dict(req["options"])
                            m0 = max(64, int(opts.get("block_bits") or 0))
                            base = FilterConfig(m=m0, k=1, key_name=name, **opts)
                            field = identity_mismatch(
                                existing, base,
                                ckpt.IDENTITY_FIELDS_SCALABLE + ("key_len",),
                            )
                        if field is not None:
                            raise protocol.BloomServiceError(
                                "CONFIG_MISMATCH",
                                f"scalable filter {name!r} exists with a "
                                f"different {field}",
                            )
                    elif has_params:
                        config = self._parse_config(req, name)
                        field = identity_mismatch(
                            existing, config, IDENTITY_FIELDS + ("key_len",)
                        )
                        if field is not None:
                            raise protocol.BloomServiceError(
                                "CONFIG_MISMATCH",
                                f"filter {name!r} exists with {field}="
                                f"{getattr(existing, field)}, requested "
                                f"{getattr(config, field)}",
                            )
                    resp = {
                        "ok": True,
                        "existed": True,
                        "config": existing.to_dict(),
                    }
                    if existing_scalable:
                        resp["scalable"] = self._policy_of(existing_filt)
                    return resp
                raise protocol.BloomServiceError(
                    "ALREADY_EXISTS", f"filter {name!r} exists"
                )
            if self.storage is not None and self.storage.has(name):
                # not in the registry, but the storage tier KNOWS the
                # tenant: it was evicted between the caller's hydrate
                # and this lock — never rebuild fresh over paged state
                raise _TenantPagedRace(name)
            if want_scalable:
                return self._create_scalable(req, name)
            config = self._parse_config(req, name)
            sink = self._sink_factory(config)
            restored = None
            if sink is not None and req.get("restore", True):
                try:
                    restored = self._tracked_restore(  # lint: allow(blocking-under-lock): create/drop commit points must serialize under the registry lock, and restore-on-create IS this create's commit; creates are control-plane-rare
                        name, config, sink, expect_scalable=False
                    )
                except ValueError as e:
                    raise protocol.BloomServiceError("CKPT_MISMATCH", str(e))
            if restored is not None:
                filt = restored
            elif sketch_registry.is_sketch(config):
                # sketch kinds (ISSUE 19) construct through the kind
                # registry — the same factory checkpoint._build_filter
                # restores through, so the two can never diverge
                filt = sketch_registry.build(config)
            elif config.shards > 1:
                # handles flat/blocked x plain/counting layouts (the same
                # routing order as checkpoint.restore — the two MUST agree
                # or a restart would reinterpret checkpoint bytes under a
                # different position spec)
                from tpubloom.parallel.sharded import ShardedBloomFilter

                filt = ShardedBloomFilter(config)
            elif config.counting and config.block_bits:
                from tpubloom.filter import BlockedCountingBloomFilter

                filt = BlockedCountingBloomFilter(config)
            elif config.counting:
                filt = CountingBloomFilter(config)
            elif config.block_bits:
                from tpubloom.filter import BlockedBloomFilter

                filt = BlockedBloomFilter(config)
            else:
                filt = BloomFilter(config)
            mf = _Managed(filt, sink, config.checkpoint_every)
            mf.applied_seq = int(
                getattr(filt, "_restored_meta", {}).get("repl_seq", 0) or 0
            )
            # log BEFORE publishing: _get reads _filters lock-free, so a
            # concurrent insert on the new filter must not be able to log
            # a seq below the create record's
            seq = self._log_create(req, mf, restored)
            self._filters[name] = mf
            self.metrics.count("filters_created")
            resp = {
                "ok": True,
                "existed": False,
                "restored_seq": getattr(filt, "_restored_seq", None),
                "config": config.to_dict(),
            }
            if seq is not None:
                resp["repl_seq"] = seq
            return resp

    def _log_create(self, req: dict, mf: _Managed, restored) -> Optional[int]:
        """Op-log a landed CreateFilter (+ the creation manifest). A
        create that bootstrapped state from a checkpoint is stamped
        ``restored_seq`` — replicas cannot reproduce those bytes from
        records, so applying such a record triggers a full resync (the
        snapshot carries the state)."""
        logged = {k: v for k, v in req.items()
                  if k not in ("rid", "min_replicas",
                               "min_replicas_timeout_ms",
                               "asking", "src_seq", "epoch")}
        if restored is not None:
            logged["restored_seq"] = getattr(restored, "_restored_seq", None)
        seq = self._log_op("CreateFilter", logged, mf, may_truncate=False)
        self._manifest_put(req["name"], {k: v for k, v in logged.items()
                                         if k != "restored_seq"})
        return seq

    # -- creation manifest ---------------------------------------------------
    #
    # Checkpoint-keyed truncation may drop a live filter's CreateFilter
    # record while newer records for it remain in the log (the create is
    # covered by a landed checkpoint; the tail is not). Replay would then
    # skip those records as "unknown filter" — losing acked writes. The
    # manifest is the durable live-filter set next to the log: replay
    # re-creates (restore=True, pulling the covering checkpoint) from it
    # FIRST, then drives the record tail over that.

    def _manifest_path(self) -> Optional[str]:
        if self._manifest_dir is None:
            return None
        import os

        return os.path.join(self._manifest_dir, "manifest.json")

    @staticmethod
    def _manifest_req_for(name: str, filt) -> dict:
        """Reconstruct a CreateFilter request from a LIVE filter — for
        manifest entries with no original request at hand (snapshot-
        installed filters on replicas, manifest rebuild at promotion)."""
        if hasattr(filt, "layers"):  # scalable
            base = filt.base_config.to_dict()
            opts = {
                k: v for k, v in base.items() if k not in ("m", "k", "key_name")
            }
            return {
                "name": name,
                "capacity": filt.capacity,
                "error_rate": filt.error_rate,
                "options": opts,
                "scalable": {
                    "growth": filt.growth,
                    "tightening": filt.tightening,
                },
            }
        return {"name": name, "config": filt.config.to_dict()}

    def rebuild_manifest(self) -> None:
        """Rewrite the creation manifest from the live filter set — a
        promotion that opened a FRESH log dir must seed it with the
        filters the replica already holds, or a later restart's replay
        would not know to restore them."""

        def mutate(manifest: dict) -> None:
            manifest.clear()
            with self._lock:
                items = list(self._filters.items())
            for name, mf in items:
                manifest[name] = self._manifest_req_for(name, mf.filter)
            if self.storage is not None:
                # paged tenants exist too (ISSUE 14): a promotion that
                # dropped them from the manifest would lose them at the
                # next restart's replay
                for name, req in self.storage.create_reqs().items():
                    manifest.setdefault(name, req)

        self._manifest_write(mutate)

    def _manifest_put(self, name: str, create_req: dict) -> None:
        self._manifest_write(lambda m: m.__setitem__(name, create_req))

    def _manifest_remove(self, name: str) -> None:
        self._manifest_write(lambda m: m.pop(name, None))

    def _manifest_write(self, mutate) -> None:
        """Read-mutate-write the manifest atomically (callers hold
        ``self._lock``, which serializes create/drop commit points)."""
        path = self._manifest_path()
        if path is None or self._replaying:
            return
        import json
        import os

        try:
            manifest = self._manifest_read() or {}
            mutate(manifest)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(manifest, f)
            os.replace(tmp, path)
        except Exception:
            log.exception("op-log creation manifest write failed")

    def _manifest_read(self) -> Optional[dict]:
        path = self._manifest_path()
        if path is None:
            return None
        import json
        import os

        if not os.path.exists(path):
            return None
        try:
            with open(path) as f:
                return json.load(f)
        except Exception:
            log.exception("op-log creation manifest unreadable; ignoring")
            return None

    def _create_scalable(self, req: dict, name: str) -> dict:
        """Scalable-filter CreateFilter branch (caller holds self._lock).

        Parity: the scalable/layered filter is the reference's Lua-lineage
        capability (SURVEY.md §2.3); serving + restore-on-create makes it a
        first-class server citizen like the fixed-size variants."""
        from tpubloom.scalable import ScalableBloomFilter

        base, policy = self._parse_scalable(req, name)
        sink = self._sink_factory(base)
        restored = None
        if sink is not None and req.get("restore", True):
            try:
                restored = self._tracked_restore(
                    name, base, sink,
                    scalable_expect=policy, expect_scalable=True,
                )
            except ValueError as e:
                raise protocol.BloomServiceError("CKPT_MISMATCH", str(e))
        if restored is not None:
            filt = restored
        else:
            filt = ScalableBloomFilter(
                policy["capacity"],
                policy["error_rate"],
                config=base,
                growth=policy["growth"],
                tightening=policy["tightening"],
            )
        mf = _Managed(filt, sink, base.checkpoint_every)
        mf.applied_seq = int(
            getattr(filt, "_restored_meta", {}).get("repl_seq", 0) or 0
        )
        seq = self._log_create(req, mf, restored)  # before publish — see CreateFilter
        self._filters[name] = mf
        self.metrics.count("filters_created")
        resp = {
            "ok": True,
            "existed": False,
            "restored_seq": getattr(filt, "_restored_seq", None),
            "config": base.to_dict(),
            "scalable": policy,
        }
        if seq is not None:
            resp["repl_seq"] = seq
        return resp

    def DropFilter(self, req: dict) -> dict:  # lint: allow(replay-safety): replay converges — a retried drop of the now-missing name answers {existed: False}, which clients already treat as success (drop of missing is a no-op by contract)
        for _ in range(4):
            if self.storage is not None:
                # page in first (ISSUE 14): the drop must log + take its
                # final checkpoint over the REAL state, and a paged
                # tenant must not answer {existed: False}
                self.storage.resolve(req["name"], control_plane=True)
            try:
                # the storage entry is forgotten INSIDE _drop's registry
                # critical section — forgetting after the lock released
                # would race a concurrent re-create of the same name and
                # delete the NEW tenant's entry
                return self._drop(req)
            except _TenantPagedRace:
                continue  # evicted between hydrate and registry lock
        raise protocol.BloomServiceError(
            "INTERNAL",
            f"drop of {req['name']!r} kept racing evictions — retry",
        )

    def _drop(self, req: dict) -> dict:
        seq = None
        with self._lock:
            mf = self._filters.pop(req["name"], None)
            if (
                mf is None
                and self.storage is not None
                and self.storage.has(req["name"])
            ):
                # evicted between the caller's hydrate and this lock —
                # a paged tenant must not answer {existed: False}
                raise _TenantPagedRace(req["name"])
            if mf is not None:
                # inside the lock: a concurrent CreateFilter of the same
                # name must not log its create before this drop
                seq = self._log_op(
                    "DropFilter",
                    {k: v for k, v in req.items()
                     if k not in ("rid", "min_replicas",
                                  "min_replicas_timeout_ms",
                                  "asking", "src_seq", "epoch")},
                    may_truncate=False,
                )
                self._manifest_remove(req["name"])
                if self.storage is not None:
                    # under the registry lock — a re-create of the same
                    # name serializes AFTER this forget (see DropFilter)
                    self.storage.forget(req["name"])
        if mf is None:
            return {"ok": True, "existed": False}
        if mf.checkpointer:
            final = req.get("final_checkpoint", True)
            with mf.lock:  # exclude donating inserts during the final snapshot
                landed = mf.checkpointer.close(final_checkpoint=final)  # lint: allow(blocking-under-lock): the filter is already unpublished from the registry — only straggler in-flight RPCs contend, and they must not donate mid-snapshot
            if final and not landed:
                # the filter is gone from memory either way — the caller
                # asked for a durability point and must know it was missed
                raise protocol.BloomServiceError(
                    "CKPT_FAILED",
                    "final checkpoint did not land: "
                    + repr(mf.checkpointer.last_error),
                )
        resp = {"ok": True, "existed": True}
        if seq is not None:
            resp["repl_seq"] = seq
        return resp

    def ListFilters(self, req: dict) -> dict:
        with self._lock:
            names = set(self._filters)
        if self.storage is not None:
            # evicted tenants still exist — paging is transparent
            names.update(self.storage.names())
        return {"ok": True, "filters": sorted(names)}

    # -- keyed-batch helpers: fixed wire encoding + coalescing (ISSUE 10) ----

    @staticmethod
    def _fixed_rows(req: dict):
        """``uint8[n, width]`` view of a request's ``keys_fixed`` buffer
        (zero-copy — ``np.frombuffer`` over the decoded msgpack bin), or
        None for msgpack-list requests."""
        fx = protocol.fixed_keys(req)
        if fx is None:
            return None
        data, width, n = fx
        return np.frombuffer(data, np.uint8).reshape(n, width)

    @classmethod
    def _keys_list(cls, req: dict) -> list:
        """Materialized key list under either encoding — the fallback
        for paths that need per-key bytes (presence, key_policy,
        filters without a packed API)."""
        keys = req.get("keys")
        if keys is not None:
            return keys
        rows = cls._fixed_rows(req)
        if rows is None:
            return []
        return [rows[i].tobytes() for i in range(rows.shape[0])]

    @staticmethod
    def _op_keys(req: dict) -> dict:
        """The key payload for this request's op-log record, in its
        original encoding (replay + replica apply handle both)."""
        if "keys" in req:
            return {"keys": req["keys"]}
        return {"keys_fixed": req["keys_fixed"]}

    @staticmethod
    def _staged_ok(mf: _Managed) -> bool:
        """Whether the filter may take the staged/packed fast paths.
        Single-chip filters always may; sharded filters may since ISSUE
        11 — their staged overrides fire the per-shard ``shard.*``
        fault points themselves and stage a REPLICATED H2D split from
        the shard_map launch (``staged_fault_points`` marks that the
        raw launch no longer bypasses the chaos surface)."""
        return hasattr(mf.filter, "stage_batch") and (
            getattr(mf.filter.config, "shards", 1) <= 1
            or getattr(mf.filter, "staged_fault_points", False)
        )

    @classmethod
    def _packed_ok(cls, mf: _Managed, rows) -> bool:
        """Whether the fixed-width rows can take the filter's zero-copy
        packed path (keys wider than key_len fall back to the list path
        so ``key_policy`` applies there)."""
        return (
            rows is not None
            and cls._staged_ok(mf)
            and hasattr(mf.filter, "insert_packed")
            and rows.shape[1] <= getattr(mf.filter.config, "key_len", 0)
        )

    def _coalesce_eligible(self, req: dict, method: str = "InsertBatch") -> bool:
        """Whether this request may park in the ingestion coalescer.
        Excluded: replay/stream-apply (exactly-once is seq-gated per
        RECORD there), the dispatcher's own fallback re-drives, and
        migration forwards (``asking``/``src_seq`` must hit the import
        gate per-request). ``Clear`` carries no key payload and is
        eligible bare (ISSUE 12: delete/clear coalesce too)."""
        c = self._coalescer
        if c is None or not c.running or c.in_dispatcher():
            return False
        if self._replaying or getattr(self._apply_seq_hint, "seq", None) is not None:
            return False
        if req.get("asking") or req.get("src_seq") is not None:
            return False
        if method != "Clear" and not isinstance(
            req.get("keys"), list
        ) and not isinstance(req.get("keys_fixed"), dict):
            return False
        return True

    @staticmethod
    def _insert_replay_unsafe(mf: _Managed, want_presence: bool) -> bool:
        """True when a REPLAYED insert that already landed would corrupt
        the answer: counting filters scatter-ADD (double-increment),
        scalable filters double-count layer fill, and a presence replay
        reports the batch's own keys as pre-existing. These answer
        retries from the rid cache instead (ISSUE 3 satellite — the same
        machinery that makes DeleteBatch retryable). Sketch kinds carry
        their own classification in the kind registry (ISSUE 19):
        multiset cuckoo adds and CMS increments both corrupt on replay."""
        return bool(
            want_presence
            or getattr(mf.filter.config, "counting", False)
            or hasattr(mf.filter, "layers")
            or sketch_registry.replay_unsafe_insert(mf.filter.config)
        )

    def InsertBatch(self, req: dict) -> dict:
        mf = self._get(req["name"])
        want_presence = bool(req.get("return_presence"))
        replay_unsafe = self._insert_replay_unsafe(mf, want_presence)
        rid = req.get("rid")
        if replay_unsafe:
            cached = self._dedup_get(rid)
            if cached is not None:
                self.metrics.count("insert_dedup_hits")
                return cached
        if self._coalesce_eligible(req):
            resp = self._coalescer.submit(
                "InsertBatch", req, replay_unsafe=replay_unsafe
            )
            if resp is not None:
                return resp
            # coalescer stopped between the check and the park — direct
        nkeys = protocol.batch_size(req)
        rows = self._fixed_rows(req)
        with self._op(req["name"], write=True) as mf, tracing.request_span(
            "InsertBatch", batch=nkeys, rid=obs.current_rid()
        ):
            presence = None
            if want_presence:
                keys = self._keys_list(req)
                # fused test-and-insert (blocked filters run it as one
                # device pass; others fall back to query-then-insert)
                if mf.supports_presence:
                    presence = mf.filter.insert_batch(
                        keys, return_presence=True
                    )
                else:
                    presence = mf.filter.include_batch(keys)
                    mf.filter.insert_batch(keys)
            elif self._packed_ok(mf, rows):
                # fixed wire encoding: the raw buffer reshapes straight
                # into the hash kernels' [B, L] layout — no per-key loop
                mf.filter.insert_packed(rows)
            else:
                mf.filter.insert_batch(self._keys_list(req))
            # honest-FULL verdicts (ISSUE 19): a cuckoo insert can reject
            # keys; collect the per-key flags under the op lock so the
            # response never claims an insert the kernel refused
            full = self._take_insert_full(mf)
            # log BEFORE notify_inserts: notify may trigger a checkpoint
            # whose snapshot contains this batch — its repl_seq stamp
            # (sampled from applied_seq at trigger time) must therefore
            # already include this op, or a crash-replay re-applies it
            seq = self._log_op(
                "InsertBatch", {"name": req["name"], **self._op_keys(req)}, mf
            )
            if seq is None:
                # apply path (replay / stream apply): echo the record's
                # own seq so the dedup-cached response stays seq-stamped
                seq = getattr(self._apply_seq_hint, "seq", None)
            if mf.checkpointer:
                mf.checkpointer.notify_inserts(nkeys)
        self.metrics.count("keys_inserted", nkeys)
        resp = {"ok": True, "n": nkeys}
        if seq is not None:
            resp["repl_seq"] = seq
        if presence is not None:
            resp["presence"] = np.packbits(np.asarray(presence)).tobytes()
        if full is not None:
            resp["full"] = full
        if replay_unsafe:
            self._dedup_put(rid, resp)
        return resp

    @staticmethod
    def _take_insert_full(mf: _Managed):
        """Packed not-inserted bitmap of the filter's last insert, or
        None for kinds whose inserts cannot fail. MUST run under the op
        lock, right after the insert — the flags are per-launch state."""
        taker = getattr(mf.filter, "take_insert_flags", None)
        if taker is None:
            return None
        flags = taker()
        if flags is None or flags.all():
            return None
        return np.packbits(~np.asarray(flags, dtype=bool)).tobytes()

    def QueryBatch(self, req: dict) -> dict:
        mf = self._get(req["name"])
        if self._coalesce_eligible(req):
            resp = self._coalescer.submit("QueryBatch", req)
            if resp is not None:
                return resp
        nkeys = protocol.batch_size(req)
        rows = self._fixed_rows(req)
        with self._op(req["name"]) as mf, tracing.request_span(
            "QueryBatch", batch=nkeys, rid=obs.current_rid()
        ):
            # see class docstring: donation makes the lock mandatory
            if rows is not None and self._packed_ok(mf, rows) and hasattr(
                mf.filter, "include_packed"
            ):
                hits = mf.filter.include_packed(rows)
            else:
                hits = mf.filter.include_batch(self._keys_list(req))
        self.metrics.count("keys_queried", nkeys)
        with obs.phase("encode"):
            packed = np.packbits(hits).tobytes()
        return {"ok": True, "hits": packed, "n": nkeys}

    def _dedup_get(self, rid) -> Optional[dict]:
        if not rid or not self._dedup_capacity:
            return None
        with self._dedup_lock:
            resp = self._dedup.get(rid)
            if resp is not None:
                self._dedup.move_to_end(rid)
        return resp

    def _dedup_put(self, rid, resp: dict) -> None:
        if not rid or not self._dedup_capacity:
            return
        with self._dedup_lock:
            self._dedup[rid] = resp
            self._dedup.move_to_end(rid)
            while len(self._dedup) > self._dedup_capacity:
                self._dedup.popitem(last=False)

    def DeleteBatch(self, req: dict) -> dict:
        mf = self._get(req["name"])
        # attribute presence is not the signal (ShardedBloomFilter carries
        # delete_batch for all layouts and raises on non-counting): the
        # config decides — counting bloom filters and the sketch kinds
        # whose registry row says supports_delete (cuckoo; a CMS cannot
        # un-count) — and everything else stays code UNSUPPORTED
        deletable = getattr(
            mf.filter.config, "counting", False
        ) or sketch_registry.supports_delete(mf.filter.config)
        if not deletable or not hasattr(mf.filter, "delete_batch"):
            raise protocol.BloomServiceError(
                "UNSUPPORTED",
                "delete requires a counting filter or a deletable kind (cuckoo)",
            )
        # Retry safety (ISSUE 2 satellite): a delete is a counter
        # DECREMENT — a replay of one that already landed would decrement
        # twice (-> false negatives). Client retries reuse the logical
        # call's rid, so a bounded rid->response cache turns the replay
        # into a cache hit instead of a second apply. (Retries from one
        # client are sequential, so the lookup/apply pair doesn't need to
        # be atomic across requests.)
        rid = req.get("rid")
        cached = self._dedup_get(rid)
        if cached is not None:
            self.metrics.count("delete_dedup_hits")
            return cached
        if self._coalesce_eligible(req, "DeleteBatch"):
            # ISSUE 12: delete-only flushes ride the scheduler — one
            # launch + one merged log record + one barrier per flush;
            # deletes are always replay-unsafe (decrements), so every
            # demuxed response is dedup-cached under its rid
            resp = self._coalescer.submit(
                "DeleteBatch", req, replay_unsafe=True
            )
            if resp is not None:
                return resp
        nkeys = protocol.batch_size(req)
        with self._op(req["name"], write=True) as mf:
            out = mf.filter.delete_batch(self._keys_list(req))
            seq = self._log_op(
                "DeleteBatch", {"name": req["name"], **self._op_keys(req)}, mf
            )
        if seq is None:  # apply path: keep the dedup response seq-stamped
            seq = getattr(self._apply_seq_hint, "seq", None)
        self.metrics.count("keys_deleted", nkeys)
        resp = {"ok": True, "n": nkeys}
        if out is not None and sketch_registry.is_sketch(mf.filter.config):
            # cuckoo reports per-key "a stored copy existed" (a False
            # flags a delete of a never-added key — a contract violation
            # worth surfacing, not masking)
            resp["deleted"] = np.packbits(np.asarray(out, dtype=bool)).tobytes()
        if seq is not None:
            resp["repl_seq"] = seq
        self._dedup_put(rid, resp)
        return resp

    def Clear(self, req: dict) -> dict:  # lint: allow(replay-safety): replay converges — clearing twice IS cleared (idempotent zeroing); the retried response's fresh repl_seq is STRONGER for barrier re-waits, not weaker
        mf = self._get(req["name"])
        if self._coalesce_eligible(req, "Clear"):
            resp = self._coalescer.submit("Clear", req)
            if resp is not None:
                return resp
        with self._op(req["name"], write=True) as mf:
            mf.filter.clear()
            seq = self._log_op("Clear", {"name": req["name"]}, mf)
        resp = {"ok": True}
        if seq is not None:
            resp["repl_seq"] = seq
        return resp

    # -- sketch plane (ISSUE 19): RedisBloom CF.*/CMS.*/TOPK.* parity ----
    #
    # The *Reserve verbs are CreateFilter with a kind-specific geometry;
    # the data verbs delegate to the bloom data-plane handlers after a
    # kind check, so coalescing, rid dedup, quorum barriers, READONLY,
    # STALE_EPOCH, MOVED/ASK, replication, and tracing are inherited —
    # never re-implemented per kind.

    def _kind_checked(self, name: str, kinds: tuple, verb: str) -> _Managed:
        """Resolve + type-check a filter for a kind-specific verb
        (Redis WRONGTYPE parity: CF.ADD on a bloom key is an error)."""
        mf = self._get(name)
        kind = sketch_registry.kind_of(mf.filter.config)
        if kind not in kinds:
            raise protocol.BloomServiceError(
                "WRONG_TYPE",
                f"{verb} needs a {'/'.join(kinds)} filter; "
                f"{name!r} is kind {kind!r}",
            )
        return mf

    @staticmethod
    def _sketch_create_req(req: dict, config: dict) -> dict:
        """CreateFilter request for a reserve verb: the kind-specific
        geometry plus the caller's durability/routing envelope (rid,
        quorum, epoch, migration hints) passed through untouched."""
        out = {
            "name": req["name"],
            "config": config,
            "exist_ok": bool(req.get("exist_ok")),
        }
        if "restore" in req:
            out["restore"] = req["restore"]
        for field in ("rid", "min_replicas", "min_replicas_timeout_ms",
                      "epoch", "asking", "src_seq"):
            if field in req:
                out[field] = req[field]
        return out

    def CFReserve(self, req: dict) -> dict:  # lint: allow(replay-safety): pure CreateFilter delegation — create replay converges (exist_ok attach / ALREADY_EXISTS), no per-key state to double-apply
        """Create a cuckoo filter sized for ``capacity`` keys."""
        capacity = int(req["capacity"])
        if capacity <= 0:
            raise protocol.BloomServiceError(
                "INVALID_ARGUMENT", "capacity must be positive"
            )
        # size for ~84% slot load — the practical ceiling of a
        # bucket-size-4 table before FULL rejections set in
        slots = max(64, round_up_pow2(math.ceil(capacity / 0.84)))
        config = {"kind": "cuckoo", "m": slots, "k": 2,
                  **req.get("options", {})}
        return self.CreateFilter(self._sketch_create_req(req, config))

    def CFAdd(self, req: dict) -> dict:  # lint: allow(replay-safety): delegates to InsertBatch, which owns the rid-dedup cache (cuckoo inserts classify replay-unsafe via the kind registry)
        """Add keys to a cuckoo filter; resp ``full`` flags rejects."""
        self._kind_checked(req["name"], ("cuckoo",), "CFAdd")
        return self.InsertBatch(req)

    def CFDel(self, req: dict) -> dict:  # lint: allow(replay-safety): delegates to DeleteBatch, which owns the rid-dedup cache
        """Delete one stored copy per key from a cuckoo filter."""
        self._kind_checked(req["name"], ("cuckoo",), "CFDel")
        return self.DeleteBatch(req)

    def CFExists(self, req: dict) -> dict:
        """Membership on a cuckoo filter (QueryBatch with a kind check)."""
        self._kind_checked(req["name"], ("cuckoo",), "CFExists")
        return self.QueryBatch(req)

    def CMSInitByDim(self, req: dict) -> dict:  # lint: allow(replay-safety): pure CreateFilter delegation — see CFReserve
        """Create a count-min sketch with explicit [depth, width] dims.
        width rounds UP to a whole-uint32 multiple of 32 (strictly more
        counters — the configured error bound stays an upper bound)."""
        width, depth = int(req["width"]), int(req["depth"])
        if width <= 0 or not (1 <= depth <= 64):
            raise protocol.BloomServiceError(
                "INVALID_ARGUMENT", "need width > 0 and depth in [1, 64]"
            )
        width = ((width + 31) // 32) * 32
        config = {"kind": "cms", "m": width, "k": depth,
                  **req.get("options", {})}
        return self.CreateFilter(self._sketch_create_req(req, config))

    def CMSIncrBy(self, req: dict) -> dict:
        """Increment key counts. Unit increments (the common streaming
        shape) ARE InsertBatch and ride the coalescer unmodified;
        weighted increments take a direct pass that answers the
        POST-update estimates (Redis CMS.INCRBY parity)."""
        self._kind_checked(req["name"], ("cms", "topk"), "CMSIncrBy")
        incs = req.get("increments")
        nkeys = protocol.batch_size(req)
        if incs is not None and len(incs) != nkeys:
            raise protocol.BloomServiceError(
                "INVALID_ARGUMENT", f"{len(incs)} increments for {nkeys} keys"
            )
        if incs is None or all(int(i) == 1 for i in incs):
            return self.InsertBatch(
                {k: v for k, v in req.items() if k != "increments"}
            )
        # weighted path: a replayed increment double-counts, so the rid
        # cache answers retries (same contract as DeleteBatch)
        rid = req.get("rid")
        cached = self._dedup_get(rid)
        if cached is not None:
            self.metrics.count("insert_dedup_hits")
            return cached
        with self._op(req["name"], write=True) as mf, tracing.request_span(
            "CMSIncrBy", batch=nkeys, rid=obs.current_rid()
        ):
            try:
                counts = mf.filter.increment_batch(
                    self._keys_list(req), [int(i) for i in incs]
                )
            except ValueError as e:
                raise protocol.BloomServiceError("INVALID_ARGUMENT", str(e))
            # log BEFORE notify_inserts — same checkpoint-stamp ordering
            # as InsertBatch; the record carries the increments so a
            # replica / crash replay re-applies the exact weights
            seq = self._log_op(
                "CMSIncrBy",
                {"name": req["name"], **self._op_keys(req),
                 "increments": [int(i) for i in incs]},
                mf,
            )
            if seq is None:
                seq = getattr(self._apply_seq_hint, "seq", None)
            if mf.checkpointer:
                mf.checkpointer.notify_inserts(nkeys)
        self.metrics.count("keys_inserted", nkeys)
        resp = {"ok": True, "n": nkeys, "counts": [int(c) for c in counts]}
        if seq is not None:
            resp["repl_seq"] = seq
        self._dedup_put(rid, resp)
        return resp

    def CMSQuery(self, req: dict) -> dict:
        """Point estimates (only ever >= the true count)."""
        self._kind_checked(req["name"], ("cms", "topk"), "CMSQuery")
        nkeys = protocol.batch_size(req)
        with self._op(req["name"]) as mf, tracing.request_span(
            "CMSQuery", batch=nkeys, rid=obs.current_rid()
        ):
            counts = mf.filter.estimate_batch(self._keys_list(req))
        self.metrics.count("keys_queried", nkeys)
        return {"ok": True, "n": nkeys, "counts": [int(c) for c in counts]}

    def TopKReserve(self, req: dict) -> dict:  # lint: allow(replay-safety): pure CreateFilter delegation — see CFReserve
        """Create a top-``topk`` heavy-hitter sketch (CMS-backed)."""
        heap = int(req["topk"])
        if heap <= 0:
            raise protocol.BloomServiceError(
                "INVALID_ARGUMENT", "topk must be positive"
            )
        width = ((int(req.get("width", 2048)) + 31) // 32) * 32
        depth = int(req.get("depth", 5))
        if width <= 0 or not (1 <= depth <= 64):
            raise protocol.BloomServiceError(
                "INVALID_ARGUMENT", "need width > 0 and depth in [1, 64]"
            )
        config = {"kind": "topk", "m": width, "k": depth, "topk": heap,
                  **req.get("options", {})}
        return self.CreateFilter(self._sketch_create_req(req, config))

    def TopKAdd(self, req: dict) -> dict:  # lint: allow(replay-safety): delegates to InsertBatch, which owns the rid-dedup cache (topk inserts classify replay-unsafe via the kind registry)
        """Count occurrences into a top-k sketch (unit increments)."""
        self._kind_checked(req["name"], ("topk",), "TopKAdd")
        return self.InsertBatch(req)

    def TopKList(self, req: dict) -> dict:
        """Current heavy hitters, estimate-descending."""
        self._kind_checked(req["name"], ("topk",), "TopKList")
        with self._op(req["name"]) as mf:
            items = mf.filter.topk_list()
        return {
            "ok": True,
            "items": [{"key": k, "count": c} for k, c in items],
        }

    def Stats(self, req: dict) -> dict:
        if "name" in req:
            with self._op(req["name"]) as mf:
                st = mf.filter.stats() if hasattr(mf.filter, "stats") else {}
            if mf.checkpointer:
                st["checkpoints_written"] = mf.checkpointer.checkpoints_written
                st["checkpoint_seq"] = mf.checkpointer.seq
                st["checkpoint"] = mf.checkpointer.obs_stats()
            return {"ok": True, "stats": st}
        return {"ok": True, "server": self.metrics.snapshot()}

    def SlowlogGet(self, req: dict) -> dict:
        """Redis ``SLOWLOG GET [n]`` parity: slowest requests first, each
        with method, args summary, batch size, duration, request id,
        timestamp, and the per-phase breakdown."""
        n = req.get("n")
        return {
            "ok": True,
            "entries": self.slowlog.entries(None if n is None else int(n)),
        }

    def SlowlogReset(self, req: dict) -> dict:
        """Redis ``SLOWLOG RESET`` parity."""
        return {"ok": True, "cleared": self.slowlog.reset()}

    def TraceGet(self, req: dict) -> dict:
        """Distributed-tracing lookup (ISSUE 15): every span THIS node
        recorded for one trace id (= the client rid), plus coalescer
        flush spans that LINK it and their children. Cross-node
        assembly is the client's job (``ClusterClient.trace``).

        The looked-up id travels as ``trace_rid`` — the bare ``rid``
        field is the TRANSPORT correlation id every client stamps per
        call, which would otherwise clobber the lookup key; raw callers
        that stamp no correlation id may still use ``rid``."""
        rid = req.get("trace_rid") or req.get("rid")
        if not isinstance(rid, str) or not rid:
            raise protocol.BloomServiceError(
                "INVALID_ARGUMENT",
                "TraceGet needs {trace_rid: <request id>}",
            )
        return {
            "ok": True,
            "rid": rid,
            "enabled": obs_trace.enabled(),
            "spans": obs_trace.get_trace(rid),
        }

    def gauge_snapshot(self) -> list:
        """Per-filter gauge readings for the Prometheus exposition: each
        entry = {filter, stats, shard_fill?, checkpoint?}. Reads run under
        the filter's op lock — a gauge must never read a device buffer a
        donating insert is recycling."""
        with self._lock:
            filters = list(self._filters.items())
        out = []
        for name, mf in filters:
            with mf.lock:
                if mf.evicted:
                    continue  # paged out mid-walk — no device gauges
                st = mf.filter.stats() if hasattr(mf.filter, "stats") else {}
                # sharded stats() already paid the per-shard popcount —
                # don't run the O(m) reduction twice under the op lock
                shard_fill = st.get("fill_ratio_per_shard")
                if shard_fill is None and hasattr(mf.filter, "shard_fill_ratios"):
                    shard_fill = mf.filter.shard_fill_ratios()
            out.append(
                {
                    "filter": name,
                    "stats": st,
                    "shard_fill": shard_fill,
                    "checkpoint": (
                        mf.checkpointer.obs_stats() if mf.checkpointer else None
                    ),
                }
            )
        return out

    def Checkpoint(self, req: dict) -> dict:
        with self._op(req["name"]) as mf:
            # snapshot copy must not race a donating insert
            if not mf.checkpointer:
                raise protocol.BloomServiceError(
                    "UNSUPPORTED", "filter has no checkpoint sink"
                )
            triggered = mf.checkpointer.trigger()
        if req.get("wait", True):
            if not mf.checkpointer.flush():
                raise protocol.BloomServiceError(
                    "CKPT_TIMEOUT", "in-flight checkpoint write did not finish"
                )
            if not triggered:
                # an older snapshot was in flight — it predates this call's
                # durability point, so take a fresh one now that it's done.
                with mf.lock:
                    triggered = mf.checkpointer.trigger()
                if not mf.checkpointer.flush():
                    raise protocol.BloomServiceError(
                        "CKPT_TIMEOUT", "checkpoint write did not finish"
                    )
            if mf.checkpointer.last_error is not None:
                raise protocol.BloomServiceError(
                    "CKPT_FAILED", repr(mf.checkpointer.last_error)
                )
        return {"ok": True, "triggered": triggered, "seq": mf.checkpointer.seq}

    def shutdown(self) -> None:
        """Final checkpoint of every managed filter. Callers doing a full
        graceful drain should ``begin_drain()`` + stop the gRPC server
        first so no insert races the final snapshots."""
        self.begin_drain()
        if self._coalescer is not None:
            # flush + complete every parked request BEFORE the final
            # snapshots (their writers were admitted pre-drain)
            self._coalescer.close()
        with self._lock:
            filters = list(self._filters.items())
        for name, mf in filters:
            if mf.checkpointer:
                with mf.lock:  # let in-flight inserts drain first
                    landed = mf.checkpointer.close(final_checkpoint=True)  # lint: allow(blocking-under-lock): shutdown path — admission is already draining, the final snapshot must exclude donating inserts
                if not landed:
                    log.error(
                        "final checkpoint for filter %r did not land: %r",
                        name, mf.checkpointer.last_error,
                    )


def _wrap(service: BloomService, method_name: str):
    handler = getattr(service, method_name)

    def unary_unary(request: bytes, context) -> bytes:
        t0 = time.perf_counter()
        with obs.request(method_name) as rctx:
            req_name = None
            # readonly + admission first, before decode: a rejection must
            # stay cheap when the server is drowning
            if service.read_only and method_name in protocol.MUTATING_METHODS:
                resp = protocol.error_response(
                    "READONLY",
                    f"{method_name} rejected: this server is a read-only "
                    f"replica — send writes to the primary",
                    details=(
                        {"primary": service.primary_address}
                        if service.primary_address
                        else None
                    ),
                )
                rctx.summary = "(readonly)"
                service.metrics.count("readonly_rejected")
            elif (
                service.oplog_error is not None
                and method_name in protocol.MUTATING_METHODS
            ):
                # fail-stop after an op-log append error: memory is ahead
                # of the log; accepting more writes would widen the
                # divergence silently (Redis MISCONF parity)
                resp = protocol.error_response(
                    "LOG_WRITE_FAILED",
                    f"{method_name} rejected: op log append failed "
                    f"({service.oplog_error}); writes are stopped until "
                    f"the log is writable and the server restarts",
                )
                rctx.summary = "(log-failstop)"
                service.metrics.count("log_failstop_rejected")
            elif (shed := service.admit(method_name)) is not None:
                resp = shed
                rctx.summary = "(shed)"
            else:
                try:
                    faults.fire("rpc.pre_handle")
                    with obs.phase("decode"):
                        req = protocol.decode(request)
                    # correlate with the client's id when it sent one; the
                    # context pre-generated a server-side id otherwise
                    if isinstance(req.get("rid"), str) and req["rid"]:
                        rctx.rid = req["rid"]
                    rctx.batch = protocol.batch_size(req)
                    rctx.summary = summarize_request(method_name, req)
                    # distributed tracing (ISSUE 15): decide capture
                    # now that the client rid (and any propagated trace
                    # context) is known — forced by the wire field, or
                    # the deterministic per-rid sample; slowlog-worthy
                    # requests are additionally captured at finish.
                    # TraceGet never traces itself: an assembly's
                    # lookup fan-out must not pollute (or evict from)
                    # the ring it is reading.
                    tmeta = req.get("trace")
                    if not isinstance(tmeta, dict):
                        tmeta = None
                    if method_name != "TraceGet":
                        obs_trace.arm_request(
                            rctx,
                            forced=bool(tmeta and tmeta.get("forced")),
                            parent=tmeta.get("span") if tmeta else None,
                        )
                    name = req.get("name")
                    req_name = name if isinstance(name, str) else None
                    if service.storage is not None and req_name is not None:
                        # key-weighted tenant heat (ISSUE 14) — the
                        # eviction rank follows the same load signal
                        # the per-slot traffic counters expose
                        service.storage.touch(req_name, rctx.batch or 1)
                    # topology-epoch fence (ISSUE 4): a mutating request
                    # stamped with an OLDER epoch than this server's was
                    # routed under a pre-failover view — reject so the
                    # client refreshes its topology instead of writing
                    # under a stale map
                    req_epoch = req.get("epoch")
                    if (
                        req_epoch is not None
                        and method_name in protocol.MUTATING_METHODS
                        and int(req_epoch) < service.epoch
                    ):
                        service.metrics.count("stale_epoch_rejected")
                        raise protocol.BloomServiceError(
                            "STALE_EPOCH",
                            f"request epoch {req_epoch} predates the "
                            f"current topology epoch {service.epoch} — "
                            f"refresh your topology",
                            details={"epoch": service.epoch},
                        )
                    # cluster slot-ownership check (ISSUE 9): MOVED /
                    # ASK / CLUSTERDOWN redirects BEFORE the handler;
                    # the importing side's seq gate short-circuits
                    # re-delivered migration forwards (exactly-once)
                    gate_dup = False
                    src_seq = None
                    if (
                        service.cluster is not None
                        and isinstance(req_name, str)
                        and method_name in cluster_node.KEYED_METHODS
                    ):
                        service.cluster.check(
                            req_name,
                            asking=bool(req.get("asking")),
                            exists=service.has_filter(req_name),
                            primary_address=(
                                service.primary_address
                                if service.read_only
                                else None
                            ),
                        )
                        if rctx.batch:
                            # per-slot key-traffic counters (ISSUE 10
                            # satellite, ROADMAP item 6): rebalance
                            # decisions can be load-driven instead of
                            # slot-count-driven. Dynamic series —
                            # declared via DYNAMIC_PREFIXES in obs.names
                            obs_counters.incr(
                                "cluster_slot_keys_total_"
                                f"{cluster_slots.key_slot(req_name)}",
                                rctx.batch,
                            )
                        if (
                            method_name in protocol.MUTATING_METHODS
                            and req.get("asking")
                            and req.get("src_seq") is not None
                        ):
                            if (
                                service.cluster.is_importing(
                                    cluster_slots.key_slot(req_name)
                                )
                                and service.cluster.gate_base(req_name)
                                is None
                            ):
                                # importing but no gate yet: the
                                # snapshot install is still in flight
                                # (or was lost to a restart) — applying
                                # now would land on state the install
                                # is about to REPLACE, silently losing
                                # the write. Refuse; the source's
                                # forward fails and the client re-drives
                                # under the same rid until the gate
                                # exists.
                                raise protocol.BloomServiceError(
                                    "IMPORT_NOT_READY",
                                    f"filter {req_name!r} has no import "
                                    f"gate yet (snapshot install in "
                                    f"flight) — retry",
                                )
                            # atomic claim: the tail replay and the live
                            # dual-write may deliver the SAME record
                            # concurrently — only one claim wins, the
                            # other acks as a dup without re-applying
                            faults.fire("cluster.migrate_apply")
                            if service.cluster.gate_claim(
                                req_name, int(req["src_seq"])
                            ):
                                src_seq = int(req["src_seq"])
                            else:
                                gate_dup = True
                                service.metrics.count("cluster_forward_dups")
                    if gate_dup:
                        # the forwarded record is already contained here
                        # (snapshot coverage / earlier delivery): ack
                        # without re-applying. Prefer the dedup cache's
                        # FULL response (an earlier delivery through the
                        # handler cached it, presence bits and this
                        # node's repl_seq included) over the bare ack.
                        cached = service._dedup_get(req.get("rid"))
                        resp = cached if cached is not None else {
                            "ok": True,
                            "migrate_dup": True,
                            "n": protocol.batch_size(req),
                        }
                    else:
                        try:
                            resp = handler(req)
                        except BaseException:
                            if src_seq is not None:
                                # the apply itself failed: the record is
                                # NOT contained — a re-delivery must pass
                                service.cluster.gate_unclaim(
                                    req_name, src_seq
                                )
                            raise
                    # a coalesced response already paid its flush's
                    # shared barrier (ISSUE 10) and was proven outside
                    # any dual-write window under the op lock — pop the
                    # marker and skip both. The dedup-cached copy is
                    # stored WITHOUT the marker, so a same-rid retry
                    # re-waits through the normal barrier below.
                    coalesced_done = isinstance(resp, dict) and bool(
                        resp.pop("_coalesced", False)
                    )
                    # durability gate (ISSUE 5): block OUTSIDE every
                    # lock until the quorum acked this write's record;
                    # a dedup-cache replay re-enters here with the
                    # cached repl_seq and re-waits on the same record
                    # (a barrier timeout does NOT unclaim: the apply
                    # stands, only its quorum ack is missing)
                    if (
                        not gate_dup
                        and not coalesced_done
                        and method_name in protocol.MUTATING_METHODS
                        and resp.get("ok")
                    ):
                        with obs_trace.span("barrier.wait"):
                            resp = service.commit_barrier(req, resp)
                        if service.cluster is not None:
                            # dual-write window (ISSUE 9): a mutating op
                            # on a migrating filter must land on the
                            # target BEFORE the client is acked
                            resp = cluster_migrate.forward_op(
                                service, method_name, req, resp
                            )
                    # post-apply fault: the handler's effect landed but the
                    # response is "lost" — the case rid-dedup must absorb
                    faults.fire("rpc.post_handle")
                except protocol.BloomServiceError as e:
                    resp = protocol.error_response(e.code, e.message, e.details)
                except Exception as e:  # surface, don't kill the channel
                    log.exception("RPC %s failed", method_name)
                    resp = protocol.error_response(
                        "INTERNAL", f"{type(e).__name__}: {e}"
                    )
                finally:
                    service.release(method_name)
            try:
                with obs.phase("encode"):
                    raw = protocol.encode(resp)
            except Exception as e:  # unserializable handler output: keep
                log.exception("RPC %s response encode failed", method_name)
                raw = protocol.encode(  # the structured error contract
                    protocol.error_response(
                        "INTERNAL",
                        f"response encode failed: {type(e).__name__}: {e}",
                    )
                )
            duration_s = time.perf_counter() - t0
            service.metrics.observe_rpc(
                method_name, duration_s, rctx.phases, rid=rctx.rid
            )
            if obs_trace.enabled() and method_name != "TraceGet":
                # commit the request's span tree (ISSUE 15): sampled/
                # forced requests always, and slowlog-worthy ones even
                # unsampled — asked BEFORE the slowlog entry lands so
                # the predicate is not perturbed by this request itself
                code = "OK"
                if isinstance(resp, dict) and not resp.get("ok", False):
                    code = (resp.get("error") or {}).get("code", "UNKNOWN")
                tattrs: dict = {"method": method_name, "code": code}
                if req_name:
                    tattrs["filter"] = req_name
                    if service.cluster is not None:
                        tattrs["slot"] = cluster_slots.key_slot(req_name)
                if rctx.batch:
                    tattrs["batch"] = int(rctx.batch)
                if isinstance(resp, dict) and resp.get("repl_seq") is not None:
                    tattrs["seq"] = int(resp["repl_seq"])
                obs_trace.finish_request(
                    rctx, duration_s, attrs=tattrs,
                    # the slowlog probe (a lock round trip) only
                    # matters when the request is NOT already armed
                    slow=(
                        not rctx.trace_armed
                        and service.slowlog.would_record(duration_s)
                    ),
                )
            service.slowlog.record(
                method=method_name,
                duration_s=duration_s,
                rid=rctx.rid,
                batch=rctx.batch,
                args=rctx.summary,
                phases=rctx.phases,
            )
            if service.monitor_hub.active:
                # MONITOR parity: one structured event per finished
                # request (key payloads stay redacted to the summary)
                service.monitor_hub.publish(
                    {
                        "kind": "op",
                        "ts": time.time(),
                        "method": method_name,
                        "name": req_name,
                        "rid": rctx.rid,
                        "batch": rctx.batch,
                        "args": rctx.summary,
                        "duration_s": duration_s,
                        "ok": bool(resp.get("ok", False)),
                    }
                )
        return raw

    return grpc.unary_unary_rpc_method_handler(unary_unary)


#: Streaming RPC name -> generator(service, req, context) (ISSUE 3).
_STREAM_BEHAVIORS = {
    "ReplStream": repl_primary.repl_stream,
    "Monitor": repl_monitor.monitor_stream,
}

#: Client-streaming RPC name -> behavior(service, request_iterator,
#: context) -> response dict (ISSUE 5).
_CLIENT_STREAM_BEHAVIORS = {
    "ReplAck": repl_primary.repl_ack,
}


#: Bidi-streaming RPC name -> behavior(service, request_iterator,
#: context) -> yields encoded ack frames (ISSUE 18 — the streaming
#: ingest plane; see :mod:`tpubloom.server.streams`).
_BIDI_STREAM_BEHAVIORS = {
    "InsertStream": server_streams.insert_stream,
    "QueryStream": server_streams.query_stream,
}


def _wrap_bidi_stream(service: BloomService, method_name: str):
    behavior = _BIDI_STREAM_BEHAVIORS[method_name]

    def stream_stream(request_iterator, context):
        service.metrics.count(f"stream_{method_name}_opened")
        # frames are decoded/encoded INSIDE the behavior: the receiver
        # thread consumes raw request frames while this handler thread
        # drains the per-stream ack queue — per-frame semantic errors
        # answer as error ACKS (the stream survives); only a transport
        # break or an injected stream.recv/stream.ack fault tears the
        # stream down (the client reconnects and replays unacked
        # frames under their original rids)
        yield from behavior(service, request_iterator, context)

    return grpc.stream_stream_rpc_method_handler(stream_stream)


def _wrap_client_stream(service: BloomService, method_name: str):
    behavior = _CLIENT_STREAM_BEHAVIORS[method_name]

    def stream_unary(request_iterator, context) -> bytes:
        service.metrics.count(f"stream_{method_name}_opened")
        # an injected repl.ack_recv (or any bug) propagates: grpc fails
        # the RPC and the replica re-opens its ack stream on heartbeat
        return protocol.encode(behavior(service, request_iterator, context))

    return grpc.stream_unary_rpc_method_handler(stream_unary)


def _wrap_stream(service: BloomService, method_name: str):
    gen_fn = _STREAM_BEHAVIORS[method_name]

    def unary_stream(request: bytes, context):
        try:
            req = protocol.decode(request) if request else {}
        except Exception:
            req = {}
        service.metrics.count(f"stream_{method_name}_opened")
        # an injected repl.stream_send fault (or any bug) propagates out
        # of the generator: grpc surfaces a stream error and the replica
        # reconnects — exactly the mid-batch-kill chaos case
        for msg in gen_fn(service, req, context):
            yield protocol.encode(msg)

    return grpc.unary_stream_rpc_method_handler(unary_stream)


def build_server(
    service: BloomService,
    address: str = "127.0.0.1:50051",
    max_workers: int = 16,
) -> tuple[grpc.Server, int]:
    """Create (not start) a grpc.Server with the BloomService mounted.

    Returns ``(server, bound_port)``; pass port 0 in ``address`` for an
    ephemeral port. ``max_workers`` sizes the handler thread pool: every
    connected replica parks TWO workers for its stream lifetimes
    (ReplStream out + ReplAck in, ISSUE 5), and blocked Wait/commit-
    barrier calls hold theirs too — size generously.
    """
    handlers = {m: _wrap(service, m) for m in protocol.METHODS}
    handlers.update(
        {m: _wrap_stream(service, m) for m in protocol.STREAM_METHODS}
    )
    handlers.update(
        {
            m: _wrap_client_stream(service, m)
            for m in protocol.CLIENT_STREAM_METHODS
        }
    )
    handlers.update(
        {
            m: _wrap_bidi_stream(service, m)
            for m in protocol.BIDI_STREAM_METHODS
        }
    )
    generic = grpc.method_handlers_generic_handler(protocol.SERVICE, handlers)
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers),
        options=list(protocol.CHANNEL_OPTIONS),
    )
    server.add_generic_rpc_handlers((generic,))
    port = server.add_insecure_port(address)
    return server, port


def _inspect_quarantine_main(argv: list) -> int:
    """``python -m tpubloom.server inspect-quarantine <ckpt_dir>
    [--purge] [--json]`` — operator view of the corrupt-checkpoint
    quarantine (ISSUE 3 satellite)."""
    import argparse
    import json as _json

    parser = argparse.ArgumentParser(
        prog="tpubloom.server inspect-quarantine",
        description="list / purge quarantined corrupt checkpoint blobs",
    )
    parser.add_argument("directory", help="the checkpoint directory")
    parser.add_argument(
        "--purge", action="store_true", help="delete every quarantined blob"
    )
    parser.add_argument(
        "--json", action="store_true", dest="as_json",
        help="machine-readable output",
    )
    args = parser.parse_args(argv)
    report = ckpt.inspect_quarantine(args.directory, purge=args.purge)
    if args.as_json:
        print(_json.dumps(report))
    else:
        print(
            f"quarantine {report['quarantine_dir']}: "
            f"{len(report['entries'])} blob(s), {report['total_bytes']} bytes"
        )
        for e in report["entries"]:
            print(
                f"  {e['file']:40s} {e['bytes']:>12d}B  "
                f"{time.strftime('%Y-%m-%d %H:%M:%S', time.localtime(e['mtime']))}"
                f"  {e['diagnosis']}"
            )
        if args.purge:
            print(f"purged {report['purged']} blob(s)")
    return 0


def _promote_main(argv: list) -> int:
    """``python -m tpubloom.server promote <address> [--epoch N]
    [--repl-log-dir DIR]`` — manual replica→primary promotion (Redis
    ``REPLICAOF NO ONE`` parity): sends the ``Promote`` RPC to the
    given replica. ``--repl-log-dir`` names the log dir the REMOTE
    process should open when it was started without one (chained
    replicas already have theirs)."""
    import argparse
    import json as _json

    from tpubloom.server.client import BloomClient

    parser = argparse.ArgumentParser(
        prog="tpubloom.server promote",
        description="promote a running replica to primary",
    )
    parser.add_argument("address", help="host:port of the replica")
    parser.add_argument(
        "--epoch", type=int, default=None,
        help="pin the topology epoch (default: the replica bumps its own)",
    )
    parser.add_argument(
        "--repl-log-dir", default=None,
        help="op-log dir the replica should adopt when it has none",
    )
    args = parser.parse_args(argv)
    req: dict = {}
    if args.epoch is not None:
        req["epoch"] = args.epoch
    if args.repl_log_dir:
        req["repl_log_dir"] = args.repl_log_dir
    with BloomClient(args.address) as client:
        resp = client._rpc("Promote", req)
    print(_json.dumps(resp))
    return 0


def main(argv: Optional[list] = None) -> None:
    """``python -m tpubloom.server [port] [checkpoint_dir]
    [--metrics-port N] [--slowlog-capacity N] [--max-in-flight N]
    [--drain-grace S] [--repl-log-dir DIR] [--replica-of HOST:PORT]
    [--repl-batch-bytes N] [--announce HOST:PORT]``

    ``--replica-of`` + ``--repl-log-dir`` together run a CHAINED replica
    (ISSUE 4): applied records re-append to the local log, ``ReplStream``
    serves downstream replicas, and promotion is cheap.

    Subcommands: ``inspect-quarantine <dir>``, ``promote <address>``.
    """
    import argparse
    import signal
    import sys as _sys

    argv = list(_sys.argv[1:]) if argv is None else list(argv)
    if argv and argv[0] == "inspect-quarantine":
        raise SystemExit(_inspect_quarantine_main(argv[1:]))
    if argv and argv[0] == "promote":
        raise SystemExit(_promote_main(argv[1:]))

    parser = argparse.ArgumentParser(
        prog="tpubloom.server", description="tpubloom gRPC server"
    )
    parser.add_argument("port", nargs="?", type=int, default=50051)
    parser.add_argument("checkpoint_dir", nargs="?", default=None)
    parser.add_argument(
        "--metrics-port",
        type=int,
        default=None,
        help="serve Prometheus text format at http://0.0.0.0:PORT/metrics "
        "(0 picks an ephemeral port; omit to disable)",
    )
    parser.add_argument(
        "--slowlog-capacity",
        type=int,
        default=128,
        help="how many slowest requests SlowlogGet retains (default 128)",
    )
    parser.add_argument(
        "--max-in-flight",
        type=int,
        default=None,
        help="cap on concurrently-executing data-plane RPCs; excess "
        "requests are shed with RESOURCE_EXHAUSTED + retry_after_ms "
        "(default: unbounded)",
    )
    parser.add_argument(
        "--drain-grace",
        type=float,
        default=15.0,
        help="seconds to let in-flight RPCs finish on SIGTERM/SIGINT "
        "before final checkpoints (default 15)",
    )
    parser.add_argument(
        "--repl-log-dir",
        default=None,
        help="append every mutating RPC to a CRC32C-framed op log in this "
        "directory (AOF parity: startup replays it over the restored "
        "checkpoints) and serve the ReplStream RPC to replicas",
    )
    parser.add_argument(
        "--repl-fsync",
        action="store_true",
        help="fsync the op log on every append (Redis appendfsync-always "
        "parity; default: OS page cache)",
    )
    parser.add_argument(
        "--replica-of",
        default=None,
        metavar="HOST:PORT",
        help="run as a read-only replica of the given primary: stream and "
        "apply its op log, serve reads, answer writes with READONLY. "
        "Combine with --repl-log-dir for a CHAINED replica (re-appends "
        "applied records locally, serves ReplStream downstream, promotes "
        "cheaply)",
    )
    parser.add_argument(
        "--repl-batch-bytes",
        type=int,
        default=None,
        help="coalesce ReplStream records into zlib-compressed frames of "
        "up to N raw bytes for replicas that negotiated the capability "
        "(WAN links; default: one record per message)",
    )
    parser.add_argument(
        "--announce",
        default=None,
        metavar="HOST:PORT",
        help="address to announce to primaries/sentinels (Redis "
        "replica-announce parity; default 127.0.0.1:<port>)",
    )
    parser.add_argument(
        "--min-replicas-to-write",
        type=int,
        default=0,
        metavar="N",
        help="synchronous-replication quorum (Redis min-replicas-to-write "
        "parity): each mutating RPC blocks after its op-log append until "
        "N replicas acknowledge the record; timeout answers "
        "NOT_ENOUGH_REPLICAS. Requires --repl-log-dir. Default 0 (async)",
    )
    parser.add_argument(
        "--cluster",
        action="store_true",
        help="run in cluster mode (ISSUE 9, Redis Cluster parity): every "
        "keyed RPC is checked against the hash-slot map (MOVED/ASK "
        "redirects), the ClusterSlots/ClusterSetSlot/MigrateSlot verbs "
        "are served, and the map persists beside the op log (or the "
        "checkpoint dir). Seed assignments with `python -m "
        "tpubloom.cluster init`",
    )
    parser.add_argument(
        "--coalesce-max-keys",
        type=int,
        default=0,
        metavar="N",
        help="enable the cross-connection ingestion coalescer (ISSUE "
        "10): concurrent InsertBatch/QueryBatch RPCs park in per-filter "
        "queues and flush as ONE device launch + ONE op-log append + "
        "ONE commit barrier once N keys are parked (or the wait budget "
        "expires). 0 disables (the default, per-request path)",
    )
    parser.add_argument(
        "--coalesce-max-wait-us",
        type=int,
        default=500,
        metavar="U",
        help="coalescer flush deadline: a parked request never waits "
        "longer than this for batch-mates (default 500us)",
    )
    parser.add_argument(
        "--max-resident-filters",
        type=int,
        default=0,
        metavar="N",
        help="multi-tenant paging (ISSUE 14): keep at most N filters "
        "RESIDENT in device HBM; cold-ranked filters are evicted to a "
        "host-RAM blob pool (and their checkpoints) and lazily "
        "re-hydrated on first RPC. 0 disables paging (the default, "
        "every filter resident for the process lifetime)",
    )
    parser.add_argument(
        "--max-resident-bytes",
        type=int,
        default=0,
        metavar="B",
        help="HBM residency budget in approximate filter bytes — the "
        "byte-denominated twin of --max-resident-filters (either or "
        "both may be set; 0 = unbounded)",
    )
    parser.add_argument(
        "--storage-warm-bytes",
        type=int,
        default=256 * 1024 * 1024,
        metavar="B",
        help="host-RAM blob pool budget for WARM (evicted) filters; "
        "over budget the coldest fully-checkpointed blobs are trimmed "
        "to COLD (checkpoint-only). Default 256MiB",
    )
    parser.add_argument(
        "--hydration-max-concurrent",
        type=int,
        default=4,
        metavar="N",
        help="at most N tenant hydrations in flight; further cold-"
        "tenant requests are shed with RESOURCE_EXHAUSTED + "
        "retry_after_ms (default 4)",
    )
    parser.add_argument(
        "--tenant-hydrations-per-min",
        type=int,
        default=0,
        metavar="N",
        help="per-tenant hydration quota (token bucket): a tenant "
        "thrashing in and out of residency faster than this is shed "
        "with retry_after_ms while hot tenants keep serving. 0 "
        "disables (the default)",
    )
    parser.add_argument(
        "--min-replicas-max-lag-ms",
        type=int,
        default=DEFAULT_MIN_REPLICAS_MAX_LAG_MS,
        metavar="M",
        help="how long the commit barrier (and a Wait with no timeout) "
        "waits for the replica quorum before giving up "
        f"(default {DEFAULT_MIN_REPLICAS_MAX_LAG_MS})",
    )
    parser.add_argument(
        "--trace-sample",
        type=float,
        default=None,
        metavar="R",
        help="distributed tracing (ISSUE 15): capture span trees for "
        "this deterministic per-rid fraction of requests (0.0 = only "
        "forced/slowlog-worthy ones) into the bounded per-node ring "
        "served by TraceGet and /trace?rid=. Omit to disable tracing "
        "entirely (the default: no wire fields, no overhead)",
    )
    parser.add_argument(
        "--flight-dir",
        default=None,
        metavar="DIR",
        help="flight-recorder dump directory (default: the op-log dir "
        "or checkpoint dir, else $TPUBLOOM_FLIGHT_DIR); lifecycle-event "
        "dumps land here on SIGTERM, fatal write-path errors and Health "
        "DEGRADED flips",
    )
    parser.add_argument(
        "--blackbox-dir",
        default=None,
        metavar="DIR",
        help="crash-forensics black box (ISSUE 16): map the SIGKILL-"
        "surviving flight/trace rings under DIR/blackbox/ (default: "
        "the op-log dir, else the checkpoint dir, else an explicit "
        "--flight-dir — NOT $TPUBLOOM_FLIGHT_DIR, which many processes "
        "share; no state dir at all leaves the box off). Read dead "
        "nodes with `python -m tpubloom.obs.blackbox DIR`",
    )
    parser.add_argument(
        "--no-blackbox",
        action="store_true",
        help="disable the crash-forensics black box even when a state "
        "dir is available",
    )
    args = parser.parse_args(argv)
    if args.min_replicas_to_write and not args.repl_log_dir:
        parser.error("--min-replicas-to-write requires --repl-log-dir")
    ckpt_dir = args.checkpoint_dir
    sink_factory = (
        (lambda config: ckpt.FileSink(ckpt_dir)) if ckpt_dir else (lambda config: None)
    )
    logging.basicConfig(level=logging.INFO)
    faults.load_env()
    for armed in faults.active():
        log.warning("fault injection armed: %s", armed)
    oplog = None
    if args.repl_log_dir:
        from tpubloom.repl import OpLog

        oplog = OpLog(args.repl_log_dir, fsync=args.repl_fsync)
    announce = args.announce or f"127.0.0.1:{args.port}"
    cluster_state = None
    if args.cluster:
        from tpubloom.cluster.node import ClusterState

        cluster_state = ClusterState(
            announce, state_dir=args.repl_log_dir or ckpt_dir
        )
        log.info(
            "cluster mode: %s (map epoch %d)",
            announce, cluster_state.epoch(),
        )
    storage_config = None
    if args.max_resident_filters > 0 or args.max_resident_bytes > 0:
        from tpubloom.storage import StorageConfig

        if not ckpt_dir:
            parser.error(
                "--max-resident-filters/--max-resident-bytes require a "
                "checkpoint_dir (the COLD tier needs a durable sink)"
            )
        storage_config = StorageConfig(
            max_resident_filters=args.max_resident_filters or None,
            max_resident_bytes=args.max_resident_bytes or None,
            warm_pool_bytes=args.storage_warm_bytes,
            hydration_max_concurrent=args.hydration_max_concurrent,
            tenant_hydrations_per_min=args.tenant_hydrations_per_min,
        )
        log.info(
            "multi-tenant paging: max %s resident filter(s) / %s bytes",
            args.max_resident_filters or "unbounded",
            args.max_resident_bytes or "unbounded",
        )
    coalesce = None
    if args.coalesce_max_keys > 0:
        from tpubloom.server.ingest import CoalesceConfig

        coalesce = CoalesceConfig(
            max_keys=args.coalesce_max_keys,
            max_wait_us=args.coalesce_max_wait_us,
        )
        log.info(
            "ingestion coalescer: flush at %d keys / %dus",
            args.coalesce_max_keys, args.coalesce_max_wait_us,
        )
    # flight recorder (ISSUE 15): dumps land beside the durable state
    # (or wherever CI's TPUBLOOM_FLIGHT_DIR points) — post-mortems of
    # chaos failures stop depending on scraping a live /metrics
    import os as _os

    flight_dir = (
        args.flight_dir
        or _os.environ.get(obs_flight.DUMP_DIR_ENV)
        or args.repl_log_dir
        or ckpt_dir
    )
    if flight_dir:
        obs_flight.configure(dump_dir=flight_dir)
    # crash-forensics black box (ISSUE 16): the mapped rings live in a
    # NODE-PRIVATE state dir (ring file names are fixed so a restart
    # reattaches to its own pre-crash history — a shared dir like
    # $TPUBLOOM_FLIGHT_DIR would collide across processes, so it is
    # deliberately not a fallback here)
    blackbox_dir = (
        None
        if args.no_blackbox
        else (
            args.blackbox_dir
            or args.repl_log_dir
            or ckpt_dir
            or args.flight_dir
        )
    )
    if blackbox_dir:
        obs_blackbox.configure(blackbox_dir, node={"addr": announce})
    service = BloomService(
        sink_factory=sink_factory,
        slowlog_capacity=args.slowlog_capacity,
        max_in_flight=args.max_in_flight,
        oplog=oplog,
        read_only=bool(args.replica_of),
        repl_batch_bytes=args.repl_batch_bytes,
        listen_address=announce,
        min_replicas_to_write=args.min_replicas_to_write,
        min_replicas_max_lag_ms=args.min_replicas_max_lag_ms,
        cluster=cluster_state,
        coalesce=coalesce,
        storage=storage_config,
        trace_sample=args.trace_sample,
    )
    if oplog is not None:
        stats = service.replay_oplog()
        log.info(
            "op log %s: replayed %d record(s) (%d already covered by "
            "checkpoints, %d failed), next seq %d",
            args.repl_log_dir, stats["applied"], stats["skipped"],
            stats["failed"], oplog.last_seq + 1,
        )
    applier = None
    if args.replica_of:
        from tpubloom.repl import (
            ReplicaApplier,
            ReplicaStateStore,
            bootstrap_from_local,
        )

        # replica durability (ISSUE 4 satellite): the cursor + manifest
        # live beside the op log (chained) or the checkpoint sink — a
        # restart partial-resyncs instead of always paying a full resync
        state_dir = args.repl_log_dir or ckpt_dir
        store = ReplicaStateStore(state_dir) if state_dir else None
        service.replica_state_store = store
        if service._manifest_dir is None and state_dir:
            service._manifest_dir = state_dir
        cursor, log_id = bootstrap_from_local(service, store)
        applier = ReplicaApplier(
            service,
            args.replica_of,
            state_store=store,
            listen_address=announce,
            initial_cursor=cursor,
            initial_log_id=log_id,
        ).start()
        log.info(
            "replicating from %s (read-only%s%s)",
            args.replica_of,
            ", chained" if oplog is not None else "",
            f", resuming at seq {cursor}" if cursor is not None else "",
        )
    server, bound = build_server(service, f"0.0.0.0:{args.port}")
    server.start()
    # power-on record (ISSUE 16): every state dir's black box carries
    # at least this — the anchor a post-mortem needs to know WHICH
    # process (role, epoch, address) wrote the final events before a
    # SIGKILL that ran no handler
    obs_flight.note(
        "boot",
        role="replica" if args.replica_of else "primary",
        epoch=int(service.epoch),
        addr=announce,
    )
    log.info("tpubloom server listening on :%d (checkpoints: %s)", bound, ckpt_dir)
    metrics_server = None
    if args.metrics_port is not None:
        from tpubloom.obs.httpd import start_metrics_server

        metrics_server = start_metrics_server(service, port=args.metrics_port)
        log.info(
            "prometheus exposition on http://0.0.0.0:%d/metrics",
            metrics_server.port,
        )

    # Graceful drain (ISSUE 2): SIGTERM/SIGINT -> stop admitting (new
    # requests shed as DRAINING; clients pace off retry_after_ms and find
    # the replacement process), finish in-flight work, write a final
    # checkpoint of every filter, then exit. Acked-but-unflushed state
    # survives the roll.
    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda signum, frame: stop.set())
    stop.wait()
    log.info("drain: refusing new work, finishing in-flight requests...")
    # flight recorder (ISSUE 15): dump FIRST — the drain itself may
    # wedge, and the whole point is having the lifecycle ring on disk
    # when the process stops being scrapeable
    obs_flight.note("drain", grace_s=float(args.drain_grace))
    obs_flight.dump("sigterm")
    # black box msync (ISSUE 16): the drain note above already landed
    # in the mapped ring lock-free; flushing here covers the machine-
    # crash-during-drain case
    obs_blackbox.sync()
    service.begin_drain()
    # Notice window BEFORE the port closes: grpc's stop() rejects new RPCs
    # at the transport, so without this pause clients would only ever see
    # raw UNAVAILABLE — never the structured DRAINING shed (with
    # retry_after_ms) or a DRAINING Health answer that tells them this is
    # a roll, not an outage.
    time.sleep(min(2.0, args.drain_grace / 3))
    server.stop(grace=args.drain_grace).wait()
    # a runtime Promote/ReplicaOf may have replaced (or dropped) the
    # startup applier and op log — drain whatever is CURRENT
    live_applier = service.replica_applier or applier
    if live_applier is not None:
        live_applier.stop()
    log.info("drain: final checkpoints...")
    service.shutdown()
    if service.oplog is not None:
        service.oplog.close()
    elif oplog is not None:
        oplog.close()
    if service.cluster is not None:
        service.cluster.close()
    if metrics_server is not None:
        metrics_server.close()
    log.info("drain complete; exiting")
