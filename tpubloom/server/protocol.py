"""Wire protocol for the tpubloom gRPC service.

Parity: this is the L4 transport of the layer map — the reference's
redis-rb/RESP hop becomes a gRPC channel from the (Ruby or Python) client
to the colocated JAX process (SURVEY.md §1; BASELINE: "#insert_batch /
#include_batch? ... ship key batches over a thin gRPC shim").

Implementation note: the environment has the ``grpc`` runtime but not
``grpc_tools`` (no protoc codegen for Python), so the service uses gRPC's
generic method handlers with **msgpack-encoded request/response maps**
instead of compiled protobufs. msgpack handles raw-byte keys natively, has
first-class Ruby support (the reference's ecosystem), and keeps the wire
format hand-decodable. Every message is a msgpack map; bulk key payloads
are msgpack ``bin`` arrays.

Request correlation: any request map MAY carry a ``rid`` field (string
request id). The server folds it into profiler spans and slowlog entries;
the stock Python client stamps one on every call. Servers generate one
when absent, so old clients stay compatible.

Fixed-width key encoding (ISSUE 10): per-key msgpack ``bin`` framing is
the host-side decode hot spot once the device stops being the bottleneck
(the PR-1 phase histograms put decode+host_prep ahead of the kernel on
the server path). A request MAY therefore replace its ``keys`` list with
``keys_fixed = {"data": <raw bytes>, "width": W, "n": N}`` — N keys of
exactly W bytes each, concatenated. The canonical use is u64 keys
(W=8, little-endian), which the server decodes **zero-copy** via
``np.frombuffer(data).reshape(n, width)`` straight into the shape the
hash kernels consume — no per-key Python loop at all. Capability
discovery: ``Health`` answers ``encodings: ["msgpack", "fixed"]``;
clients negotiate per-connection and keep the msgpack list path for
servers (or key sets) that can't. The two encodings are semantically
identical: a u64 shipped fixed hits the same filter positions as its
8-byte little-endian ``bin`` twin.

Service: ``/tpubloom.BloomService/<Method>`` for Method in METHODS.
"""

from __future__ import annotations

import msgpack

SERVICE = "tpubloom.BloomService"

#: gRPC message-size caps shared by every hop that may carry a filter
#: snapshot blob (client channels, node→node migration links, the
#: server itself) — ONE definition, or a future bump would miss a copy
#: and surface as RESOURCE_EXHAUSTED only on the stale path.
CHANNEL_OPTIONS = (
    ("grpc.max_receive_message_length", 256 * 1024 * 1024),
    ("grpc.max_send_message_length", 256 * 1024 * 1024),
)

METHODS = (
    "Health",
    "CreateFilter",
    "DropFilter",
    "ListFilters",
    "InsertBatch",
    "QueryBatch",
    "DeleteBatch",
    "Clear",
    "Stats",
    "Checkpoint",
    "SlowlogGet",
    "SlowlogReset",
    "TraceGet",
    "Promote",
    "ReplicaOf",
    "Wait",
    "ClusterSlots",
    "ClusterSetSlot",
    "MigrateSlot",
    "MigrateInstall",
    # sketch plane (ISSUE 19 — RedisBloom CF.*/CMS.*/TOPK.* parity).
    # Reserve verbs are CreateFilter with a kind-specific geometry;
    # Add/Del/Exists ride the bloom data-plane machinery (coalescer,
    # dedup, quorum barriers, MOVED/ASK) via delegation in the service.
    "CFReserve",
    "CFAdd",
    "CFDel",
    "CFExists",
    "CMSInitByDim",
    "CMSIncrBy",
    "CMSQuery",
    "TopKReserve",
    "TopKAdd",
    "TopKList",
)

#: Server-streaming RPCs (ISSUE 3): each response frame is one msgpack
#: map. ``ReplStream`` is the primary→replica changefeed (PSYNC parity:
#: request ``{cursor?}``, frames ``full_sync_begin/snapshot/
#: full_sync_end/partial_sync/record/heartbeat``); ``Monitor`` is the
#: Redis-MONITOR-parity live op stream (request ``{name?}`` to filter by
#: filter name, frames ``hello/op/heartbeat``).
STREAM_METHODS = (
    "ReplStream",
    "Monitor",
)

#: Client-streaming RPCs (ISSUE 5): each REQUEST frame is one msgpack
#: map; the server answers one map when the stream ends. ``ReplAck`` is
#: the replica→primary acknowledgement channel of the synchronous-
#: replication path: frames ``{"sid": <session id from the sync frame>,
#: "seq": <newest op seq fully applied>}``, coalesced latest-wins and
#: re-sent periodically so a lost frame heals. The primary folds them
#: into per-replica acked cursors that the ``Wait`` RPC and the
#: ``min-replicas-to-write`` commit barrier block on.
CLIENT_STREAM_METHODS = (
    "ReplAck",
)

#: Bidirectional-streaming RPCs (ISSUE 18 — the streaming ingest
#: plane): one persistent stream amortizes transport the way the
#: coalescer amortizes device launches. Every frame in BOTH directions
#: is one msgpack map.
#:
#: Client→server DATA frames (both methods)::
#:
#:     {"seq": <client frame seq, 1-based, monotone per stream>,
#:      "rid": <frame request id — retained across reconnect replays>,
#:      "name": <filter>,
#:      "keys_fixed": {"data", "width", "n"}   # or "keys": [b, ...]
#:      # InsertStream only, all optional:
#:      "return_presence": bool, "min_replicas": int,
#:      "min_replicas_timeout_ms": int, "epoch": int}
#:
#: Server→client ACK frames: the FIRST frame on every stream is
#: ``{"kind": "hello", "credit": <initial window>}``; afterwards one
#: ``{"kind": "ack", "seq": <echoed frame seq>, "credit": <fresh
#: window>, "resp": <the full unary-shaped response map>}`` per data
#: frame — NOT necessarily in frame order (split insert flushes,
#: multi-filter groups, and direct-path interleave reorder
#: completions); each ack echoes its frame's ``seq``, so match on
#: that. ``resp`` is EXACTLY what the unary
#: ``InsertBatch``/``QueryBatch`` would have answered (``ok/n``,
#: presence/hits bitmaps, ``repl_seq``, quorum verdicts from the
#: one-barrier-per-flush path, or an ``error`` map) — acks are
#: pipelined, so many frames ride one coalesced flush.
#:
#: Flow control: ``credit`` is the number of UNACKED data frames the
#: client may have in flight, derived from the coalescer's parked-key
#: budget (``ingest_parked_current`` vs ``max_parked_keys``). Grants
#: only ride ack frames and never drop below 1 — an over-budget server
#: PARKS the stream (acks slow down, the window shrinks toward 1)
#: instead of shedding.
#:
#: Exactly-once replay: a client whose stream died mid-flight
#: reconnects and re-sends ONLY its unacked frames under their ORIGINAL
#: rids; the server's rid→response dedup cache (ISSUE 2/3, rebuilt from
#: the op log's per-frame ``parts`` on restart) answers any frame whose
#: first flight already applied from cache — zero double-applies, even
#: for counting-filter inserts.
BIDI_STREAM_METHODS = (
    "InsertStream",
    "QueryStream",
)

#: Mutating RPCs: replicated through the op log, rejected with
#: ``READONLY`` on replicas (Redis ``replica-read-only`` parity). A
#: mutating request MAY carry the caller's cached topology ``epoch``
#: (ISSUE 4): a server whose epoch is newer answers ``STALE_EPOCH`` so
#: topology-aware clients refresh instead of writing under a stale view.
MUTATING_METHODS = frozenset(
    {
        "CreateFilter",
        "DropFilter",
        "InsertBatch",
        "DeleteBatch",
        "Clear",
        # sketch-plane writes (ISSUE 19); the read verbs
        # (CFExists/CMSQuery/TopKList) stay replica-servable
        "CFReserve",
        "CFAdd",
        "CFDel",
        "CMSInitByDim",
        "CMSIncrBy",
        "TopKReserve",
        "TopKAdd",
    }
)

#: Durability-gate RPC (ISSUE 5, Redis ``WAIT`` parity): ``Wait``
#: ``{numreplicas, timeout_ms, seq?}`` blocks until at least
#: ``numreplicas`` replicas have acknowledged every record up to ``seq``
#: (default: the server's current log head; clients send their last
#: write's ``repl_seq``) and answers ``{nreplicas}`` — the count
#: actually acked, even when below the target (Redis WAIT returns the
#: count, it does not error). Mutating requests MAY carry
#: ``min_replicas`` (+ ``min_replicas_timeout_ms``) to demand a
#: per-request commit barrier stronger than the server's
#: ``--min-replicas-to-write`` default; a barrier that times out answers
#: ``NOT_ENOUGH_REPLICAS`` (Redis ``NOREPLICAS`` parity) with
#: ``details={acked, needed, seq, applied: true}`` — the write DID apply
#: and IS logged locally, only the quorum ack is missing, so a retry
#: under the same rid re-waits on the same record instead of
#: re-applying.

#: Distributed tracing (ISSUE 15): ``TraceGet`` ``{trace_rid}`` answers
#: ``{rid, enabled, spans: [...]}`` — every span THIS node recorded for
#: that trace id (the client rid), plus any coalescer flush span that
#: LINKS it and that flush trace's children. The lookup key travels as
#: ``trace_rid`` because the bare ``rid`` field is the per-call
#: transport correlation id clients stamp on every request (raw callers
#: that stamp none may use ``rid``). Unsheddable control plane:
#: the trace of a slow request is most needed exactly when the node is
#: drowning. A request MAY carry ``trace = {"forced": true, "span":
#: <parent span id>}`` to force capture regardless of the server's
#: ``--trace-sample`` rate and to parent the server's root span under
#: the client's hop span; with tracing off servers ignore the field and
#: clients stamp none (the off path is wire-identical to pre-ISSUE-15).

#: HA control-plane RPCs (ISSUE 4): ``Promote`` (replica→primary,
#: ``REPLICAOF NO ONE`` parity) and ``ReplicaOf`` (re-point/demote,
#: ``REPLICAOF host port`` parity). Epoch-stamped; stale epochs are
#: rejected with ``STALE_EPOCH``. Deliberately NOT in MUTATING_METHODS
#: (they must run on replicas) and never shed (a failover must land on
#: an overloaded cluster).
HA_METHODS = frozenset({"Promote", "ReplicaOf"})

#: Cluster-mode RPCs (ISSUE 9 — Redis Cluster parity). ``ClusterSlots``
#: answers the node's slot map (``{enabled, epoch, self, ranges:
#: [[start, end, addr], ...], migrating, importing}`` — CLUSTER SLOTS
#: parity; clients build their slot→shard cache from it).
#: ``ClusterSetSlot`` is the admin verb (CLUSTER SETSLOT parity, plus a
#: bulk ``assign`` form the rebalancer pushes whole maps with).
#: ``MigrateSlot`` ``{slot, target}`` drives a live slot migration from
#: the owning node; ``MigrateInstall`` is its node→node snapshot hop
#: (``{name, blob, src_seq}``; ``{name, probe: true}`` probes the
#: target's resume point). A keyed request for a slot this node does
#: not own answers ``MOVED`` (details ``{slot, addr}``); a migrating
#: slot's missing filter answers ``ASK`` (one-shot redirect, the
#: follow-up carries ``asking: true`` — ASKING parity); an unassigned
#: slot answers ``CLUSTERDOWN``. Migration forwards additionally stamp
#: ``src_seq`` (the record's source-log seq) for the target's
#: exactly-once import gate.
CLUSTER_METHODS = frozenset(
    {"ClusterSlots", "ClusterSetSlot", "MigrateSlot", "MigrateInstall"}
)

#: The sentinel coordinator's own little gRPC service (ISSUE 4):
#: ``Topology`` (client-facing: the current epoch/primary/replicas —
#: SENTINEL get-master-addr parity), ``VoteDown`` (epoch-stamped
#: SDOWN→ODOWN leader vote), ``AnnounceTopology`` (post-failover view
#: propagation), ``Ping`` (liveness).
SENTINEL_SERVICE = "tpubloom.Sentinel"
SENTINEL_METHODS = ("Ping", "Topology", "VoteDown", "AnnounceTopology")

#: Sentinel server-streaming RPCs (ISSUE 9 satellite): ``TopologyEvents``
#: pushes the cluster view to subscribed clients — one ``{kind:
#: "topology", epoch, primary, replicas}`` frame on subscribe and on
#: every change, ``{kind: "heartbeat", epoch}`` while idle — so
#: topology-aware clients re-point on failover without waiting for a
#: refresh-on-error round trip.
SENTINEL_STREAM_METHODS = ("TopologyEvents",)


#: Wire encodings this server generation understands for bulk key
#: payloads (advertised by ``Health`` for per-connection negotiation).
#: ``msgpack`` = the original per-key ``bin`` list; ``fixed`` = the
#: ``keys_fixed`` raw-buffer form above.
ENCODINGS = ("msgpack", "fixed")

#: Sanity bound on ``keys_fixed.width`` — wider "keys" are almost
#: certainly a corrupt length field, and width*n must not be trusted to
#: allocate unbounded memory shapes.
FIXED_WIDTH_MAX = 4096


def fixed_keys(req: dict):
    """Validate and unpack a request's ``keys_fixed`` payload; returns
    ``(data, width, n)`` or None when the request uses the msgpack
    ``keys`` list. Raises :class:`BloomServiceError`
    ``INVALID_ARGUMENT`` on a malformed frame (mismatched byte count,
    non-positive width) — decode errors must be structured, not
    a reshape traceback."""
    fx = req.get("keys_fixed")
    if fx is None:
        return None
    try:
        data, width, n = fx["data"], int(fx["width"]), int(fx["n"])
    except (TypeError, KeyError, ValueError):
        raise BloomServiceError(
            "INVALID_ARGUMENT",
            "keys_fixed must be {data: bytes, width: int, n: int}",
        )
    if not isinstance(data, (bytes, bytearray, memoryview)):
        raise BloomServiceError(
            "INVALID_ARGUMENT", "keys_fixed.data must be raw bytes"
        )
    if width <= 0 or width > FIXED_WIDTH_MAX or n < 0:
        raise BloomServiceError(
            "INVALID_ARGUMENT",
            f"keys_fixed width {width} / n {n} out of range "
            f"(0 < width <= {FIXED_WIDTH_MAX}, n >= 0)",
        )
    if len(data) != width * n:
        raise BloomServiceError(
            "INVALID_ARGUMENT",
            f"keys_fixed carries {len(data)} bytes, expected "
            f"width*n = {width * n}",
        )
    return bytes(data), width, n


#: Minimum batch size before an equal-width bytes LIST auto-upgrades to
#: the fixed encoding: tiny batches gain nothing from it, and the
#: upgrade changes the op-log record shape record consumers see — keep
#: scalar/small calls byte-identical to the classic path. Numpy arrays
#: always ship fixed (passing one IS the opt-in).
FIXED_LIST_MIN = 8


def pack_fixed_keys(keys) -> dict | None:
    """Client-side: the ``keys_fixed`` payload for a batch, or None when
    the batch is not fixed-width encodable. Accepts a numpy integer
    array (canonically u64 — shipped as little-endian bytes) or a
    list/tuple of at least :data:`FIXED_LIST_MIN` equal-length
    ``bytes``."""
    import numpy as np

    if isinstance(keys, np.ndarray) and keys.ndim == 1 and keys.size:
        if keys.dtype.kind not in ("u", "i"):
            return None
        arr = np.ascontiguousarray(keys, dtype="<u8")
        return {"data": arr.tobytes(), "width": 8, "n": int(arr.size)}
    if isinstance(keys, (list, tuple)) and len(keys) >= FIXED_LIST_MIN:
        first = keys[0]
        if not isinstance(first, (bytes, bytearray)):
            return None
        width = len(first)
        if width == 0 or width > FIXED_WIDTH_MAX:
            return None
        if any(
            not isinstance(k, (bytes, bytearray)) or len(k) != width
            for k in keys
        ):
            return None
        return {"data": b"".join(bytes(k) for k in keys),
                "width": width, "n": len(keys)}
    return None


def batch_size(req: dict) -> int:
    """Key count of a request under either encoding (0 when keyless)."""
    keys = req.get("keys")
    if isinstance(keys, list):
        return len(keys)
    fx = req.get("keys_fixed")
    if isinstance(fx, dict):
        try:
            return int(fx["n"])
        except (KeyError, TypeError, ValueError):
            return 0
    return 0


def sentinel_method_path(method: str) -> str:
    return f"/{SENTINEL_SERVICE}/{method}"


def encode(msg: dict) -> bytes:
    return msgpack.packb(msg, use_bin_type=True)


def decode(data: bytes) -> dict:
    return msgpack.unpackb(data, raw=False)


def method_path(method: str) -> str:
    return f"/{SERVICE}/{method}"


def error_response(code: str, message: str, details: dict | None = None) -> dict:
    """``details`` carries structured, machine-readable error context —
    e.g. overload sheds (``RESOURCE_EXHAUSTED``/``DRAINING``) include
    ``retry_after_ms`` so clients pace their retries instead of
    hammering."""
    err: dict = {"code": code, "message": message}
    if details:
        err["details"] = details
    return {"ok": False, "error": err}


def check(resp: dict) -> dict:
    """Client-side: raise on an error response, else return it."""
    if not resp.get("ok", False):
        err = resp.get("error", {})
        raise BloomServiceError(
            err.get("code", "UNKNOWN"),
            err.get("message", ""),
            err.get("details") or {},
        )
    return resp


class BloomServiceError(RuntimeError):
    def __init__(self, code: str, message: str, details: dict | None = None):
        super().__init__(f"{code}: {message}")
        self.code = code
        self.message = message
        self.details = details or {}
