"""Server observability: counters + latency histograms.

Parity: the reference gem has no metrics; operators lean on Redis
INFO/SLOWLOG (SURVEY.md §5 "Metrics/logging/observability"). The build
equivalent pinned there: keys inserted/queried, batch sizes, kernel/request
latency, checkpoint lag, fill ratio & predicted FPR (the filter classes
provide the last two via ``stats()``).
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict


class LatencyHistogram:
    """Fixed log2 buckets from 1us to ~67s — cheap, lock-free enough."""

    BUCKETS = [2**i for i in range(27)]  # microseconds

    def __init__(self):
        self.counts = [0] * (len(self.BUCKETS) + 1)
        self.total_us = 0
        self.n = 0

    def observe(self, seconds: float) -> None:
        us = seconds * 1e6
        self.total_us += us
        self.n += 1
        for i, b in enumerate(self.BUCKETS):
            if us < b:
                self.counts[i] += 1
                return
        self.counts[-1] += 1

    def summary(self) -> dict:
        if not self.n:
            return {"n": 0}
        cum = 0
        out = {"n": self.n, "mean_us": self.total_us / self.n}
        for q in (0.5, 0.99):
            target = q * self.n
            cum = 0
            for i, c in enumerate(self.counts):
                cum += c
                if cum >= target:
                    out[f"p{int(q * 100)}_us_lt"] = (
                        self.BUCKETS[i] if i < len(self.BUCKETS) else float("inf")
                    )
                    break
        return out


class Metrics:
    """Process-wide counters + per-RPC latency histograms."""

    def __init__(self):
        self._lock = threading.Lock()
        self.counters: dict[str, int] = defaultdict(int)
        self.latency: dict[str, LatencyHistogram] = defaultdict(LatencyHistogram)
        self.started_at = time.time()

    def count(self, name: str, n: int = 1) -> None:
        with self._lock:
            self.counters[name] += n

    def time_rpc(self, method: str):
        m = self

        class _Timer:
            def __enter__(self):
                self.t0 = time.perf_counter()
                return self

            def __exit__(self, *exc):
                with m._lock:
                    m.latency[method].observe(time.perf_counter() - self.t0)

        return _Timer()

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "uptime_s": time.time() - self.started_at,
                "counters": dict(self.counters),
                "latency": {k: v.summary() for k, v in self.latency.items()},
            }
