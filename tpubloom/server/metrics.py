"""Server observability: counters + latency/phase histograms.

Parity: the reference gem has no metrics; operators lean on Redis
INFO/SLOWLOG (SURVEY.md §5 "Metrics/logging/observability"). The build
equivalent pinned there: keys inserted/queried, batch sizes, kernel/request
latency, checkpoint lag, fill ratio & predicted FPR (the filter classes
provide the last two via ``stats()``).

This module holds the in-process numbers; :mod:`tpubloom.obs.exposition`
renders them as a Prometheus scrape and :mod:`tpubloom.obs.slowlog` keeps
the per-request tail. ``Metrics.observe_rpc`` also files the per-phase
breakdown (decode/host_prep/h2d/kernel/d2h/encode) the request context
collected, keyed ``"<method>/<phase>"``.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict
from typing import Optional
from tpubloom.utils import locks


class LatencyHistogram:
    """Fixed log2 buckets from 1us to ~67s — O(1) observe via bit_length.

    Exemplars (ISSUE 9 satellite): each bucket remembers the NEWEST
    observation's request id — the OpenMetrics exemplar linking a
    latency bucket to the exact request behind it, which is the same
    rid the slowlog entry and the profiler span carry. One slot per
    bucket (last-write-wins): an exemplar is a breadcrumb, not a log.
    """

    BUCKETS = [2**i for i in range(27)]  # microsecond upper bounds

    def __init__(self):
        self.counts = [0] * (len(self.BUCKETS) + 1)
        self.total_us = 0
        self.n = 0
        #: bucket index -> {"rid", "value_s", "ts"} (newest observation)
        self.exemplars: dict = {}

    def observe(self, seconds: float, *, rid: Optional[str] = None) -> None:
        us = seconds * 1e6
        self.total_us += us
        self.n += 1
        # us < 2^i  <=>  int(us).bit_length() <= i, so bit_length IS the
        # bucket index (clamped into the overflow bucket) — no linear scan
        bucket = min(int(us).bit_length(), len(self.BUCKETS))
        self.counts[bucket] += 1
        if rid:
            self.exemplars[bucket] = {
                "rid": rid,
                "value_s": seconds,
                "ts": time.time(),
            }

    def cumulative(self) -> list:
        """Cumulative bucket counts (len(BUCKETS)+1, last = n) — the
        Prometheus ``le`` series."""
        out, cum = [], 0
        for c in self.counts:
            cum += c
            out.append(cum)
        return out

    def export(self) -> dict:
        return {
            "counts": list(self.counts),
            "total_us": self.total_us,
            "n": self.n,
            "exemplars": {k: dict(v) for k, v in self.exemplars.items()},
        }

    def summary(self) -> dict:
        if not self.n:
            return {"n": 0}
        out = {
            "n": self.n,
            "mean_us": self.total_us / self.n,
            "buckets_cum": self.cumulative(),
        }
        for q in (0.5, 0.99):
            target = q * self.n
            cum = 0
            for i, c in enumerate(self.counts):
                cum += c
                if cum >= target:
                    out[f"p{int(q * 100)}_us_lt"] = (
                        self.BUCKETS[i] if i < len(self.BUCKETS) else float("inf")
                    )
                    break
        return out


class Metrics:
    """Process-wide counters + per-RPC latency and phase histograms."""

    def __init__(self):
        self._lock = locks.named_lock("obs.metrics")
        self.counters: dict[str, int] = defaultdict(int)
        self.latency: dict[str, LatencyHistogram] = defaultdict(LatencyHistogram)
        #: "<method>/<phase>" -> histogram (same buckets as latency)
        self.phases: dict[str, LatencyHistogram] = defaultdict(LatencyHistogram)
        #: time spent blocked on the synchronous-replication gate
        #: (ISSUE 5): both the per-write commit barrier and the Wait RPC
        #: observe here — the latency cost of the durability knob
        self.waits = LatencyHistogram()
        #: tenant hydration latency (ISSUE 14): how long a paging fault
        #: takes to restore a WARM/COLD filter to device — the cost of
        #: multiplexing more tenants than HBM holds, and the number the
        #: --max-resident-bytes sizing runbook is calibrated against
        self.hydrations = LatencyHistogram()
        self.started_at = time.time()

    def count(self, name: str, n: int = 1) -> None:
        with self._lock:
            self.counters[name] += n

    def observe_rpc(
        self,
        method: str,
        seconds: float,
        phases: Optional[dict] = None,
        rid: Optional[str] = None,
    ) -> None:
        """File one finished RPC: total latency + its phase breakdown.
        ``rid`` becomes the latency AND phase buckets' exemplar — the
        slowlog / trace correlation handle (ISSUE 9 satellite; phases
        joined in ISSUE 10: a decode or h2d outlier now names the exact
        request behind it, same as the end-to-end histogram)."""
        with self._lock:
            self.latency[method].observe(seconds, rid=rid)
            for phase_name, phase_s in (phases or {}).items():
                self.phases[f"{method}/{phase_name}"].observe(
                    phase_s, rid=rid
                )

    def observe_wait(self, seconds: float) -> None:
        """File one replica-ack wait (commit barrier or Wait RPC)."""
        with self._lock:
            self.waits.observe(seconds)

    def observe_hydration(self, seconds: float) -> None:
        """File one tenant hydration (storage paging fault, ISSUE 14)."""
        with self._lock:
            self.hydrations.observe(seconds)

    def snapshot(self) -> dict:
        from tpubloom.obs import counters as global_counters

        with self._lock:
            return {
                "uptime_s": time.time() - self.started_at,
                "counters": dict(self.counters),
                "latency": {k: v.summary() for k, v in self.latency.items()},
                "phases": {k: v.summary() for k, v in self.phases.items()},
                "wait_barrier": self.waits.summary(),
                "hydration": self.hydrations.summary(),
                "process_counters": global_counters.global_counters(),
            }

    def export(self) -> dict:
        """Raw histogram data for the Prometheus renderer."""
        with self._lock:
            return {
                "uptime_s": time.time() - self.started_at,
                "counters": dict(self.counters),
                "bucket_bounds_us": list(LatencyHistogram.BUCKETS),
                "latency": {k: v.export() for k, v in self.latency.items()},
                "phases": {k: v.export() for k, v in self.phases.items()},
                "waits": self.waits.export(),
                "hydrations": self.hydrations.export(),
            }
