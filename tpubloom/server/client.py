"""Python client for the tpubloom gRPC service.

Parity: the Python-native mirror of the Ruby ``:jax`` driver (SURVEY.md §1
layer-map row L1: "Python-native API mirrors it") — same batch surface as
the local :class:`tpubloom.filter.BloomFilter`, but over the wire.

Failure handling (SURVEY.md §5 failure-detection row — "gRPC health check
+ reconnect/backoff"; the reference's redis-rb raises on connection loss
and leaves retry to the caller, the new framework does better):

* ``UNAVAILABLE`` (server down / restarting) is retried with exponential
  backoff + jitter. Safe because every retried op is idempotent — bloom
  insert/query/clear/checkpoint can be replayed freely. ``delete_batch``
  (a counting-filter counter decrement) is retryable too since ISSUE 2:
  retries reuse the logical call's rid and the server keeps a bounded
  rid→response dedup cache, so a replayed delete that already landed is
  answered from cache instead of double-decrementing.
* ``RESOURCE_EXHAUSTED`` / ``DRAINING`` (overload shed / graceful roll)
  are retried for EVERY method — a shed happens before the handler runs,
  so nothing was applied — pacing off the server's ``retry_after_ms``
  hint when it beats local backoff.
* ``NOT_FOUND`` after a server restart (the new process has not seen the
  filter yet) is healed transparently: the client replays the original
  ``create_filter`` request with ``exist_ok=True, restore=True`` — the
  server restores the newest checkpoint — then retries the op once.
* A **circuit breaker** guards the whole channel: after
  ``breaker_threshold`` consecutive *logical* transport failures (a call
  that exhausted its UNAVAILABLE retries), calls fail fast with
  ``CIRCUIT_OPEN`` for ``breaker_cooldown`` seconds instead of stacking
  more backoff on a dead server; one half-open probe then decides
  between closing and re-opening. Breaker state is exported as the
  process gauge ``client_breaker_state`` (0 closed / 1 half-open /
  2 open).

Replication-awareness (ISSUE 3):

* **read-preference routing** — construct with ``replicas=[addr, ...],
  read_preference="replica"`` and ``QueryBatch`` traffic round-robins
  over the read replicas (writes ALWAYS go to the primary). A replica
  that fails (down, lagging NOT_FOUND, READONLY confusion) falls back
  to the primary for that call — counted in
  ``client_replica_fallbacks`` — so replica loss degrades to primary
  reads, never to errors.
* **READONLY redirect** — a write answered with ``READONLY`` (the
  configured "primary" is actually a replica, e.g. mid-failover) is
  retried once against the primary address the replica's error details
  advertise (Redis MOVED-style), transparently re-pointing the client.
* **retryable non-idempotent inserts** — counting/scalable/presence
  inserts are now auto-retried on ``UNAVAILABLE`` like DeleteBatch:
  retries reuse the logical call's rid and the server answers a replay
  whose first attempt landed from its rid→response cache instead of
  double-applying. (Servers older than ISSUE 3 do not cache inserts —
  pin ``max_retries=0`` per call-site if you must talk to one.)

Durability (ISSUE 5 — Redis ``WAIT`` / ``min-replicas-to-write``
parity):

* every mutating response carries the op-log ``repl_seq`` of its record
  (tracked as ``self.last_write_seq``); :meth:`BloomClient.wait`
  blocks until N replicas acknowledged it and returns the achieved
  count (WAIT semantics — short counts report, they do not raise);
* ``insert_batch`` / ``delete_batch`` / ``clear`` accept a per-call
  ``min_replicas=`` (+ ``min_replicas_timeout_ms=``): the server blocks
  the RPC after its op-log append until that many replicas acked the
  record. A barrier that times out raises ``NOT_ENOUGH_REPLICAS`` —
  deliberately NOT auto-retried (the write applied and is logged; the
  caller decides whether to re-wait via :meth:`wait`, retry under the
  same rid, or surface the degraded durability).

Observability: every RPC is stamped with a generated request id
(``self.last_rid`` after the call) which the server folds into its
profiler spans and slowlog entries — ``slowlog_get()`` entries carry the
same ids, so a slow call seen client-side can be found server-side.
Retries of one logical call share the rid.
"""

from __future__ import annotations

import queue
import random
import threading
import time
from typing import Optional, Sequence

import grpc
import numpy as np

from tpubloom.obs import counters as obs_counters
from tpubloom.obs import flight as obs_flight
from tpubloom.obs import trace as obs_trace
from tpubloom.obs.context import new_rid
from tpubloom.server import protocol
from tpubloom.utils import locks

#: error codes meaning "the server refused BEFORE running the handler" —
#: replaying is safe for every method, idempotent or not
_SHED_CODES = frozenset({"RESOURCE_EXHAUSTED", "DRAINING"})

#: methods eligible for replica routing under read_preference="replica".
#: Deliberately narrow: Stats/Slowlog are per-host diagnostics (you want
#: the host you asked), Health is a liveness probe of its target.
_REPLICA_READS = frozenset({"QueryBatch"})

_CHANNEL_OPTIONS = list(protocol.CHANNEL_OPTIONS)

_BREAKER_GAUGE = {"closed": 0, "half-open": 1, "open": 2}


def fetch_topology(
    sentinels: Sequence[str], *, timeout: float = 2.0
) -> Optional[dict]:
    """Ask each sentinel for the current cluster view (``SENTINEL
    get-master-addr-by-name`` parity); first answer wins. Returns
    ``{"epoch", "primary", "replicas"}`` or None when no sentinel is
    reachable."""
    for addr in sentinels:
        channel = grpc.insecure_channel(addr)
        try:
            raw = channel.unary_unary(
                protocol.sentinel_method_path("Topology"),
                request_serializer=lambda b: b,
                response_deserializer=lambda b: b,
            )(protocol.encode({}), timeout=timeout)
            resp = protocol.decode(raw)
            if resp.get("ok") and resp.get("primary"):
                return resp
        except grpc.RpcError:
            continue
        finally:
            channel.close()
    return None


class CircuitOpenError(protocol.BloomServiceError):
    """Raised without touching the network while the breaker is open."""

    def __init__(self, address: str, cooldown_left: float):
        super().__init__(
            "CIRCUIT_OPEN",
            f"circuit to {address} is open for another "
            f"{cooldown_left:.2f}s after consecutive transport failures",
        )


class CircuitBreaker:
    """Per-channel fail-fast: K consecutive logical transport failures
    open the circuit for a cooldown; one half-open probe then decides.

    Counts *logical* calls (after each call's own UNAVAILABLE backoff is
    exhausted), not raw attempts — a single patient call riding out a
    restart must not trip the breaker. ``threshold=0`` disables."""

    def __init__(self, threshold: int = 5, cooldown: float = 5.0):
        self.threshold = threshold
        self.cooldown = cooldown
        self._consecutive = 0
        self._state = "closed"
        self._opened_at = 0.0
        self._half_open_at = 0.0
        self._lock = locks.named_lock("client.breaker")
        obs_counters.set_gauge("client_breaker_state", 0)

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def _set_state(self, state: str) -> None:
        self._state = state
        obs_counters.set_gauge("client_breaker_state", _BREAKER_GAUGE[state])
        # flight recorder (ISSUE 15): breaker flips are exactly the
        # lifecycle breadcrumbs a post-mortem of a client-side outage
        # needs (note() under the breaker lock only touches
        # obs.counters — the declared client.breaker -> obs.counters
        # edge, same as the gauge above)
        obs_flight.note("breaker", state=state)

    def check(self, address: str) -> None:
        """Raise :class:`CircuitOpenError` while open; transition to
        half-open (admitting exactly this one probe) once the cooldown
        has elapsed."""
        if not self.threshold:
            return
        with self._lock:
            if self._state == "closed":
                return
            now = time.monotonic()
            if self._state == "open":
                elapsed = now - self._opened_at
                if elapsed >= self.cooldown:
                    self._set_state("half-open")
                    self._half_open_at = now
                    return  # this caller is the probe
                raise CircuitOpenError(address, self.cooldown - elapsed)
            # half-open: one probe at a time — but a probe that vanished
            # without reaching record_* (interrupt, encode error) must not
            # wedge the breaker forever, so a stale probe slot reopens
            # after another cooldown
            elapsed = now - self._half_open_at
            if elapsed >= self.cooldown:
                self._half_open_at = now
                return
            raise CircuitOpenError(address, self.cooldown - elapsed)

    def record_success(self) -> None:
        if not self.threshold:
            return
        with self._lock:
            self._consecutive = 0
            if self._state != "closed":
                self._set_state("closed")
                obs_counters.incr("breaker_closed")

    def record_failure(self) -> None:
        if not self.threshold:
            return
        with self._lock:
            self._consecutive += 1
            tripped = (
                self._state == "half-open"
                or (self._state == "closed"
                    and self._consecutive >= self.threshold)
            )
            if tripped:
                self._set_state("open")
                self._opened_at = time.monotonic()
                obs_counters.incr("breaker_opened")


class ServerStream:
    """Iterable over one server-streaming RPC, decoding each msgpack
    frame; ``cancel()`` tears the stream down (safe mid-iteration)."""

    def __init__(self, call):
        self._call = call

    def __iter__(self):
        for raw in self._call:
            yield protocol.decode(raw)

    def cancel(self) -> None:
        self._call.cancel()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.cancel()


class StreamSession:
    """One live bidi ingest stream (ISSUE 18): the client half of
    ``InsertStream``/``QueryStream``. Obtain via
    :meth:`BloomClient.insert_stream` / :meth:`BloomClient.query_stream`
    and use as a context manager; :meth:`send` ships one seq-stamped
    frame (blocking only when the server's credit window is exhausted —
    that IS the flow control), acks are consumed by a background reader
    and surfaced through :meth:`result` / :meth:`drain`.

    Exactly-once replay: every frame keeps its ORIGINAL rid for its
    whole lifetime. When the transport dies mid-stream (server SIGKILL,
    network cut), the next ``send``/``drain`` reconnects — refreshing
    the topology first when sentinels are configured — and re-sends
    only the still-unacked frames, in seq order, under those original
    rids; the server's rid→response dedup cache (rebuilt from the op
    log's merged-record ``parts`` across restarts) answers any frame
    whose first flight already applied, so nothing double-applies even
    on counting filters. Reconnects are budgeted like unary retries
    (``client.max_retries``, reset by any successful ack).

    Single-producer: one thread drives ``send``/``drain``/``result``;
    the internal reader is the only other toucher of session state.
    """

    def __init__(self, client: "BloomClient", method: str, name: str,
                 *, defaults: Optional[dict] = None):
        self._client = client
        self._method = method  # "InsertStream" | "QueryStream"
        self._name = name
        self._defaults = dict(defaults or {})
        self._cond = locks.named_condition("client.stream")
        self._seq = 0
        #: seq -> frame dict still awaiting its ack — THE replay source
        self._unacked: dict = {}
        self._results: dict = {}
        self._credit = 0  # 0 until the server's hello grants a window
        self._broken: Optional[BaseException] = None
        self._failed: Optional[BaseException] = None
        self._closed = False
        self._connects = 0
        self._sendq: "queue.Queue" = queue.Queue()
        self._call = None
        self._reader: Optional[threading.Thread] = None
        self._connect()

    # -- transport ------------------------------------------------------------

    def _connect(self) -> None:
        self._sendq = sendq = queue.Queue()

        def frames():
            while True:
                item = sendq.get()
                if item is None:
                    return
                yield item

        call = self._client._bidi_calls[self._method](frames(), timeout=None)
        with self._cond:
            self._call = call
            self._credit = 0
            self._broken = None
        # replay first, in seq order, original rids: these frames were
        # inside the PREVIOUS grant's window, so jumping the fresh
        # hello is at worst a brief over-send the server parks
        for seq in sorted(self._unacked):
            sendq.put(protocol.encode(self._unacked[seq]))
        self._reader = threading.Thread(
            target=self._read_loop, args=(call,),
            name="tpubloom-stream-reader", daemon=True,
        )
        self._reader.start()

    def _read_loop(self, call) -> None:
        client = self._client
        try:
            for raw in call:
                frame = protocol.decode(raw)
                kind = frame.get("kind")
                if kind == "hello":
                    with self._cond:
                        self._credit = max(1, int(frame.get("credit") or 1))
                        self._cond.notify_all()
                    continue
                if kind == "credit":
                    # server-initiated shrink on an idle stream (ISSUE
                    # 19 satellite): adopt the tighter window so the
                    # next burst can't overrun a coalescer other
                    # streams filled while this one sent nothing
                    with self._cond:
                        self._credit = max(1, int(frame.get("credit") or 1))
                        self._cond.notify_all()
                    continue
                if kind != "ack":
                    continue
                resp = frame.get("resp") or {}
                if resp.get("repl_seq") is not None:
                    client.last_write_seq = int(resp["repl_seq"])
                seq = frame.get("seq")
                with self._cond:
                    self._unacked.pop(seq, None)
                    if seq is not None:
                        self._results[seq] = resp
                    self._credit = max(1, int(frame.get("credit") or 1))
                    self._connects = 0  # progress resets the budget
                    self._cond.notify_all()
        except grpc.RpcError as e:
            with self._cond:
                if self._call is call and not self._closed:
                    self._broken = e
                self._cond.notify_all()
            return
        # clean end-of-stream with frames unanswered = the server died
        # after half-close but before draining — same replay path
        with self._cond:
            if self._call is call and self._unacked and not self._closed:
                self._broken = protocol.BloomServiceError(
                    "UNAVAILABLE",
                    f"{self._method} ended with "
                    f"{len(self._unacked)} unacked frame(s)",
                )
            self._cond.notify_all()

    def _reconnect(self) -> None:
        client = self._client
        with self._cond:
            err = self._broken
            if err is None:
                return
            self._connects += 1
            n = self._connects
            if n > client.max_retries:
                self._failed = err
                raise err
        old = self._call
        if old is not None:
            old.cancel()
        reader = self._reader
        if reader is not None:
            reader.join(timeout=5.0)
        time.sleep(
            min(client.backoff_max, client.backoff_base * (2 ** (n - 1)))
            * (0.5 + random.random())
        )
        moved = False
        if client.sentinels:
            # the primary may have MOVED across the kill — follow the
            # sentinels' view before replaying (the rebuilt _bidi_calls
            # point at the fresh channel)
            try:
                moved = client.refresh_topology()
            except Exception:  # noqa: BLE001 — reconnect is best-effort
                pass
        if not moved:
            # same address: swap the dead channel for a fresh one, or
            # gRPC's grown connect backoff makes every remaining retry
            # fail fast against the stale subchannel while the server
            # restart is already accepting connections
            client._rebuild_primary_channel()
        self._connect()

    # -- producer API ---------------------------------------------------------

    def send(self, keys, **overrides) -> int:
        """Ship one frame; returns its seq. Blocks while the credit
        window is full (or the hello has not landed yet) — the server's
        backpressure, not an error. ``overrides`` are per-frame wire
        fields (``return_presence``, ``min_replicas``, ...)."""
        locks.note_blocking("client.stream")
        client = self._client
        if self._failed is not None:
            raise self._failed
        self._seq += 1
        seq = self._seq
        frame = {"seq": seq, "rid": new_rid(), "name": self._name}
        frame.update(self._defaults)
        frame.update(overrides)
        client._encode_keys(frame, keys)
        if (
            self._method == "InsertStream"
            and client.epoch is not None
            and "epoch" not in frame
        ):
            frame["epoch"] = client.epoch
        if client.trace_sample > 0 and obs_trace.hit(
            frame["rid"], client.trace_sample
        ):
            frame["trace"] = {
                "forced": True, "span": obs_trace.new_span_id(),
            }
        while True:
            with self._cond:
                if self._failed is not None:
                    raise self._failed
                broken = self._broken
                if broken is None:
                    if len(self._unacked) < self._credit:
                        self._unacked[seq] = frame
                        sendq = self._sendq
                        break
                    self._cond.wait(timeout=0.05)
                    continue
            self._reconnect()
        sendq.put(protocol.encode(frame))
        return seq

    def drain(self, timeout: float = 60.0) -> list:
        """Block until every sent frame is acked (reconnecting/replaying
        as needed); returns the raw per-frame responses in seq order.
        Per-frame verdicts — including error maps — are the entries;
        use :meth:`result` for raise-on-error access to one frame."""
        deadline = time.monotonic() + timeout
        while True:
            with self._cond:
                if self._failed is not None:
                    raise self._failed
                broken = self._broken
                if broken is None:
                    if not self._unacked:
                        return [
                            self._results[s] for s in sorted(self._results)
                        ]
                    self._cond.wait(timeout=0.05)
            if broken is not None:
                self._reconnect()
            if time.monotonic() > deadline:
                raise protocol.BloomServiceError(
                    "DEADLINE_EXCEEDED",
                    f"stream drain: {len(self._unacked)} frame(s) still "
                    f"unacked after {timeout:.0f}s",
                )

    def result(self, seq: int, timeout: float = 60.0) -> dict:
        """This frame's verdict, exactly as the unary call would have
        answered (raises :class:`protocol.BloomServiceError` on an
        error verdict — ``protocol.check`` semantics)."""
        deadline = time.monotonic() + timeout
        while True:
            with self._cond:
                if seq in self._results:
                    return protocol.check(dict(self._results[seq]))
                if self._failed is not None:
                    raise self._failed
                broken = self._broken
                if broken is None:
                    self._cond.wait(timeout=0.05)
            if broken is not None:
                self._reconnect()
            if time.monotonic() > deadline:
                raise protocol.BloomServiceError(
                    "DEADLINE_EXCEEDED",
                    f"stream result: seq {seq} unacked after {timeout:.0f}s",
                )

    @property
    def unacked(self) -> int:
        with self._cond:
            return len(self._unacked)

    def close(self, timeout: float = 30.0) -> None:
        """Drain (best-effort), half-close the send side, wait for the
        server to finish the stream. Never raises — a session used via
        ``with`` must tear down even after a terminal failure."""
        with self._cond:
            if self._closed:
                return
        try:
            self.drain(timeout=timeout)
        except Exception:  # noqa: BLE001 — teardown path
            pass
        with self._cond:
            self._closed = True
        self._sendq.put(None)
        reader = self._reader
        if reader is not None:
            reader.join(timeout=timeout)
        call = self._call
        if call is not None:
            call.cancel()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class BloomClient:
    """Blocking client; one instance per channel, filters addressed by name."""

    def __init__(
        self,
        address: Optional[str] = None,
        *,
        timeout: float = 60.0,
        max_retries: int = 5,
        backoff_base: float = 0.2,
        backoff_max: float = 5.0,
        breaker_threshold: int = 5,
        breaker_cooldown: float = 5.0,
        replicas: Optional[Sequence[str]] = None,
        read_preference: str = "primary",
        sentinels: Optional[Sequence[str]] = None,
        topology: Optional[dict] = None,
        encoding: str = "auto",
        trace_sample: float = 0.0,
    ):
        """``replicas`` + ``read_preference="replica"`` route QueryBatch
        traffic round-robin over read replicas (writes always hit
        ``address``); a failing replica falls back to the primary for
        that call.

        Topology-awareness (ISSUE 4): pass ``sentinels=[addr, ...]``
        (resolved + cached with its epoch; refreshed on ``READONLY`` /
        ``UNAVAILABLE`` / ``STALE_EPOCH``, so writes fail over to the
        new primary — rid-dedup server-side guarantees a re-driven
        acknowledged batch never double-applies) or a static
        ``topology={"epoch", "primary", "replicas"}``. Either may stand
        in for ``address``/``replicas``.

        ``encoding`` (ISSUE 10): ``"auto"`` (default) ships
        fixed-width-encodable key batches — numpy u64 arrays, or lists
        of equal-length bytes — as the zero-copy ``fixed`` wire
        encoding once a ``Health`` probe confirmed this connection's
        server supports it (negotiated per-connection, re-probed after
        a failover re-point); ``"msgpack"`` pins the classic per-key
        list; ``"fixed"`` is ``auto`` that raises no error either — it
        simply falls back when the server or the key shape can't."""
        if read_preference not in ("primary", "replica"):
            raise ValueError(
                f"read_preference must be 'primary' or 'replica', "
                f"got {read_preference!r}"
            )
        if encoding not in ("auto", "fixed", "msgpack"):
            raise ValueError(
                f"encoding must be 'auto', 'fixed' or 'msgpack', "
                f"got {encoding!r}"
            )
        self.encoding = encoding
        #: distributed tracing (ISSUE 15): fraction of logical calls
        #: this client traces (deterministic per rid). A traced call
        #: records a local ``client.hop`` span and stamps ``trace =
        #: {"forced": true, "span": <hop id>}`` on the wire so every
        #: server hop captures its tree under the same rid regardless
        #: of server-side sampling. 0.0 (the default) adds NO wire
        #: fields and no per-call work.
        self.trace_sample = float(trace_sample)
        if self.trace_sample > 0:
            obs_trace.ensure_enabled()
        #: None = not yet probed for THIS connection; True/False once a
        #: Health answer settled whether the server speaks `fixed`
        self._fixed_negotiated: Optional[bool] = None
        self.sentinels = list(sentinels or ())
        #: cached topology epoch — stamped on mutating requests so a
        #: server under a newer topology answers STALE_EPOCH and we
        #: refresh instead of writing under a stale map
        self.epoch: Optional[int] = None
        if topology is None and self.sentinels:
            topology = fetch_topology(self.sentinels)
        if topology is not None:
            self.epoch = int(topology.get("epoch") or 0)
            address = topology.get("primary") or address
            if replicas is None:
                replicas = topology.get("replicas")
        if address is None:
            if self.sentinels:
                # the caller asked for sentinel-resolved routing: falling
                # back to a hardcoded default here would silently connect
                # to the wrong (or a stale) node
                raise protocol.BloomServiceError(
                    "NO_TOPOLOGY",
                    f"no sentinel of {self.sentinels} answered and no "
                    f"explicit address was given",
                )
            address = "127.0.0.1:50051"
        self.address = address
        self.timeout = timeout
        self.max_retries = max_retries
        self.backoff_base = backoff_base
        self.backoff_max = backoff_max
        self.read_preference = read_preference
        self.breaker = CircuitBreaker(breaker_threshold, breaker_cooldown)
        self.last_rid: Optional[str] = None
        #: op-log seq of this client's newest acknowledged write — what
        #: :meth:`wait` asks the durability quorum about (WAIT parity)
        self.last_write_seq: Optional[int] = None
        self._creations: dict[str, dict] = {}
        self._channel = grpc.insecure_channel(address, options=_CHANNEL_OPTIONS)
        self._calls = self._make_calls(self._channel)
        self._stream_calls = self._make_stream_calls(self._channel)
        self._bidi_calls = self._make_bidi_calls(self._channel)
        #: (address, channel, calls) per read replica, round-robined
        self._replicas: list = []
        for addr in replicas or ():
            ch = grpc.insecure_channel(addr, options=_CHANNEL_OPTIONS)
            self._replicas.append((addr, ch, self._make_calls(ch)))
        self._rr = 0
        #: channels replaced by the topology-PUSH thread (ISSUE 9
        #: satellite): retired instead of closed at swap time — an
        #: in-flight call on the old channel must fail over through the
        #: normal retry path, not die on an out-of-band close. Bounded:
        #: only the newest few stay open (older ones have had ample
        #: grace by the next topology change); the rest close in
        #: :meth:`_retire_channel`, the remainder at :meth:`close`.
        self._retired_channels: list = []
        #: serializes topology adoption between the push thread and
        #: user threads' refresh-on-error — an unlocked epoch compare
        #: could interleave so an OLDER view is applied last
        self._topo_lock = locks.named_lock("client.topology")
        self._push_stop: Optional[threading.Event] = None
        self._push_thread: Optional[threading.Thread] = None
        self._push_call = None

    @staticmethod
    def _make_calls(channel) -> dict:
        return {
            m: channel.unary_unary(
                protocol.method_path(m),
                request_serializer=lambda b: b,
                response_deserializer=lambda b: b,
            )
            for m in protocol.METHODS
        }

    @staticmethod
    def _make_stream_calls(channel) -> dict:
        return {
            m: channel.unary_stream(
                protocol.method_path(m),
                request_serializer=lambda b: b,
                response_deserializer=lambda b: b,
            )
            for m in protocol.STREAM_METHODS
        }

    @staticmethod
    def _make_bidi_calls(channel) -> dict:
        return {
            m: channel.stream_stream(
                protocol.method_path(m),
                request_serializer=lambda b: b,
                response_deserializer=lambda b: b,
            )
            for m in protocol.BIDI_STREAM_METHODS
        }

    def _call_once(
        self, method: str, req: dict, calls=None, timeout: Optional[float] = None
    ) -> dict:
        calls = self._calls if calls is None else calls
        raw = calls[method](
            protocol.encode(req),
            timeout=self.timeout if timeout is None else timeout,
        )
        return protocol.check(protocol.decode(raw))

    def _call_timeout(self, method: str, req: dict) -> Optional[float]:
        """Per-call gRPC deadline: a server legitimately blocking on a
        replica quorum (commit barrier / Wait) for longer than
        ``self.timeout`` must not be killed by the client first — the
        deadline stretches to the requested wait plus margin. ``Wait``
        with ``timeout_ms<=0`` means "server cap" (60s), so allow that
        much."""
        wait_ms = req.get("min_replicas_timeout_ms")
        if method == "Wait":
            wait_ms = req.get("timeout_ms")
            if wait_ms is not None and int(wait_ms) <= 0:
                wait_ms = 60_000  # the server's WAIT_TIMEOUT_CAP_S
        if not wait_ms:
            return None
        return max(self.timeout, int(wait_ms) / 1000.0 + 5.0)

    def _try_replica(self, method: str, req: dict) -> Optional[dict]:
        """One replica attempt for a routed read; None = fall back to the
        primary path (replica down, still syncing, or otherwise unable)."""
        # snapshot the pool: the topology-push thread REPLACES
        # self._replicas wholesale, so indexing the attribute twice
        # could race an adoption into IndexError/ZeroDivisionError
        replicas = self._replicas
        if (
            not replicas
            or self.read_preference != "replica"
            or method not in _REPLICA_READS
        ):
            return None
        self._rr = rr = (self._rr + 1) % len(replicas)
        addr, _, calls = replicas[rr % len(replicas)]
        try:
            return self._call_once(method, req, calls)
        except (grpc.RpcError, protocol.BloomServiceError):
            # includes NOT_FOUND from a replica that has not yet synced
            # the filter — the primary answers authoritatively
            obs_counters.incr("client_replica_fallbacks")
            return None

    def _follow_primary(self, address: str, *, close_old: bool = True) -> None:
        """READONLY redirect: re-point the primary channel (the old
        channel is closed; replica channels are untouched).
        ``close_old=False`` retires the old channel instead of closing
        it — the topology-push thread swaps channels while calls may be
        in flight on the old one."""
        old = self._channel
        self.address = address
        self._channel = grpc.insecure_channel(address, options=_CHANNEL_OPTIONS)
        self._calls = self._make_calls(self._channel)
        self._stream_calls = self._make_stream_calls(self._channel)
        self._bidi_calls = self._make_bidi_calls(self._channel)
        # per-CONNECTION capability: the new primary re-negotiates
        self._fixed_negotiated = None
        if close_old:
            old.close()
        else:
            self._retire_channel(old)
        obs_counters.incr("client_primary_redirects")

    def _set_replicas(
        self, addrs: Sequence[str], *, close_old: bool = True
    ) -> None:
        """Replace the replica channel pool (topology refresh).
        ``close_old=False`` retires dropped channels instead of closing
        them — the PUSH thread swaps the pool while replica reads may
        be in flight, and an out-of-band close would kill them instead
        of letting the replica-fallback path absorb the loss."""
        keep = {a: (a, ch, calls) for a, ch, calls in self._replicas}
        fresh = []
        for addr in addrs:
            if addr in keep:
                fresh.append(keep.pop(addr))
            else:
                ch = grpc.insecure_channel(addr, options=_CHANNEL_OPTIONS)
                fresh.append((addr, ch, self._make_calls(ch)))
        for _, ch, _ in keep.values():
            if close_old:
                ch.close()
            else:
                self._retire_channel(ch)
        self._replicas = fresh
        self._rr = 0

    def _rebuild_primary_channel(self) -> None:
        """Re-dial the primary on a FRESH channel (same address). A
        killed server leaves the old channel in TRANSIENT_FAILURE with
        gRPC's internal connect backoff growing toward minutes, so
        calls created on it fail fast without ever re-dialing — a
        stream reconnect budget can exhaust while the server is already
        back up. Swapping the channel makes each budgeted retry perform
        an immediate dial instead. The old channel is retired, not
        closed — sibling threads may still have calls in flight on it."""
        with self._topo_lock:
            old = self._channel
            self._channel = grpc.insecure_channel(
                self.address, options=_CHANNEL_OPTIONS
            )
            self._calls = self._make_calls(self._channel)
            self._stream_calls = self._make_stream_calls(self._channel)
            self._bidi_calls = self._make_bidi_calls(self._channel)
            self._retire_channel(old)

    def _retire_channel(self, ch) -> None:
        self._retired_channels.append(ch)
        while len(self._retired_channels) > 8:
            # anything older than the last few swaps has had ample
            # grace for its in-flight calls — close it, or a long-lived
            # push-enabled client leaks a channel per failover
            self._retired_channels.pop(0).close()

    def _adopt_topology(self, topo: dict, *, close_old: bool = True) -> bool:
        """Adopt one sentinel view iff its epoch is not older than the
        cached one; True iff the PRIMARY changed. Serialized: the push
        thread and user-thread refreshes must not interleave their
        epoch compare-and-apply, or an older view can be applied last."""
        with self._topo_lock:
            epoch = int(topo.get("epoch") or 0)
            if self.epoch is not None and epoch < self.epoch:
                return False
            self.epoch = epoch
            changed = (
                bool(topo.get("primary")) and topo["primary"] != self.address
            )
            if changed:
                self._follow_primary(topo["primary"], close_old=close_old)
            self._set_replicas(topo.get("replicas") or (), close_old=close_old)
            return changed

    def refresh_topology(self) -> bool:
        """Re-resolve the cluster view from the sentinel list; adopt it
        iff its epoch is not older than the cached one. True iff the
        PRIMARY changed (the signal that a retried write should reset
        its backoff — it now targets a different process)."""
        if not self.sentinels:
            return False
        topo = fetch_topology(self.sentinels)
        if topo is None:
            return False
        obs_counters.incr("client_topology_refreshes")
        # retire (never close) the swapped channels: with the push
        # thread or any multi-threaded use, an out-of-band close would
        # kill a sibling thread's in-flight call instead of letting it
        # fail over through the retry path; the retire cap bounds them
        return self._adopt_topology(topo, close_old=False)

    # -- sentinel topology push (ISSUE 9 satellite) --------------------------

    def enable_topology_push(self) -> bool:
        """Subscribe to the sentinels' ``TopologyEvents`` server-stream
        on a background thread: failovers re-point this client the
        moment the sentinel announces them, instead of waiting for the
        next error-triggered refresh (refresh-on-error stays as the
        fallback — a dead push stream degrades, it does not break).
        Returns False (no thread) when the client has no sentinels."""
        if not self.sentinels or self._push_thread is not None:
            return False
        self._push_stop = threading.Event()
        self._push_thread = threading.Thread(
            target=self._topology_push_loop,
            name="tpubloom-topology-push",
            daemon=True,
        )
        self._push_thread.start()
        return True

    def _topology_push_loop(self) -> None:
        stop = self._push_stop
        backoff = 0.2
        # randomized order: every client of the fleet gets the same
        # sentinel list, and each subscriber parks a worker on its
        # sentinel for the stream lifetime — spreading subscriptions
        # keeps any one sentinel's pool free for election RPCs (the
        # sentinel additionally caps subscribers and answers
        # SUBSCRIBERS_FULL, which lands here as an ended stream)
        order = list(self.sentinels)
        random.shuffle(order)
        while not stop.is_set():
            for addr in order:
                if stop.is_set():
                    return
                channel = grpc.insecure_channel(addr)
                try:
                    call = channel.unary_stream(
                        protocol.sentinel_method_path("TopologyEvents"),
                        request_serializer=lambda b: b,
                        response_deserializer=lambda b: b,
                    )(protocol.encode({}), timeout=None)
                    self._push_call = call
                    for raw in call:
                        if stop.is_set():
                            return
                        frame = protocol.decode(raw)
                        if frame.get("kind") != "topology":
                            continue  # heartbeat keeps the stream alive
                        backoff = 0.2  # a live stream resets the backoff
                        if self._adopt_topology(frame, close_old=False):
                            obs_counters.incr("client_topology_pushes")
                except grpc.RpcError:
                    pass
                except Exception:  # noqa: BLE001 — the push is best-effort
                    pass
                finally:
                    self._push_call = None
                    channel.close()
            stop.wait(backoff * (0.5 + random.random()))
            backoff = min(5.0, backoff * 2)

    def _rpc(self, method: str, req: dict, *, rid: Optional[str] = None) -> dict:
        # request-correlation id: one per LOGICAL call (retries and the
        # NOT_FOUND heal's final retry share it); exposed as last_rid so
        # callers can find their request in the server slowlog/trace.
        # DeleteBatch and non-idempotent InsertBatch retries lean on this
        # id: the server's dedup cache answers a replayed rid from cache
        # instead of re-applying. Callers spanning MULTIPLE _rpc calls
        # per logical op (the cluster client's redirect healing) pass
        # ``rid=`` so every hop shares one id.
        locks.note_blocking("client.rpc")
        self.last_rid = rid = rid or new_rid()
        req = {**req, "rid": rid}
        if self.epoch is not None and method in protocol.MUTATING_METHODS:
            req["epoch"] = self.epoch
        # distributed tracing (ISSUE 15): a traced call records one
        # local client.hop span per _rpc (cluster redirect follow-ups
        # call _rpc again → sibling hops under the same rid) and forces
        # server-side capture via the wire trace field. Untraced calls
        # take the exact pre-ISSUE-15 path: no field, no timers.
        # TraceGet itself is exempt — assembling a trace must not
        # inject lookup spans into (or evict spans out of) the very
        # rings it is reading.
        if (
            method == "TraceGet"
            or self.trace_sample <= 0
            or not obs_trace.hit(rid, self.trace_sample)
        ):
            return self._rpc_attempts(method, req)
        hop = obs_trace.new_span_id()
        req["trace"] = {"forced": True, "span": hop}
        w0, t0 = time.time(), time.perf_counter()
        code = "OK"
        try:
            return self._rpc_attempts(method, req)
        except protocol.BloomServiceError as e:
            code = e.code
            raise
        except grpc.RpcError:
            code = "UNAVAILABLE"
            raise
        finally:
            obs_trace.record_span(
                "client.hop",
                rid=rid,
                span=hop,
                start=w0,
                duration_s=time.perf_counter() - t0,
                attrs={"method": method, "addr": self.address, "code": code},
            )

    def _rpc_attempts(self, method: str, req: dict) -> dict:
        """The retry/heal loop of one logical call (split from
        :meth:`_rpc` so the tracing wrapper brackets every hop)."""
        rid = req["rid"]
        routed = self._try_replica(method, req)
        if routed is not None:
            return routed
        # fail fast while the breaker is open — no network, no backoff
        self.breaker.check(self.address)
        recreated = False
        redirected = False
        failover_reset = False
        stale_refreshed = False
        attempt = 0
        shed_attempt = 0
        call_timeout = self._call_timeout(method, req)
        while True:
            try:
                resp = self._call_once(method, req, timeout=call_timeout)
                self.breaker.record_success()
                if resp.get("repl_seq") is not None:
                    self.last_write_seq = int(resp["repl_seq"])
                return resp
            except grpc.RpcError as e:
                if e.code() is grpc.StatusCode.UNAVAILABLE and self.sentinels:
                    # the primary may be mid-failover: re-resolve the
                    # topology. A changed primary resets the retry budget
                    # ONCE — the retry targets a different process, and
                    # the rid guarantees an already-applied batch answers
                    # from the dedup cache instead of double-applying.
                    if self.refresh_topology() and not failover_reset:
                        failover_reset = True
                        attempt = 0
                        if self.epoch is not None and "epoch" in req:
                            req["epoch"] = self.epoch
                        continue
                if (
                    e.code() is not grpc.StatusCode.UNAVAILABLE
                    or attempt >= self.max_retries
                ):
                    # one LOGICAL failure (own retries exhausted) = one
                    # breaker strike — patient riders don't trip it
                    self.breaker.record_failure()
                    raise
                delay = min(
                    self.backoff_max, self.backoff_base * (2 ** attempt)
                ) * (0.5 + random.random())
                time.sleep(delay)
                attempt += 1
            except protocol.BloomServiceError as e:
                # an application-level answer means the transport is fine
                self.breaker.record_success()
                if e.code == "STALE_EPOCH" and not stale_refreshed:
                    # our cached topology predates a failover: adopt the
                    # server's epoch, re-resolve, retry once under the
                    # fresh view
                    stale_refreshed = True
                    server_epoch = e.details.get("epoch")
                    if server_epoch is not None:
                        self.epoch = max(self.epoch or 0, int(server_epoch))
                    self.refresh_topology()
                    if self.epoch is not None and "epoch" in req:
                        req["epoch"] = self.epoch
                    continue
                if e.code in _SHED_CODES:
                    # shed BEFORE execution — safe to replay any method,
                    # even the non-idempotent ones; pace off the server's
                    # hint when it beats local backoff
                    if shed_attempt >= self.max_retries:
                        raise
                    delay = min(
                        self.backoff_max,
                        self.backoff_base * (2 ** shed_attempt),
                    )
                    hint_ms = e.details.get("retry_after_ms")
                    if hint_ms:
                        delay = max(delay, hint_ms / 1000.0)
                    time.sleep(delay * (0.75 + random.random() / 2))
                    shed_attempt += 1
                    continue
                if e.code == "READONLY" and not redirected:
                    # the "primary" we were pointed at is a replica
                    # (failover, stale config). Its error advertises the
                    # real primary — follow it once, Redis-MOVED-style;
                    # with sentinels, their view wins over the hint
                    # (mid-failover a replica may not know its new
                    # primary yet).
                    redirected = True
                    if self.sentinels and self.refresh_topology():
                        if self.epoch is not None and "epoch" in req:
                            req["epoch"] = self.epoch
                        continue
                    primary = e.details.get("primary")
                    if not primary or primary == self.address:
                        raise
                    self._follow_primary(primary)
                    continue
                # Heal a restarted server: replay the remembered creation
                # (restores the newest checkpoint), then retry the op once.
                creation = self._creations.get(req.get("name", ""))
                if (
                    e.code != "NOT_FOUND"
                    or method in ("CreateFilter", "DropFilter")
                    or recreated
                    or creation is None
                ):
                    raise
                # through _rpc, not _call_once: the heal itself must ride
                # out UNAVAILABLE if the server is still coming up
                self._rpc(
                    "CreateFilter",
                    {**creation, "exist_ok": True, "restore": True},
                )
                self.last_rid = rid  # the heal is internal; report ours
                recreated = True

    # -- service-level -------------------------------------------------------

    def health(self) -> dict:
        return self._rpc("Health", {})

    def wait_ready(
        self,
        timeout: float = 30.0,
        poll: float = 0.1,
        *,
        accept_degraded: bool = True,
    ) -> dict:
        """Block until the server is actually serving, not merely until the
        channel connects: the gRPC channel comes up before restore-on-create
        and warm-up finish, so callers racing the service would see
        NOT_FOUND churn. Polls the Health RPC until it reports ``SERVING``
        — or ``DEGRADED`` too by default, since a degraded server (e.g. it
        quarantined a corrupt checkpoint on restore) IS serving and may
        stay degraded until its next good checkpoint; pass
        ``accept_degraded=False`` to insist on fully healthy. Servers
        predating the status field count as SERVING. Returns the final
        health response; raises TimeoutError otherwise."""
        ready = {"SERVING", "DEGRADED"} if accept_degraded else {"SERVING"}
        deadline = time.monotonic() + timeout
        grpc.channel_ready_future(self._channel).result(timeout=timeout)
        last: object = None
        while True:
            try:
                h = self.health()
                if h.get("status", "SERVING") in ready:
                    return h
                last = h
            except (grpc.RpcError, protocol.BloomServiceError) as e:
                # includes CircuitOpenError: keep polling until the
                # breaker's cooldown lets the next probe through
                last = e
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"server at {self.address} not ready within "
                    f"{timeout}s (last: {last!r})"
                )
            time.sleep(poll)

    def create_filter(
        self,
        name: str,
        *,
        capacity: Optional[int] = None,
        error_rate: Optional[float] = None,
        config: Optional[dict] = None,
        exist_ok: bool = False,
        restore: bool = True,
        scalable: bool = False,
        growth: int = 2,
        tightening: float = 0.5,
        **options,
    ) -> dict:
        """``scalable=True`` creates a scalable (layered) filter: it grows
        past ``capacity`` by pushing larger, tighter layers while the
        compound FPR stays below ``error_rate / (1 - tightening)``.
        Scalable filters are sized by capacity/error_rate (not a raw
        ``config``); ``options`` become the base layer template
        (key_len, block_bits, seed, ...)."""
        req: dict = {"name": name, "exist_ok": exist_ok, "restore": restore}
        if scalable:
            if config is not None:
                raise ValueError(
                    "scalable filters are sized by capacity/error_rate, "
                    "not a raw config"
                )
            req["capacity"] = capacity
            req["error_rate"] = error_rate
            req["options"] = options
            req["scalable"] = {"growth": growth, "tightening": tightening}
        elif config is not None:
            req["config"] = config
        else:
            req["capacity"] = capacity
            req["error_rate"] = error_rate
            req["options"] = options
        resp = self._rpc("CreateFilter", req)
        # Bare attaches (no config, no capacity) adopt the server's config —
        # remember the adopted config so the NOT_FOUND heal can replay a
        # well-formed creation.
        if config is None and capacity is None:
            if "scalable" in resp:
                # replay a scalable creation: policy from the response,
                # base template = adopted config minus the placeholder m/k
                opts = {
                    k: v
                    for k, v in resp["config"].items()
                    if k not in ("m", "k", "key_name")
                }
                self._creations[name] = {
                    "name": name,
                    "capacity": resp["scalable"]["capacity"],
                    "error_rate": resp["scalable"]["error_rate"],
                    "options": opts,
                    "scalable": {
                        "growth": resp["scalable"]["growth"],
                        "tightening": resp["scalable"]["tightening"],
                    },
                }
            else:
                self._creations[name] = {"name": name, "config": resp["config"]}
        else:
            self._creations[name] = req
        return resp

    def drop_filter(self, name: str, *, final_checkpoint: bool = True) -> dict:
        resp = self._rpc(
            "DropFilter", {"name": name, "final_checkpoint": final_checkpoint}
        )
        self._creations.pop(name, None)  # only forget once the drop landed
        return resp

    def list_filters(self) -> list:
        return self._rpc("ListFilters", {})["filters"]

    # -- per-filter ops ------------------------------------------------------

    @staticmethod
    def _keys(keys) -> list:
        if isinstance(keys, np.ndarray):
            # integer keys through the msgpack path: each key ships as
            # its little-endian u64 bytes (the fixed encoding's twin)
            arr = np.ascontiguousarray(keys, dtype="<u8")
            return [arr[i].tobytes() for i in range(arr.size)]
        return [k.encode() if isinstance(k, str) else bytes(k) for k in keys]

    def _fixed_ok(self) -> bool:
        """Lazy per-connection negotiation: one Health probe decides
        whether this server speaks the ``fixed`` encoding. Probe
        failures degrade to msgpack for this connection — never an
        error."""
        if self.encoding == "msgpack":
            return False
        if self._fixed_negotiated is None:
            try:
                h = self._rpc("Health", {})
                self._fixed_negotiated = "fixed" in (h.get("encodings") or ())
            except (grpc.RpcError, protocol.BloomServiceError):
                self._fixed_negotiated = False
        return bool(self._fixed_negotiated)

    def _encode_keys(self, req: dict, keys) -> dict:
        """Fold the key batch into ``req`` under the best negotiated
        encoding (ISSUE 10): fixed-width-encodable batches (numpy
        integer arrays — canonically u64 — or equal-length bytes) ship
        as ONE raw buffer the server decodes zero-copy; everything else
        takes the msgpack list path."""
        # negotiation first — it is one cached-bool check after the
        # initial Health probe, while pack_fixed_keys copies the whole
        # batch (wasted per call against a msgpack-only server)
        if self.encoding != "msgpack" and self._fixed_ok():
            fx = protocol.pack_fixed_keys(keys)
            if fx is not None:
                req["keys_fixed"] = fx
                return req
        req["keys"] = self._keys(keys)
        return req

    @staticmethod
    def _durability(req: dict, min_replicas, timeout_ms) -> dict:
        """Fold the per-call durability override into a request (ISSUE
        5): the server blocks the RPC until ``min_replicas`` replicas
        acked its record (NOT_ENOUGH_REPLICAS on timeout)."""
        if min_replicas is not None:
            req["min_replicas"] = int(min_replicas)
        if timeout_ms is not None:
            req["min_replicas_timeout_ms"] = int(timeout_ms)
        return req

    def insert_batch(
        self,
        name: str,
        keys,
        *,
        return_presence: bool = False,
        min_replicas: Optional[int] = None,
        min_replicas_timeout_ms: Optional[int] = None,
    ):
        """Insert a batch; with ``return_presence`` also get each key's
        membership BEFORE the batch (fused test-and-insert server-side —
        the dedup primitive). Returns the insert count, or the presence
        bool array when requested. ``min_replicas`` demands a per-call
        durability quorum stronger than the server default."""
        req = self._durability(
            self._encode_keys({"name": name}, keys),
            min_replicas, min_replicas_timeout_ms,
        )
        if not return_presence:
            return self._rpc("InsertBatch", req)["n"]
        req["return_presence"] = True
        # retryable since ISSUE 3: retries reuse the rid and the server
        # answers a replay whose first attempt landed from its dedup
        # cache (same machinery as DeleteBatch), presence bits included
        resp = self._rpc("InsertBatch", req)
        return self._unpack_bool(resp, "presence")

    @staticmethod
    def _unpack_bool(resp: dict, field: str) -> np.ndarray:
        if field not in resp:
            raise protocol.BloomServiceError(
                "UNSUPPORTED",
                f"server response has no '{field}' field — the server is "
                f"probably too old for this request (got {sorted(resp)})",
            )
        return np.unpackbits(
            np.frombuffer(resp[field], np.uint8), count=resp["n"]
        ).astype(bool)

    def include_batch(self, name: str, keys) -> np.ndarray:
        resp = self._rpc(
            "QueryBatch", self._encode_keys({"name": name}, keys)
        )
        return self._unpack_bool(resp, "hits")

    def delete_batch(
        self,
        name: str,
        keys: Sequence[bytes | str],
        *,
        min_replicas: Optional[int] = None,
        min_replicas_timeout_ms: Optional[int] = None,
    ) -> int:
        """Counting-filter delete. Auto-retried like any other op: retries
        reuse the call's rid and the server's dedup cache answers a replay
        whose first attempt already landed, so no double-decrement."""
        req = self._durability(
            {"name": name, "keys": self._keys(keys)},
            min_replicas, min_replicas_timeout_ms,
        )
        return self._rpc("DeleteBatch", req)["n"]

    def insert(self, name: str, key: bytes | str) -> None:
        self.insert_batch(name, [key])

    def include(self, name: str, key: bytes | str) -> bool:
        return bool(self.include_batch(name, [key])[0])

    def clear(
        self,
        name: str,
        *,
        min_replicas: Optional[int] = None,
        min_replicas_timeout_ms: Optional[int] = None,
    ) -> None:
        self._rpc(
            "Clear",
            self._durability(
                {"name": name}, min_replicas, min_replicas_timeout_ms
            ),
        )

    def wait(
        self,
        numreplicas: int,
        timeout_ms: int = 1000,
        *,
        seq: Optional[int] = None,
    ) -> int:
        """Redis ``WAIT`` parity: block until ``numreplicas`` replicas
        have acknowledged this client's last write (or ``seq``), up to
        ``timeout_ms``; returns how many actually acked — possibly
        fewer (WAIT reports, it does not raise). With no prior write
        the server gates on its current log head."""
        req: dict = {
            "numreplicas": int(numreplicas),
            "timeout_ms": int(timeout_ms),
        }
        target = self.last_write_seq if seq is None else seq
        if target is not None:
            req["seq"] = int(target)
        return self._rpc("Wait", req)["nreplicas"]

    def stats(self, name: Optional[str] = None) -> dict:
        resp = self._rpc("Stats", {"name": name} if name else {})
        return resp.get("stats", resp.get("server"))

    def checkpoint(self, name: str, *, wait: bool = True) -> dict:
        return self._rpc("Checkpoint", {"name": name, "wait": wait})

    # -- sketch plane (ISSUE 19): cuckoo / count-min / top-k -----------------

    def _remember_sketch_creation(self, name: str, resp: dict) -> None:
        """Sketch reserves heal like bloom creations: remember the
        server-adopted config so the NOT_FOUND heal can replay it."""
        if isinstance(resp.get("config"), dict):
            self._creations[name] = {"name": name, "config": resp["config"]}

    def cf_reserve(
        self, name: str, capacity: int, *, exist_ok: bool = False, **options
    ) -> dict:
        """Create a cuckoo filter sized for ``capacity`` keys
        (RedisBloom ``CF.RESERVE``)."""
        req: dict = {
            "name": name, "capacity": int(capacity), "exist_ok": exist_ok,
        }
        if options:
            req["options"] = options
        resp = self._rpc("CFReserve", req)
        self._remember_sketch_creation(name, resp)
        return resp

    def cf_add(
        self,
        name: str,
        keys,
        *,
        min_replicas: Optional[int] = None,
        min_replicas_timeout_ms: Optional[int] = None,
    ) -> np.ndarray:
        """Add keys to a cuckoo filter. Returns a bool array: True per
        key that landed, False per key the (honestly) FULL table
        rejected — unlike a bloom filter, a cuckoo filter refuses
        rather than silently degrade its FPR."""
        req = self._durability(
            self._encode_keys({"name": name}, keys),
            min_replicas, min_replicas_timeout_ms,
        )
        resp = self._rpc("CFAdd", req)
        if "full" in resp:
            return ~self._unpack_bool(resp, "full")
        return np.ones(int(resp["n"]), dtype=bool)

    def cf_del(
        self,
        name: str,
        keys,
        *,
        min_replicas: Optional[int] = None,
        min_replicas_timeout_ms: Optional[int] = None,
    ) -> np.ndarray:
        """Delete ONE stored copy per key from a cuckoo filter
        (``CF.DEL``). Returns per-key bools: True where a copy
        existed and was removed. Retries reuse the rid; the dedup
        cache absorbs replays, so no double-remove."""
        req = self._durability(
            {"name": name, "keys": self._keys(keys)},
            min_replicas, min_replicas_timeout_ms,
        )
        return self._unpack_bool(self._rpc("CFDel", req), "deleted")

    def cf_exists(self, name: str, keys) -> np.ndarray:
        """Cuckoo membership (``CF.EXISTS``, batched) — no false
        negatives; false-positive rate bounded by the fingerprint."""
        resp = self._rpc(
            "CFExists", self._encode_keys({"name": name}, keys)
        )
        return self._unpack_bool(resp, "hits")

    def cms_init_by_dim(
        self, name: str, width: int, depth: int, *,
        exist_ok: bool = False, **options,
    ) -> dict:
        """Create a count-min sketch (``CMS.INITBYDIM``); width rounds
        up to a multiple of 32 (error bound only tightens)."""
        req: dict = {
            "name": name, "width": int(width), "depth": int(depth),
            "exist_ok": exist_ok,
        }
        if options:
            req["options"] = options
        resp = self._rpc("CMSInitByDim", req)
        self._remember_sketch_creation(name, resp)
        return resp

    def cms_incrby(
        self,
        name: str,
        keys,
        increments: Optional[Sequence[int]] = None,
        *,
        min_replicas: Optional[int] = None,
        min_replicas_timeout_ms: Optional[int] = None,
    ) -> Optional[list]:
        """Increment key counts (``CMS.INCRBY``). Weighted increments
        return the post-update estimates; unit increments (or None)
        ride the coalesced insert path and return None — follow with
        :meth:`cms_query` when you need the counts."""
        req = self._durability(
            {"name": name, "keys": self._keys(keys)},
            min_replicas, min_replicas_timeout_ms,
        )
        if increments is not None:
            req["increments"] = [int(i) for i in increments]
        resp = self._rpc("CMSIncrBy", req)
        counts = resp.get("counts")
        return [int(c) for c in counts] if counts is not None else None

    def cms_query(self, name: str, keys) -> np.ndarray:
        """Point estimates (``CMS.QUERY``) — each only ever >= the
        true count."""
        resp = self._rpc(
            "CMSQuery", {"name": name, "keys": self._keys(keys)}
        )
        return np.asarray(resp["counts"], dtype=np.uint32)

    def topk_reserve(
        self, name: str, topk: int, *, width: int = 2048, depth: int = 5,
        exist_ok: bool = False, **options,
    ) -> dict:
        """Create a top-``topk`` heavy-hitter sketch (``TOPK.RESERVE``)."""
        req: dict = {
            "name": name, "topk": int(topk), "width": int(width),
            "depth": int(depth), "exist_ok": exist_ok,
        }
        if options:
            req["options"] = options
        resp = self._rpc("TopKReserve", req)
        self._remember_sketch_creation(name, resp)
        return resp

    def topk_add(
        self,
        name: str,
        keys,
        *,
        min_replicas: Optional[int] = None,
        min_replicas_timeout_ms: Optional[int] = None,
    ) -> int:
        """Count occurrences into a top-k sketch (``TOPK.ADD``)."""
        req = self._durability(
            self._encode_keys({"name": name}, keys),
            min_replicas, min_replicas_timeout_ms,
        )
        return int(self._rpc("TopKAdd", req)["n"])

    def topk_list(self, name: str) -> list:
        """Current heavy hitters as ``(key_bytes, estimate)`` pairs,
        estimate-descending (``TOPK.LIST WITHCOUNT``)."""
        resp = self._rpc("TopKList", {"name": name})
        return [(item["key"], int(item["count"])) for item in resp["items"]]

    # -- high availability (ISSUE 4) -----------------------------------------

    def promote(
        self,
        *,
        epoch: Optional[int] = None,
        repl_log_dir: Optional[str] = None,
    ) -> dict:
        """Promote the server this client points at from replica to
        primary (``REPLICAOF NO ONE`` parity). ``repl_log_dir`` names
        the op-log dir the REMOTE process should adopt when it was
        started without one."""
        req: dict = {}
        if epoch is not None:
            req["epoch"] = epoch
        if repl_log_dir:
            req["repl_log_dir"] = repl_log_dir
        return self._rpc("Promote", req)

    def replica_of(
        self, primary: Optional[str], *, epoch: Optional[int] = None
    ) -> dict:
        """Redis ``REPLICAOF`` parity: re-point the server at a new
        primary (or pass None / ``"NO ONE"`` to promote it)."""
        req: dict = {"primary": primary}
        if epoch is not None:
            req["epoch"] = epoch
        return self._rpc("ReplicaOf", req)

    # -- cluster mode (ISSUE 9) ----------------------------------------------

    def cluster_slots(self) -> dict:
        """This node's slot-map view (Redis ``CLUSTER SLOTS`` parity):
        ``{enabled, epoch, ranges, migrating, importing}``. Routed
        cluster traffic wants :class:`tpubloom.cluster.ClusterClient`;
        this is the per-node admin/bootstrap probe."""
        return self._rpc("ClusterSlots", {})

    def cluster_set_slot(self, **req) -> dict:
        """Admin verb (``CLUSTER SETSLOT`` parity): ``slot=/state=/addr=``
        or the bulk ``assign=[[start, end, addr], ...], epoch=`` form."""
        return self._rpc("ClusterSetSlot", req)

    def migrate_slot(self, slot: int, target: str) -> dict:
        """Drive the live migration of ``slot`` from this node (its
        owner) to ``target``; blocks until the handoff finalizes."""
        return self._rpc("MigrateSlot", {"slot": int(slot), "target": target})

    def migrate_install_probe(self, name: str) -> dict:
        """Resume probe of the migration target's import gate for one
        filter (``{"have": <source seq>|None}``) — the node→node
        ``MigrateInstall`` hop's read-only form, exposed for tooling."""
        return self._rpc("MigrateInstall", {"name": name, "probe": True})

    # -- observability -------------------------------------------------------

    def slowlog_get(self, n: Optional[int] = None) -> list:
        """Slowest server requests (slowest first), Redis SLOWLOG GET
        parity. Entries carry the rid this client stamped on each call."""
        req = {"n": n} if n is not None else {}
        return self._rpc("SlowlogGet", req)["entries"]

    def trace_get(self, rid: Optional[str] = None) -> list:
        """Distributed-tracing lookup (ISSUE 15): the spans the
        CONNECTED node recorded for one rid (default: this client's
        last call), plus coalescer flush spans that link it. Assemble
        cross-node views with ``ClusterClient.trace``."""
        resp = self._rpc("TraceGet", {"trace_rid": rid or self.last_rid})
        return resp.get("spans") or []

    def trace_get_fan(self, rid: str) -> list:
        """Best-effort ``TraceGet`` against the primary AND every
        configured replica channel — a replica's ``repl.apply`` spans
        live in ITS ring, not the primary's. Unreachable nodes are
        skipped (a trace lookup must never fail a post-mortem)."""
        spans: list = []
        try:
            spans.extend(self.trace_get(rid))
        except (grpc.RpcError, protocol.BloomServiceError):
            pass
        for _addr, _ch, calls in list(self._replicas):
            try:
                resp = self._call_once(
                    "TraceGet", {"trace_rid": rid}, calls
                )
                spans.extend(resp.get("spans") or [])
            except (grpc.RpcError, protocol.BloomServiceError):
                continue
        return spans

    def slowlog_reset(self) -> int:
        """Clear the server slowlog; returns how many entries dropped."""
        return self._rpc("SlowlogReset", {})["cleared"]

    def monitor(self, name: Optional[str] = None) -> "ServerStream":
        """Redis ``MONITOR`` parity: a live stream of every request the
        server finishes, as dicts (``kind: hello/op/heartbeat``), with
        optional per-filter-name filtering (which MONITOR itself cannot
        do). Iterate the returned stream; ``.cancel()`` to stop."""
        req = {"name": name} if name else {}
        return ServerStream(
            self._stream_calls["Monitor"](protocol.encode(req), timeout=None)
        )

    def insert_stream(
        self,
        name: str,
        *,
        return_presence: bool = False,
        min_replicas: Optional[int] = None,
        min_replicas_timeout_ms: Optional[int] = None,
    ) -> "StreamSession":
        """Open a persistent ``InsertStream`` (ISSUE 18): one bidi RPC
        carrying many seq-stamped insert frames with pipelined per-frame
        acks — InsertBatch semantics per frame (presence fusion,
        durability quorums, dedup replay safety) without per-call RPC
        setup. The keyword defaults stamp every frame; ``send`` can
        override per frame. Use as a context manager::

            with client.insert_stream("events") as s:
                for batch in batches:
                    s.send(batch)
                results = s.drain()
        """
        defaults: dict = {}
        if return_presence:
            defaults["return_presence"] = True
        if min_replicas is not None:
            defaults["min_replicas"] = int(min_replicas)
        if min_replicas_timeout_ms is not None:
            defaults["min_replicas_timeout_ms"] = int(min_replicas_timeout_ms)
        return StreamSession(self, "InsertStream", name, defaults=defaults)

    def query_stream(self, name: str) -> "StreamSession":
        """Open a persistent ``QueryStream``: QueryBatch semantics per
        frame, acks carry packed hit bitmaps (unpack with
        ``np.unpackbits(np.frombuffer(resp["hits"], np.uint8),
        count=resp["n"])``)."""
        return StreamSession(self, "QueryStream", name)

    def repl_stream(self, cursor: Optional[int] = None) -> "ServerStream":
        """Raw access to the replication changefeed (what a replica
        consumes): ``full_sync_begin/snapshot/full_sync_end/partial_sync/
        record/heartbeat`` frames. Mostly for tooling/tests — run a real
        replica with ``python -m tpubloom.server --replica-of``."""
        req = {"cursor": cursor} if cursor is not None else {}
        return ServerStream(
            self._stream_calls["ReplStream"](protocol.encode(req), timeout=None)
        )

    def close(self) -> None:
        if self._push_stop is not None:
            self._push_stop.set()
            call = self._push_call
            if call is not None:
                call.cancel()
            self._push_thread.join(timeout=5.0)
            self._push_thread = None
            self._push_stop = None
        self._channel.close()
        for ch in self._retired_channels:
            ch.close()
        self._retired_channels = []
        for _, ch, _ in self._replicas:
            ch.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
