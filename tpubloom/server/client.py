"""Python client for the tpubloom gRPC service.

Parity: the Python-native mirror of the Ruby ``:jax`` driver (SURVEY.md §1
layer-map row L1: "Python-native API mirrors it") — same batch surface as
the local :class:`tpubloom.filter.BloomFilter`, but over the wire.
"""

from __future__ import annotations

from typing import Optional, Sequence

import grpc
import numpy as np

from tpubloom.server import protocol


class BloomClient:
    """Blocking client; one instance per channel, filters addressed by name."""

    def __init__(self, address: str = "127.0.0.1:50051", *, timeout: float = 60.0):
        self.address = address
        self.timeout = timeout
        self._channel = grpc.insecure_channel(
            address,
            options=[
                ("grpc.max_receive_message_length", 256 * 1024 * 1024),
                ("grpc.max_send_message_length", 256 * 1024 * 1024),
            ],
        )
        self._calls = {
            m: self._channel.unary_unary(
                protocol.method_path(m),
                request_serializer=lambda b: b,
                response_deserializer=lambda b: b,
            )
            for m in protocol.METHODS
        }

    def _rpc(self, method: str, req: dict) -> dict:
        raw = self._calls[method](protocol.encode(req), timeout=self.timeout)
        return protocol.check(protocol.decode(raw))

    # -- service-level -------------------------------------------------------

    def health(self) -> dict:
        return self._rpc("Health", {})

    def wait_ready(self, timeout: float = 30.0) -> dict:
        grpc.channel_ready_future(self._channel).result(timeout=timeout)
        return self.health()

    def create_filter(
        self,
        name: str,
        *,
        capacity: Optional[int] = None,
        error_rate: Optional[float] = None,
        config: Optional[dict] = None,
        exist_ok: bool = False,
        restore: bool = True,
        **options,
    ) -> dict:
        req: dict = {"name": name, "exist_ok": exist_ok, "restore": restore}
        if config is not None:
            req["config"] = config
        else:
            req["capacity"] = capacity
            req["error_rate"] = error_rate
            req["options"] = options
        return self._rpc("CreateFilter", req)

    def drop_filter(self, name: str, *, final_checkpoint: bool = True) -> dict:
        return self._rpc(
            "DropFilter", {"name": name, "final_checkpoint": final_checkpoint}
        )

    def list_filters(self) -> list:
        return self._rpc("ListFilters", {})["filters"]

    # -- per-filter ops ------------------------------------------------------

    @staticmethod
    def _keys(keys: Sequence[bytes | str]) -> list:
        return [k.encode() if isinstance(k, str) else bytes(k) for k in keys]

    def insert_batch(self, name: str, keys: Sequence[bytes | str]) -> int:
        return self._rpc("InsertBatch", {"name": name, "keys": self._keys(keys)})["n"]

    def include_batch(self, name: str, keys: Sequence[bytes | str]) -> np.ndarray:
        resp = self._rpc("QueryBatch", {"name": name, "keys": self._keys(keys)})
        return np.unpackbits(
            np.frombuffer(resp["hits"], np.uint8), count=resp["n"]
        ).astype(bool)

    def delete_batch(self, name: str, keys: Sequence[bytes | str]) -> int:
        return self._rpc("DeleteBatch", {"name": name, "keys": self._keys(keys)})["n"]

    def insert(self, name: str, key: bytes | str) -> None:
        self.insert_batch(name, [key])

    def include(self, name: str, key: bytes | str) -> bool:
        return bool(self.include_batch(name, [key])[0])

    def clear(self, name: str) -> None:
        self._rpc("Clear", {"name": name})

    def stats(self, name: Optional[str] = None) -> dict:
        resp = self._rpc("Stats", {"name": name} if name else {})
        return resp.get("stats", resp.get("server"))

    def checkpoint(self, name: str, *, wait: bool = True) -> dict:
        return self._rpc("Checkpoint", {"name": name, "wait": wait})

    def close(self) -> None:
        self._channel.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
