"""Host-side runtime: gRPC service, RESP (Redis protocol) client, metrics."""
