"""Deterministic fault injection (ISSUE 2 tentpole).

The reference gem's failure story was "redis-rb raises and the caller
retries"; ours (retry/backoff, NOT_FOUND heal, checkpoint restore,
overload shedding) is only as good as the faults it has actually been
driven through. This module is the driving rig: a process-global
registry of **named fault points** that production code calls into
(:func:`fire`), and **trigger policies** tests or operators arm against
them (:func:`arm`). Disarmed — the normal state — a fault point costs
one dict lookup.

Fault points (the stable vocabulary; :data:`KNOWN_POINTS`):

* ``ckpt.write``        — inside ``FileSink.put`` before the tmp write
* ``ckpt.fsync``        — inside ``FileSink.put`` before fsync+rename
* ``ckpt.restore_read`` — inside ``FileSink.get`` before reading a blob
* ``rpc.pre_handle``    — in the server RPC wrapper before the handler
* ``rpc.post_handle``   — after the handler, before the response encodes
* ``repl.append``       — inside ``OpLog.append`` before bytes are written
* ``repl.stream_send``  — in the primary's ReplStream generator before
  each snapshot/record send (kills a replication stream mid-batch)
* ``repl.apply``        — in the replica/replay apply path before a
  record's handler runs
* ``repl.reappend``     — on a chained replica, before an applied record
  re-appends to the local op log (ISSUE 4)
* ``repl.ack``          — replica side, before an ack frame ships on the
  ``ReplAck`` stream; a firing DROPS that frame (ack loss in flight —
  the periodic re-ack heals it once disarmed) (ISSUE 5)
* ``repl.ack_recv``     — primary side, per ack frame received; a firing
  kills the ack stream (the replica re-opens it on its next heartbeat)
* ``ha.promote``        — at the top of replica→primary promotion
* ``ha.vote``           — in the sentinel vote-request/grant path
* ``cluster.migrate_send`` — slot migration, source side: before each
  probe/snapshot-install/tail-record send to the new owner (ISSUE 9)
* ``cluster.migrate_apply`` — slot migration, target side: in
  ``MigrateInstall`` and per gated dual-write forward received
* ``ingest.coalesce``     — in ``IngestCoalescer.submit`` before a
  request parks (nothing applied — retry-safe) (ISSUE 10)
* ``ingest.flush``        — in the ingest dispatcher before a coalesced
  flush applies (ditto; every parked request in the flush errors)
* ``storage.evict``       — in the residency manager before an eviction
  takes the victim's lock; a firing ABORTS the eviction cleanly — the
  tenant stays resident and serving (ISSUE 14)
* ``storage.hydrate``     — before a paged tenant's hydration restores;
  nothing published — the faulted request errors, a retry re-hydrates
* ``shard.insert`` / ``shard.query`` / ``shard.delete`` — per-shard
  points in :class:`tpubloom.parallel.sharded.ShardedBloomFilter`:
  fired once per shard the batch routes to, with ``shard=<index>``
  context — arm with a ``shard=N`` predicate for partial failures
* ``dist.initialize``   — in ``initialize_multihost`` before joining

Trigger policies (``policy`` argument / env syntax):

* ``always``            — every pass through the point fires
* ``once``              — exactly one firing, then the fault disarms
* ``nth:N``             — every Nth pass fires (1-indexed: pass N, 2N, ...)
* ``prob:P[:seed=S]``   — each pass fires with probability P from a
  seeded PRNG, so a "random" chaos run replays byte-identically

Modes decide what a firing does: ``raise`` (default) raises
:class:`InjectedFault` from inside the point; ``torn`` is returned to
the caller as a directive — only points that know how to tear their own
work honor it (``ckpt.write`` truncates the blob mid-write, the torn-
file case CRC validation must catch). A ``times=K`` cap bounds any
policy to K total firings.

**Predicates** (ISSUE 4): a point may fire with context
(``fire("shard.insert", shard=3)``); an armed fault with a predicate
(``arm(..., pred={"shard": 3})`` / env ``shard.insert=always:shard=3``)
only triggers on passes whose context matches every predicate item —
passes that don't match don't consume the policy budget.

Arming: tests call :func:`arm` / :func:`disarm` / :func:`reset`
directly; operators set ``TPUBLOOM_FAULTS`` before process start, e.g.::

    TPUBLOOM_FAULTS="ckpt.fsync=once,rpc.pre_handle=prob:0.01:seed=7"
    TPUBLOOM_FAULTS="ckpt.write=nth:3:mode=torn:times=2"
    TPUBLOOM_FAULTS="shard.insert=once:shard=2"

Every firing increments the process-global counters
``faults_injected`` and ``fault_<point>`` (dots become underscores), so
a chaos run is auditable from ``/metrics`` like any other event.
"""

from __future__ import annotations

import os
import random
import threading
from typing import Optional

from tpubloom.obs import counters as _counters
from tpubloom.utils import locks

ENV_VAR = "TPUBLOOM_FAULTS"

#: The registered fault-point names. ``arm`` rejects unknown points so a
#: typo'd chaos config fails loudly instead of silently injecting nothing.
KNOWN_POINTS = {
    "ckpt.write",
    "ckpt.fsync",
    "ckpt.restore_read",
    "rpc.pre_handle",
    "rpc.post_handle",
    "repl.append",
    "repl.stream_send",
    "repl.apply",
    "repl.reappend",
    "repl.ack",
    "repl.ack_recv",
    "ha.promote",
    "ha.vote",
    "cluster.migrate_send",
    "cluster.migrate_apply",
    "ingest.coalesce",
    "ingest.flush",
    "stream.recv",
    "stream.ack",
    "cuckoo.kick",
    "cms.update",
    "storage.evict",
    "storage.hydrate",
    "shard.insert",
    "shard.query",
    "shard.delete",
    "dist.initialize",
}

MODES = ("raise", "torn")

_lock = locks.named_lock("faults.registry")
_armed: dict[str, "_Fault"] = {}
_env_loaded = False


class InjectedFault(RuntimeError):
    """What an armed ``mode="raise"`` fault point raises.

    Deliberately a plain RuntimeError subclass: production error paths
    must treat it like any real I/O or handler failure — code that
    special-cases InjectedFault is testing the test, not the system.
    """

    def __init__(self, point: str):
        super().__init__(f"injected fault at {point!r}")
        self.point = point


def register_point(name: str) -> None:
    """Extend the vocabulary (subsystems grown later add theirs here)."""
    with _lock:
        KNOWN_POINTS.add(name)


class _Fault:
    """One armed fault: policy + mode + remaining-firings budget."""

    __slots__ = ("point", "policy", "mode", "times", "pred", "_passes",
                 "_nth", "_prob", "_rng", "fired")

    def __init__(
        self,
        point: str,
        policy: str,
        mode: str,
        times: Optional[int],
        pred: Optional[dict] = None,
    ):
        self.point = point
        self.policy = policy
        self.mode = mode
        self.times = times
        self.pred = pred or {}
        self._passes = 0
        self.fired = 0
        self._nth = 0
        self._prob = 0.0
        self._rng: Optional[random.Random] = None
        if policy == "always":
            pass
        elif policy == "once":
            self.times = 1
        elif policy.startswith("nth:"):
            self._nth = int(policy.split(":", 1)[1])
            if self._nth < 1:
                raise ValueError(f"nth policy needs N >= 1, got {self._nth}")
        elif policy.startswith("prob:"):
            parts = policy.split(":")
            self._prob = float(parts[1])
            if not 0.0 <= self._prob <= 1.0:
                raise ValueError(f"prob policy needs 0 <= P <= 1, got {self._prob}")
            seed = 0
            for p in parts[2:]:
                if p.startswith("seed="):
                    seed = int(p[len("seed="):])
            self._rng = random.Random(seed)
        else:
            raise ValueError(
                f"unknown fault policy {policy!r} "
                "(want always | once | nth:N | prob:P[:seed=S])"
            )

    def matches(self, ctx: dict) -> bool:
        """True iff every predicate item equals the pass context (string
        comparison, so ``shard=3`` from the env matches ``shard=3`` the
        int). A pass that doesn't match doesn't consume the budget."""
        return all(
            str(ctx.get(key)) == str(want) for key, want in self.pred.items()
        )

    def should_fire(self) -> bool:
        """One pass through the point; True iff the fault triggers now."""
        if self.times is not None and self.fired >= self.times:
            return False
        self._passes += 1
        if self._nth:
            hit = self._passes % self._nth == 0
        elif self._rng is not None:
            hit = self._rng.random() < self._prob
        else:  # always / once
            hit = True
        if hit:
            self.fired += 1
        return hit

    def describe(self) -> dict:
        return {
            "point": self.point,
            "policy": self.policy,
            "mode": self.mode,
            "times": self.times,
            "pred": dict(self.pred),
            "passes": self._passes,
            "fired": self.fired,
        }


def arm(
    point: str,
    policy: str = "always",
    *,
    mode: str = "raise",
    times: Optional[int] = None,
    pred: Optional[dict] = None,
) -> None:
    """Arm ``point`` with a trigger policy (replacing any previous arm).
    ``pred`` restricts firing to passes whose :func:`fire` context
    matches every item (e.g. ``pred={"shard": 2}``)."""
    if point not in KNOWN_POINTS:
        raise ValueError(
            f"unknown fault point {point!r} (known: {sorted(KNOWN_POINTS)})"
        )
    if mode not in MODES:
        raise ValueError(f"unknown fault mode {mode!r} (want one of {MODES})")
    fault = _Fault(point, policy, mode, times, pred)
    with _lock:
        _armed[point] = fault


def disarm(point: str) -> bool:
    """Disarm one point; True if it was armed."""
    with _lock:
        return _armed.pop(point, None) is not None


def reset() -> None:
    """Disarm everything (test isolation; also forgets env-var arming)."""
    global _env_loaded
    with _lock:
        _armed.clear()
        _env_loaded = True  # an explicit reset overrides the env config


def active() -> list[dict]:
    """Describe every armed fault (policy, mode, pass/fire counts)."""
    with _lock:
        return [f.describe() for f in _armed.values()]


def is_armed(point: str) -> bool:
    """True iff a fault is currently armed at ``point`` — lets callers
    skip expensive context computation (e.g. host-side shard routing)
    on the normal, disarmed path."""
    if not _env_loaded:
        load_env()
    return point in _armed


def fire(point: str, **ctx) -> Optional[str]:
    """Production-code hook: pass through fault point ``point``.

    Disarmed (or armed-but-not-triggering): returns None, and the caller
    proceeds normally. Triggering with ``mode="raise"``: raises
    :class:`InjectedFault`. Triggering with a directive mode (``torn``):
    returns the mode string — the caller implements the directive (and
    callers that don't know the directive treat it as None, which keeps
    directive faults safe to arm against any point). ``ctx`` carries
    pass context matched against the armed fault's predicate
    (``fire("shard.insert", shard=2)``).
    """
    if not _env_loaded:
        load_env()
    fault = _armed.get(point)
    if fault is None:
        return None
    with _lock:
        if (
            _armed.get(point) is not fault
            or not fault.matches(ctx)
            or not fault.should_fire()
        ):
            return None
    _counters.incr("faults_injected")
    _counters.incr("fault_" + point.replace(".", "_"))
    if fault.mode == "raise":
        raise InjectedFault(point)
    return fault.mode


def load_env(force: bool = False) -> None:
    """Parse ``TPUBLOOM_FAULTS`` once (idempotent; the first ``fire`` of
    the process also calls this — the server calls it eagerly at startup
    so armed faults are logged before traffic arrives). ``force``
    re-parses even after a previous load/reset (tests).

    Syntax: comma-separated ``point=policy[:mode=M][:times=K][:key=V...]``
    items; the policy may itself carry colons (``nth:3``,
    ``prob:0.1:seed=7``); any other ``key=V`` part becomes a predicate
    item (``shard.insert=once:shard=2``).
    """
    global _env_loaded
    with _lock:
        if _env_loaded and not force:
            return
        _env_loaded = True
    spec = os.environ.get(ENV_VAR, "").strip()
    if not spec:
        return
    for item in spec.split(","):
        item = item.strip()
        if not item:
            continue
        point, _, rest = item.partition("=")
        mode, times, policy_parts = "raise", None, []
        pred: dict = {}
        for part in rest.split(":"):
            if part.startswith("mode="):
                mode = part[len("mode="):]
            elif part.startswith("times="):
                times = int(part[len("times="):])
            elif part.startswith("seed=") or "=" not in part:
                # seed= belongs to the prob policy; bare parts are policy
                policy_parts.append(part)
            else:
                key, _, val = part.partition("=")
                pred[key] = val
        arm(point.strip(), ":".join(policy_parts) or "always",
            mode=mode, times=times, pred=pred or None)
