"""Async checkpoint/resume of the device bit array.

Parity: the reference delegates persistence entirely to Redis (RDB/AOF
snapshots of the bitmap string; SURVEY.md §5 "Checkpoint/resume").
Here it is first-class (BASELINE: "Redis persistence degrades to an async
checkpoint of the device bit-array"):

* **snapshot**: the filter's packed array is first copied HBM->HBM (a fast
  on-device copy — necessary because inserts jit with buffer donation,
  which recycles the *original* buffer in place as soon as the next insert
  runs), then copied device->host asynchronously and handed to a background
  writer thread. Inserts resume as soon as the on-device copy is enqueued.
  ``trigger()`` must not race a donating insert — call it from the same
  thread as inserts, or under the filter's op lock (the server does);
* **formats**: plain filters serialize to the reference's Redis-string-bitmap
  format (a ``:ruby``-driver filter can read a ``:jax``-built checkpoint);
  counting/sharded payloads add nothing new — counting uses raw
  little-endian words, sharded uses the shard-major global bitmap;
* **sinks**: a local file directory, or a real Redis via the zero-dependency
  RESP client (``tpubloom.server.resp``) — ``SET key_name <bitmap>`` exactly
  like the reference would have left it;
* **monotonic sequence numbers** tag every snapshot; restore picks the
  newest *intact* generation. Crash-consistency contract: a lagging
  checkpoint only loses the most recent inserts, never corrupts
  (scatter-OR is monotone) — the fault-injection tests pin this;
* **format v2 integrity framing** (ISSUE 2): every blob is
  ``MAGIC2 | header_len u64le | header_crc32c u32le | header_json |
  payload`` with the payload's CRC32C and byte length recorded in the
  header. Restore detects torn, truncated, and bit-rotted files instead
  of trusting the newest blob byte-for-byte; on a :class:`FileSink` it
  **walks generations newest→oldest** past corrupt files, moves each one
  to ``<dir>/corrupt/`` (quarantine — a re-walk must not trip over the
  same file twice), and bumps the process-global
  ``ckpt_corrupt_detected`` counter. v1 blobs (``TPUBLOOM1``) still
  restore — structural validation only, as before;
* **retention GC**: the async checkpointer prunes to the last N good
  generations after each successful write (never the quarantine dir).

Fault points (:mod:`tpubloom.faults`): ``ckpt.write`` (before the tmp
write; honors the ``torn`` directive by silently truncating the blob —
the bit-rot-after-fsync case), ``ckpt.fsync`` (before fsync+rename: a
raise here must leave NO partial final file), ``ckpt.restore_read``
(before a blob read on restore).
"""

from __future__ import annotations

import json
import logging
import os
import queue
import re
import threading
import time
from typing import Optional, Tuple

import numpy as np

from tpubloom import faults
from tpubloom.config import FilterConfig, identity_mismatch
from tpubloom.obs import counters as _counters
from tpubloom.sketch import registry as sketch_registry
from tpubloom.utils import locks
from tpubloom.utils.crc32c import crc32c

log = logging.getLogger("tpubloom.checkpoint")

MAGIC = b"TPUBLOOM1\n"  # v1: no integrity framing (read-compat only)
MAGIC_V2 = b"TPUBLOOM2\n"  # v2: header + payload CRC32C

#: Default checkpoint generations the async checkpointer's GC retains.
#: >1 by design: the newest generation being corrupt is exactly the case
#: the restore walk exists for, so there must be a predecessor to fall
#: back to.
DEFAULT_RETAIN = 4


class CheckpointCorruptError(ValueError):
    """A blob failed integrity validation (torn, truncated, bit-rotted).

    Distinct from plain ValueError config/identity mismatches: corruption
    is skippable (fall back a generation), a mismatch is an operator
    error that must surface."""

#: Base-config identity for scalable checkpoints: the template's m/k are
#: placeholders (each layer derives its own from the growth policy), so
#: only the fields every layer inherits participate.
IDENTITY_FIELDS_SCALABLE = (
    "seed", "counting", "shards", "block_bits", "block_hash"
)

_CKPT_RE = re.compile(r"^(?P<name>.+)\.(?P<seq>\d{12,})\.ckpt$")


def _serialize(
    config: FilterConfig, seq: int, words: np.ndarray, extra: Optional[dict] = None
) -> bytes:
    """Self-describing checkpoint: magic + json header + payload.

    Plain filters store the payload in Redis-bitmap byte order so the blob
    under the payload offset is byte-identical to what the reference's
    SETBIT loop would have produced; counting filters store raw LE words.
    """
    from tpubloom.utils.packing import words_to_redis_bitmap

    if sketch_registry.is_sketch(config):
        # sketch kinds (ISSUE 19): flat uint32 storage (cuckoo slots /
        # CMS counter grid) under the kind registry's blob tag, so a
        # restore can refuse a blob whose layout disagrees with the
        # config's kind
        payload = words.reshape(-1).astype("<u4").tobytes()
        fmt = sketch_registry.blob_format(config)
    elif config.counting:
        payload = words.astype("<u4").tobytes()
        fmt = "counting_le_words"
    elif config.block_bits:
        # blocked layout is its own position spec — exporting it as a Redis
        # bitmap would look like (wrong) flat positions; store raw rows.
        payload = words.reshape(-1).astype("<u4").tobytes()
        fmt = "blocked_le_words"
    else:
        payload = words_to_redis_bitmap(words.reshape(-1), config.m)
        fmt = "redis_bitmap"
    return _frame(
        {
            "config": config.to_dict(),
            "seq": seq,
            "format": fmt,
            "time": time.time(),
            "extra": extra or {},
        },
        payload,
    )


def _serialize_scalable(
    base_config: FilterConfig,
    meta: dict,
    seq: int,
    layer_words,
    extra: Optional[dict] = None,
) -> bytes:
    """Layer-stack checkpoint: header lists per-layer config + fill count
    (scalable.snapshot_meta), payload = concatenated per-layer raw LE
    words. Geometry is re-derived from the growth policy on restore and
    verified against the stored layer configs."""
    payloads = [
        np.asarray(w, dtype=np.uint32).reshape(-1).astype("<u4").tobytes()
        for w in layer_words
    ]
    meta = {**meta, "layer_nbytes": [len(p) for p in payloads]}
    return _frame(
        {
            "config": base_config.to_dict(),
            "seq": seq,
            "format": "scalable_stack",
            "time": time.time(),
            "extra": extra or {},
            "scalable": meta,
        },
        b"".join(payloads),
    )


def _frame(header: dict, payload: bytes) -> bytes:
    """Format-v2 writer: the header records the payload's length and
    CRC32C; the header bytes get their own CRC32C right after the length
    word, so corruption anywhere in the blob is attributable."""
    header = {**header, "payload_len": len(payload),
              "payload_crc32c": crc32c(payload)}
    hdr = json.dumps(header).encode()
    return (
        MAGIC_V2
        + len(hdr).to_bytes(8, "little")
        + crc32c(hdr).to_bytes(4, "little")
        + hdr
        + payload
    )


def _deserialize(data: bytes) -> Tuple[dict, bytes]:
    """Parse + integrity-check a blob (v2 full CRC, v1 structural only).

    Raises :class:`CheckpointCorruptError` on anything torn, truncated,
    or bit-rotted; restore treats that as "fall back a generation"."""
    if data.startswith(MAGIC_V2):
        off = len(MAGIC_V2)
        if len(data) < off + 12:
            raise CheckpointCorruptError("checkpoint truncated in framing")
        hlen = int.from_bytes(data[off : off + 8], "little")
        hcrc = int.from_bytes(data[off + 8 : off + 12], "little")
        hdr = data[off + 12 : off + 12 + hlen]
        if len(hdr) != hlen:
            raise CheckpointCorruptError("checkpoint truncated in header")
        if crc32c(hdr) != hcrc:
            raise CheckpointCorruptError("checkpoint header CRC32C mismatch")
        header = json.loads(hdr)  # CRC passed: json is structurally sound
        payload = data[off + 12 + hlen :]
        if len(payload) != header["payload_len"]:
            raise CheckpointCorruptError(
                f"checkpoint payload truncated: header says "
                f"{header['payload_len']} bytes, found {len(payload)}"
            )
        if crc32c(payload) != header["payload_crc32c"]:
            raise CheckpointCorruptError("checkpoint payload CRC32C mismatch")
        return header, payload
    if data.startswith(MAGIC):
        # v1 (pre-integrity framing): best-effort structural validation —
        # a torn v1 header fails json parse; a torn v1 payload is
        # undetectable here (that is why v2 exists).
        off = len(MAGIC)
        hlen = int.from_bytes(data[off : off + 8], "little")
        raw = data[off + 8 : off + 8 + hlen]
        if len(raw) != hlen:
            raise CheckpointCorruptError("v1 checkpoint truncated in header")
        try:
            header = json.loads(raw)
        except ValueError as e:
            raise CheckpointCorruptError(f"v1 checkpoint header unparseable: {e}")
        return header, data[off + 8 + hlen :]
    raise CheckpointCorruptError("not a tpubloom checkpoint (bad magic)")


def payload_to_words(config: FilterConfig, header: dict, payload: bytes) -> np.ndarray:
    from tpubloom.utils.packing import redis_bitmap_to_words

    if header["format"] in ("counting_le_words", "blocked_le_words") or (
        header["format"].startswith("sketch_")
    ):
        return np.frombuffer(payload, dtype="<u4").astype(np.uint32)
    return redis_bitmap_to_words(payload, config.m)


class FileSink:
    """Checkpoints as ``<dir>/<key_name>.<seq>.ckpt`` files (atomic rename).

    Crash invariant (pinned by the chaos suite): a failure at ANY point
    of ``put`` — including an injected ``ckpt.write``/``ckpt.fsync``
    fault — leaves no partial ``.ckpt`` visible; either the rename
    happened with fully-fsynced bytes behind it, or the previous
    generation is still the newest. Files that fail integrity checks at
    restore are moved to ``<dir>/corrupt/`` so a re-walk never pays for
    the same corpse twice."""

    CORRUPT_SUBDIR = "corrupt"

    #: Default cap on the quarantine dir: corrupt blobs are post-mortem
    #: material, not an unbounded landfill — oldest corpses are dropped
    #: once the dir exceeds this (ISSUE 3 satellite; operators inspect
    #: survivors with ``python -m tpubloom.server inspect-quarantine``).
    QUARANTINE_MAX_BYTES = 256 << 20

    def __init__(self, directory: str, *, quarantine_max_bytes: Optional[int] = None):
        self.directory = directory
        self.quarantine_max_bytes = (
            self.QUARANTINE_MAX_BYTES
            if quarantine_max_bytes is None
            else quarantine_max_bytes
        )
        os.makedirs(directory, exist_ok=True)

    def _path(self, key_name: str, seq: int) -> str:
        return os.path.join(self.directory, f"{key_name}.{seq:012d}.ckpt")

    def put(self, key_name: str, seq: int, blob: bytes) -> None:
        final = self._path(key_name, seq)
        tmp = final + ".tmp"
        try:
            directive = faults.fire("ckpt.write")
            if directive == "torn":
                # the bit-rot/torn-write case: the write "succeeds" from
                # the process's view but half the blob is gone — only the
                # restore-side CRC walk can catch this
                blob = blob[: max(1, len(blob) // 2)]
            with open(tmp, "wb") as f:
                f.write(blob)
                f.flush()
                faults.fire("ckpt.fsync")
                os.fsync(f.fileno())
            os.replace(tmp, final)
        except BaseException:
            # never leave a stale tmp behind — a later put of the same
            # seq must not accidentally resurrect half-written bytes
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    def list_seqs(self, key_name: str) -> list:
        """All generations for ``key_name``, newest first."""
        return sorted(
            (
                int(m.group("seq"))
                for fn in os.listdir(self.directory)
                if (m := _CKPT_RE.match(fn)) and m.group("name") == key_name
            ),
            reverse=True,
        )

    def latest_seq(self, key_name: str) -> Optional[int]:
        seqs = self.list_seqs(key_name)
        return seqs[0] if seqs else None

    def get(self, key_name: str, seq: Optional[int] = None) -> Optional[bytes]:
        if seq is None:
            seq = self.latest_seq(key_name)
            if seq is None:
                return None
        path = self._path(key_name, seq)
        if not os.path.exists(path):
            return None
        faults.fire("ckpt.restore_read")
        with open(path, "rb") as f:
            return f.read()

    def quarantine(self, key_name: str, seq: int) -> Optional[str]:
        """Move a corrupt generation into ``<dir>/corrupt/``; returns the
        new path (None if the file vanished underneath us)."""
        src = self._path(key_name, seq)
        qdir = os.path.join(self.directory, self.CORRUPT_SUBDIR)
        os.makedirs(qdir, exist_ok=True)
        dst = os.path.join(qdir, os.path.basename(src))
        try:
            os.replace(src, dst)
        except FileNotFoundError:
            return None
        self._enforce_quarantine_cap(qdir, protect=dst)
        return dst

    def _enforce_quarantine_cap(self, qdir: str, protect: str) -> None:
        """Drop oldest quarantined blobs until the dir fits the cap (the
        just-quarantined file is protected — the freshest corpse is the
        one an operator most wants to autopsy). 0 disables the cap."""
        if not self.quarantine_max_bytes:
            return
        entries = []
        for fn in os.listdir(qdir):
            path = os.path.join(qdir, fn)
            try:
                st = os.stat(path)
            except OSError:
                continue
            entries.append((st.st_mtime, st.st_size, path))
        total = sum(size for _, size, _ in entries)
        for _, size, path in sorted(entries):
            if total <= self.quarantine_max_bytes:
                break
            if path == protect:
                continue
            try:
                os.unlink(path)
                total -= size
                _counters.incr("ckpt_quarantine_evicted")
            except OSError:
                pass

    def prune(self, key_name: str, keep: int = 2) -> int:
        """Drop all but the newest ``keep`` generations (quarantined files
        live in a subdirectory and are never touched); returns the number
        of files removed."""
        seqs = self.list_seqs(key_name)  # newest first
        pruned = 0
        for s in seqs[keep:] if keep else seqs:
            try:
                os.unlink(self._path(key_name, s))
                pruned += 1
            except FileNotFoundError:
                pass
        return pruned


class RedisSink:
    """Checkpoints into a live Redis, keeping the reference's storage model.

    Multi-generation parity with :class:`FileSink` (ISSUE 3 satellite —
    closes the PR-2 "single newest blob = data loss" follow-up). Keys
    written per checkpoint:

    * ``<key_name>`` — the RAW Redis bitmap (flat layouts), the exact
      string the reference's ``:ruby`` driver GETBITs against;
    * ``<key_name>:tpubloom.ckpt:<seq>`` — the framed blob for that
      generation (header + payload, seq/config-aware restore);
    * ``<key_name>:tpubloom.ckpt.seqs`` — JSON index of retained seqs,
      newest first (the RESP client has no KEYS/SCAN, so enumeration is
      explicit — and atomic per sink because every mutation runs under
      the sink lock);
    * ``<key_name>:tpubloom.ckpt`` — the newest blob under the legacy
      key, kept so pre-ISSUE-3 readers still restore.

    With ``list_seqs``/``quarantine``/``prune`` present, the corrupt-
    newest restore walk and the retention GC behave exactly as on a
    :class:`FileSink`: a bit-rotted newest generation is copied to
    ``<key_name>:tpubloom.ckpt.corrupt:<seq>``, dropped from the index,
    and the previous generation restores.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 6379, **kwargs):
        from tpubloom.server.resp import RespClient

        self._client = RespClient(host, port, **kwargs)
        self._lock = locks.named_lock("ckpt.redis_sink")

    def _index_key(self, key_name: str) -> str:
        return f"{key_name}:tpubloom.ckpt.seqs"

    def _gen_key(self, key_name: str, seq: int) -> str:
        return f"{key_name}:tpubloom.ckpt:{seq:012d}"

    def _read_index(self, key_name: str) -> list:
        """Retained seqs newest-first (caller holds the lock). Falls back
        to the legacy single-blob key for sinks written before the
        index existed."""
        raw = self._client.get(self._index_key(key_name))
        if raw is not None:
            return sorted((int(s) for s in json.loads(raw)), reverse=True)
        legacy = self._client.get(f"{key_name}:tpubloom.ckpt")
        if legacy is None:
            return []
        try:
            header, _ = _deserialize(legacy)
        except ValueError:
            return []
        return [int(header["seq"])]

    def _write_index(self, key_name: str, seqs: list) -> None:
        self._client.set(
            self._index_key(key_name),
            json.dumps(sorted(set(seqs), reverse=True)).encode(),
        )

    def put(self, key_name: str, seq: int, blob: bytes) -> None:
        header, payload = _deserialize(blob)
        with self._lock:
            if header["format"] == "redis_bitmap":
                self._client.set(key_name, payload)
            self._client.set(self._gen_key(key_name, seq), blob)
            self._client.set(f"{key_name}:tpubloom.ckpt", blob)  # legacy readers
            self._write_index(key_name, self._read_index(key_name) + [seq])

    def list_seqs(self, key_name: str) -> list:
        """All retained generations, newest first (FileSink parity)."""
        with self._lock:
            return self._read_index(key_name)

    def latest_seq(self, key_name: str) -> Optional[int]:
        seqs = self.list_seqs(key_name)
        return seqs[0] if seqs else None

    def get(self, key_name: str, seq: Optional[int] = None) -> Optional[bytes]:
        with self._lock:
            if seq is None:
                seqs = self._read_index(key_name)
                if not seqs:
                    return None
                seq = seqs[0]
            blob = self._client.get(self._gen_key(key_name, seq))
            if blob is None:
                # legacy layout: the only copy lives under the bare key
                blob = self._client.get(f"{key_name}:tpubloom.ckpt")
                if blob is not None:
                    try:
                        header, _ = _deserialize(blob)
                    except ValueError:
                        return None  # corrupt legacy blob: nothing older exists
                    if header["seq"] != seq:
                        return None
            return blob

    def quarantine(self, key_name: str, seq: int) -> Optional[str]:
        """Move a corrupt generation to ``...ckpt.corrupt:<seq>`` and drop
        it from the index so the restore walk never re-reads it; returns
        the corrupt key (None if the blob vanished underneath us)."""
        with self._lock:
            gen = self._gen_key(key_name, seq)
            blob = self._client.get(gen)
            if blob is None:
                blob = self._client.get(f"{key_name}:tpubloom.ckpt")
            dst = f"{key_name}:tpubloom.ckpt.corrupt:{seq:012d}"
            if blob is not None:
                self._client.set(dst, blob)
            self._client.delete(gen)
            self._write_index(
                key_name,
                [s for s in self._read_index(key_name) if s != seq],
            )
            return dst if blob is not None else None

    def prune(self, key_name: str, keep: int = 2) -> int:
        """Drop all but the newest ``keep`` generations (retention GC,
        FileSink parity); returns generations removed."""
        with self._lock:
            seqs = self._read_index(key_name)
            victims = seqs[keep:] if keep else seqs
            for s in victims:
                self._client.delete(self._gen_key(key_name, s))
            if victims:
                self._write_index(key_name, seqs[:keep] if keep else [])
            return len(victims)

    def close(self) -> None:
        self._client.close()


def inspect_quarantine(directory: str, *, purge: bool = False) -> dict:
    """Operator view of ``<directory>/corrupt/`` (ISSUE 3 satellite;
    CLI: ``python -m tpubloom.server inspect-quarantine``).

    Each entry carries a ``diagnosis`` from re-running the integrity
    checks: what exactly is broken (header CRC, payload CRC, truncation
    ...) plus the header fields when they are still readable — enough to
    decide whether the corpse is worth a deeper post-mortem before
    ``--purge`` drops it."""
    qdir = os.path.join(directory, FileSink.CORRUPT_SUBDIR)
    entries = []
    if os.path.isdir(qdir):
        for fn in sorted(os.listdir(qdir)):
            path = os.path.join(qdir, fn)
            try:
                st = os.stat(path)
            except OSError:
                continue
            diagnosis, header_info = "unreadable", None
            try:
                with open(path, "rb") as f:
                    blob = f.read()
                try:
                    _deserialize(blob)
                    diagnosis = "intact (quarantined by an older build?)"
                except CheckpointCorruptError as e:
                    diagnosis = str(e)
                # best effort: a payload-corrupt blob still has a good
                # header — surface seq/config for the post-mortem
                if blob.startswith(MAGIC_V2):
                    off = len(MAGIC_V2)
                    hlen = int.from_bytes(blob[off : off + 8], "little")
                    hdr = blob[off + 12 : off + 12 + hlen]
                    if len(hdr) == hlen and crc32c(hdr) == int.from_bytes(
                        blob[off + 8 : off + 12], "little"
                    ):
                        h = json.loads(hdr)
                        header_info = {
                            "seq": h.get("seq"),
                            "format": h.get("format"),
                            "time": h.get("time"),
                        }
            except OSError as e:
                diagnosis = f"read failed: {e}"
            entries.append(
                {
                    "file": fn,
                    "bytes": st.st_size,
                    "mtime": st.st_mtime,
                    "diagnosis": diagnosis,
                    "header": header_info,
                }
            )
    purged = 0
    if purge:
        for e in entries:
            try:
                os.unlink(os.path.join(qdir, e["file"]))
                purged += 1
            except OSError:
                pass
    return {
        "quarantine_dir": qdir,
        "entries": entries,
        "total_bytes": sum(e["bytes"] for e in entries),
        "purged": purged,
    }


def _device_snapshot(words):
    """Copy ``words`` out of donation's reach and start the D2H transfer.

    jax.Array: snapshot to a fresh device buffer (immune to the next
    insert donating the original), then start the async copy; NumPy:
    plain copy."""
    if hasattr(words, "copy_to_host_async"):
        import jax.numpy as jnp

        words = jnp.array(words, copy=True)
        words.copy_to_host_async()
        return words
    return np.array(words, copy=True)


def _usage_extra(filter_obj) -> dict:
    """Usage counters recorded in every checkpoint so restore can rebuild
    server stats — plus any kind-specific host-side state the filter
    declares (ISSUE 19: the top-k heavy-hitter heap rides here; the
    counter grid alone can't name which keys are hot)."""
    out = {
        "n_inserted": getattr(filter_obj, "n_inserted", 0),
        "n_queried": getattr(filter_obj, "n_queried", 0),
    }
    sketch_extra = getattr(filter_obj, "sketch_extra", None)
    if sketch_extra is not None:
        out.update(sketch_extra())
    return out


def snapshot_blob(
    filter_obj, *, seq: Optional[int] = None, extra: Optional[dict] = None
) -> Tuple[str, int, bytes]:
    """Serialize a live filter (plain/counting/sharded/scalable) into one
    checkpoint-format blob WITHOUT touching any sink; returns
    ``(key_name, seq, blob)``.

    Shared by :func:`save` and the replication full-resync path (the
    primary streams these blobs to bootstrapping replicas — one format
    for disk, Redis, and the wire). Must not run concurrently with a
    donating insert on the same filter (caller holds the op lock)."""
    seq = seq if seq is not None else int(time.time() * 1000)
    full_extra = {**_usage_extra(filter_obj), **(extra or {})}
    if hasattr(filter_obj, "layers"):  # scalable layer stack
        blob = _serialize_scalable(
            filter_obj.base_config,
            filter_obj.snapshot_meta(),
            seq,
            [np.asarray(layer.words) for layer in filter_obj.layers],
            full_extra,
        )
        return filter_obj.base_config.key_name, seq, blob
    words = np.asarray(filter_obj.words)
    blob = _serialize(filter_obj.config, seq, words, full_extra)
    return filter_obj.config.key_name, seq, blob


def restore_blob(
    blob: bytes,
    config: Optional[FilterConfig] = None,
    *,
    scalable_expect: Optional[dict] = None,
    expect_scalable: Optional[bool] = None,
):
    """Rebuild a live filter from one in-memory blob (integrity-checked
    like any sink read) — the replica side of :func:`snapshot_blob`.
    With no ``config`` the blob's own stored config is adopted (the
    replica bootstrap case: the primary's config IS the truth)."""
    header, payload = _deserialize(blob)
    if config is None:
        config = FilterConfig.from_dict(header["config"])
    return _build_filter(config, header, payload, scalable_expect, expect_scalable)


def save(filter_obj, sink, *, seq: Optional[int] = None, extra: Optional[dict] = None) -> int:
    """Synchronous snapshot of any filter (plain/counting/sharded/scalable)."""
    key_name, seq, blob = snapshot_blob(filter_obj, seq=seq, extra=extra)
    sink.put(key_name, seq, blob)
    return seq


#: Growth-policy fields that must match between a scalable checkpoint and a
#: restore request — they determine every layer's (m, k, seed) geometry.
SCALABLE_POLICY_FIELDS = ("capacity", "error_rate", "growth", "tightening")


def _restore_scalable(config: FilterConfig, header: dict, payload: bytes,
                      expect: Optional[dict] = None):
    """Rebuild a ScalableBloomFilter from a ``scalable_stack`` blob.

    ``config`` is the base/template config (what you would pass as
    ``ScalableBloomFilter(config=...)``); its identity fields must match
    the checkpoint's stored base config. ``expect`` optionally pins the
    growth-policy parameters (server CreateFilter passes the request's)."""
    from tpubloom.scalable import ScalableBloomFilter

    saved = header["config"]
    field = identity_mismatch(saved, config, IDENTITY_FIELDS_SCALABLE)
    if field is not None:
        raise ValueError(
            f"scalable checkpoint/config mismatch on base {field}: "
            f"saved={saved.get(field, '<absent: default>')} "
            f"requested={getattr(config, field)}"
        )
    meta = header["scalable"]
    if expect is not None:
        for f in SCALABLE_POLICY_FIELDS:
            if f in expect and expect[f] != meta[f]:
                raise ValueError(
                    f"scalable checkpoint/policy mismatch on {f}: "
                    f"saved={meta[f]} requested={expect[f]}"
                )
    f = ScalableBloomFilter(
        meta["capacity"],
        meta["error_rate"],
        config=config,
        growth=meta["growth"],
        tightening=meta["tightening"],
    )
    words, off = [], 0
    for nbytes in meta["layer_nbytes"]:
        words.append(
            np.frombuffer(payload[off : off + nbytes], dtype="<u4").astype(
                np.uint32
            )
        )
        off += nbytes
    f._load_layers(meta, words)
    f._restored_seq = header["seq"]
    f._restored_meta = header.get("extra", {})
    return f


def restore(
    config: FilterConfig,
    sink,
    *,
    seq: Optional[int] = None,
    scalable_expect: Optional[dict] = None,
    expect_scalable: Optional[bool] = None,
):
    """Rebuild a filter from the newest (or given) checkpoint in ``sink``.

    Returns a BloomFilter / BlockedBloomFilter / CountingBloomFilter /
    BlockedCountingBloomFilter / ShardedBloomFilter / ScalableBloomFilter
    according to ``config`` and the stored format, or None if the sink has
    no checkpoint. Config identity (m, k, seed, counting) must match the
    checkpoint — positions are only portable between identical hash
    configs. For ``scalable_stack`` blobs, ``config`` is the base/template
    config and ``scalable_expect`` optionally pins the growth policy.
    ``expect_scalable`` (when not None) rejects a blob of the other kind
    up front — before any device arrays are built.

    Robustness (ISSUE 2): on sinks that expose generations
    (``list_seqs``, i.e. :class:`FileSink`) and with no explicit ``seq``
    pinned, corruption in the newest blob is not fatal — the walk falls
    back generation by generation, quarantining each corrupt file and
    bumping ``ckpt_corrupt_detected``; a blob unreadable due to an I/O
    error is skipped (not quarantined — the bytes may be fine) and bumps
    ``ckpt_restore_read_errors``. Only if every generation is corrupt or
    absent does restore return None. Identity/config mismatches are NOT
    skipped: a wrong config must surface, not silently fall back to an
    older blob that happens to match.
    """
    locks.note_blocking(
        "ckpt.restore",
        allow=("service.registry",),
        reason="restore-on-create/promote IS the create's commit point and "
        "must serialize under the registry lock; control-plane-rare",
    )
    if seq is None and hasattr(sink, "list_seqs"):
        for s in sink.list_seqs(config.key_name):
            try:
                blob = sink.get(config.key_name, s)
            except Exception as e:
                _counters.incr("ckpt_restore_read_errors")
                log.warning(
                    "checkpoint %r seq %d unreadable (%s); trying older",
                    config.key_name, s, e,
                )
                continue
            if blob is None:
                continue
            try:
                header, payload = _deserialize(blob)
            except CheckpointCorruptError as e:
                _counters.incr("ckpt_corrupt_detected")
                qpath = (
                    sink.quarantine(config.key_name, s)
                    if hasattr(sink, "quarantine")
                    else None
                )
                log.error(
                    "checkpoint %r seq %d corrupt (%s)%s; trying older",
                    config.key_name, s, e,
                    f", quarantined to {qpath}" if qpath else "",
                )
                continue
            return _build_filter(
                config, header, payload, scalable_expect, expect_scalable
            )
        return None
    blob = sink.get(config.key_name, seq)
    if blob is None:
        return None
    header, payload = _deserialize(blob)
    return _build_filter(config, header, payload, scalable_expect, expect_scalable)


def _build_filter(
    config: FilterConfig,
    header: dict,
    payload: bytes,
    scalable_expect: Optional[dict] = None,
    expect_scalable: Optional[bool] = None,
):
    """Validated header+payload -> live filter (shared by both restore
    paths; the routing below MUST agree with CreateFilter's)."""
    is_stack = header["format"] == "scalable_stack"
    if expect_scalable is not None and is_stack != expect_scalable:
        raise ValueError(
            f"checkpoint for {config.key_name!r} holds a "
            f"{'scalable layer stack' if is_stack else 'fixed-size filter'}; "
            f"requested a "
            f"{'scalable' if expect_scalable else 'fixed-size'} filter"
        )
    if is_stack:
        return _restore_scalable(config, header, payload, scalable_expect)
    saved = header["config"]
    field = identity_mismatch(saved, config)
    if field is not None:
        # .get: legacy headers may predate a field (it then mismatched
        # against the field's default, e.g. block_bits -> flat)
        raise ValueError(
            f"checkpoint/config mismatch on {field}: "
            f"saved={saved.get(field, '<absent: default>')} "
            f"requested={getattr(config, field)}"
        )
    words = payload_to_words(config, header, payload)
    if sketch_registry.is_sketch(config):
        # sketch kinds restore through the SAME registry factory
        # CreateFilter builds with; the blob tag must agree with the
        # config's kind (identity_mismatch above already rejects a kind
        # flip, this guards a mislabeled/corrupted payload tag)
        import jax.numpy as jnp

        expect_fmt = sketch_registry.blob_format(config)
        if header["format"] != expect_fmt:
            raise ValueError(
                f"checkpoint payload tag {header['format']!r} does not "
                f"match kind {config.kind!r} (want {expect_fmt!r})"
            )
        f = sketch_registry.build(config)
        f.words = jnp.asarray(words.reshape(f.words.shape))
        loader = getattr(f, "load_sketch_extra", None)
        if loader is not None:
            loader(header.get("extra", {}))
    elif config.shards > 1:
        from tpubloom.parallel.sharded import ShardedBloomFilter
        import jax

        f = ShardedBloomFilter(config)
        f.words = jax.device_put(words.reshape(f.words.shape), f.sharding)
    elif config.counting and config.block_bits:
        from tpubloom.filter import BlockedCountingBloomFilter
        import jax.numpy as jnp

        f = BlockedCountingBloomFilter(config)
        f.words = jnp.asarray(words).reshape(f.words.shape)
    elif config.counting:
        from tpubloom.filter import CountingBloomFilter

        f = CountingBloomFilter(config)
        import jax.numpy as jnp

        f.words = jnp.asarray(words)
    elif config.block_bits:
        from tpubloom.filter import BlockedBloomFilter
        import jax.numpy as jnp

        f = BlockedBloomFilter(config)
        f.words = jnp.asarray(words.reshape(f.words.shape))
    else:
        from tpubloom.filter import BloomFilter
        import jax.numpy as jnp

        f = BloomFilter(config)
        f.words = jnp.asarray(words)
    f._restored_seq = header["seq"]
    f._restored_meta = header.get("extra", {})
    f.n_inserted = int(f._restored_meta.get("n_inserted", 0))
    f.n_queried = int(f._restored_meta.get("n_queried", 0))
    return f


class AsyncCheckpointer:
    """Background checkpoint writer with bounded lag.

    ``notify_inserts(n)`` after each batch; every ``every_n_inserts`` a
    snapshot is taken (device->host copy started immediately, serialization
    + sink write on the worker thread). If a write is still in flight the
    trigger is deferred — checkpoints never queue up unboundedly, inserts
    are never blocked (SURVEY.md §5 failure-detection row: config 3 requires
    periodic checkpointing with bounded tail loss on crash).
    """

    def __init__(
        self,
        filter_obj,
        sink,
        *,
        every_n_inserts: int = 0,
        meta_fn=None,
        retain: int = DEFAULT_RETAIN,
    ):
        """``meta_fn() -> dict`` (optional) is sampled at trigger time and
        stored in the checkpoint header's ``extra`` field — the streaming
        pipeline records its stream offset this way so resume knows where
        to replay from. ``retain`` bounds how many generations the sink
        keeps (GC runs after each successful write, on sinks with
        ``prune``); 0 disables GC."""
        self.filter = filter_obj
        self.sink = sink
        self.every_n_inserts = every_n_inserts
        self.meta_fn = meta_fn
        self.retain = retain
        self._since_last = 0
        # Millisecond-epoch base keeps sequence numbers monotonic across
        # process restarts (restore picks the max seq in the sink).
        self._seq = int(time.time() * 1000)
        self._queue: "queue.Queue" = queue.Queue(maxsize=1)
        self._busy = threading.Event()
        self._trigger_lock = locks.named_lock("ckpt.trigger")
        self._stop = False
        self.last_error: Optional[Exception] = None
        self.checkpoints_written = 0
        #: observability (the /metrics checkpoint gauges): when the last
        #: checkpoint landed in the sink + how long its write took
        self.last_checkpoint_time: Optional[float] = None
        self.last_checkpoint_duration_s: Optional[float] = None
        #: the ``extra`` header of the last checkpoint that verifiably
        #: LANDED (not merely triggered) — the replication layer reads
        #: ``last_landed_meta["repl_seq"]`` to know how much op-log tail
        #: is already covered by durable state and can be truncated
        self.last_landed_meta: Optional[dict] = None
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()

    def _run(self) -> None:
        while True:
            item = self._queue.get()
            if item is None:
                return
            seq, key_name, blob_fn, extra = item
            t0 = time.perf_counter()
            try:
                # blob_fn blocks until the async D2H copies land.
                self.sink.put(key_name, seq, blob_fn())
                self.checkpoints_written += 1
                self.last_checkpoint_time = time.time()
                self.last_checkpoint_duration_s = time.perf_counter() - t0
                self.last_landed_meta = extra
                self.last_error = None  # a success clears a transient failure
                if self.retain and hasattr(self.sink, "prune"):
                    # GC AFTER a confirmed-good write: the newest file is
                    # intact, so dropping generations beyond `retain`
                    # never strips the corruption fallback
                    try:
                        self.sink.prune(key_name, keep=self.retain)
                    except Exception:  # GC failure must not fail the write
                        log.exception("checkpoint GC for %r failed", key_name)
            except Exception as e:  # surfaced via last_error + health checks
                self.last_error = e
            finally:
                self._busy.clear()

    def notify_inserts(self, n: int) -> None:
        self._since_last += n
        if self.every_n_inserts and self._since_last >= self.every_n_inserts:
            self.trigger()  # resets _since_last itself when it fires

    def obs_stats(self) -> dict:
        """Checkpoint gauges for /metrics and the per-filter Stats RPC:
        lag (inserts since the last trigger fired), age (seconds since a
        write last landed), last write duration, seq, written count."""
        return {
            "lag_inserts": self._since_last,
            "age_seconds": (
                time.time() - self.last_checkpoint_time
                if self.last_checkpoint_time is not None
                else None
            ),
            "last_duration_seconds": self.last_checkpoint_duration_s,
            "seq": self._seq,
            "checkpoints_written": self.checkpoints_written,
            "in_flight": self._busy.is_set(),
            "last_error": (
                repr(self.last_error) if self.last_error is not None else None
            ),
        }

    def trigger(self) -> bool:
        """Start an async checkpoint now; False if one is still in flight.

        Must not run concurrently with a donating insert on the same filter
        (caller provides that exclusion — see module docstring).
        """
        with self._trigger_lock:
            if self._stop or self._busy.is_set():
                return False
            self._busy.set()
            # a landed trigger restarts the lag window — manual triggers
            # (Checkpoint RPC) count too, or the lag gauge would lie
            self._since_last = 0
            self._seq = max(self._seq + 1, int(time.time() * 1000))
            extra = _usage_extra(self.filter)
            if self.meta_fn:
                extra.update(self.meta_fn())
            seq = self._seq
            if hasattr(self.filter, "layers"):
                # scalable: snapshot every layer + the stack meta NOW
                # (consistent under the caller's op lock; layers may grow
                # after trigger returns)
                base = self.filter.base_config
                meta = self.filter.snapshot_meta()
                words_list = [
                    _device_snapshot(layer.words) for layer in self.filter.layers
                ]
                blob_fn = (
                    lambda: _serialize_scalable(base, meta, seq, words_list, extra)
                )
                key_name = base.key_name
            else:
                cfg = self.filter.config
                words = _device_snapshot(self.filter.words)
                blob_fn = lambda: _serialize(cfg, seq, np.asarray(words), extra)
                key_name = cfg.key_name
        self._queue.put((seq, key_name, blob_fn, extra))
        return True

    def flush(self, timeout: float = 60.0) -> bool:
        """Block until the in-flight checkpoint (if any) is written.

        Returns False if it is still unfinished at ``timeout`` — callers
        treating a checkpoint as a durability point must check this.
        """
        locks.note_blocking(
            "ckpt.flush",
            allow=("filter.op",),
            reason="DropFilter/shutdown close under the op lock by design: "
            "the final snapshot must exclude donating inserts, and the "
            "filter is already unpublished so only stragglers contend",
        )
        deadline = time.time() + timeout
        while self._busy.is_set() and time.time() < deadline:
            time.sleep(0.005)
        return not self._busy.is_set()

    def close(self, *, final_checkpoint: bool = True) -> bool:
        """Stop the worker; with ``final_checkpoint`` take one last snapshot.

        Returns True iff the final snapshot verifiably landed in the sink
        (always True when ``final_checkpoint=False``). Callers using close as
        a durability point (DropFilter, server shutdown) must check this —
        silently dropping the filter after a missed final write would lose
        the tail of the stream without anyone knowing.
        """
        locks.note_blocking(
            "ckpt.close",
            allow=("filter.op",),
            reason="DropFilter/shutdown close under the op lock by design: "
            "the final snapshot must exclude donating inserts, and the "
            "filter is already unpublished so only stragglers contend",
        )
        ok = True
        if final_checkpoint:
            ok = self.flush()  # drain any in-flight write first
            ok = self.trigger() and ok
            ok = self.flush() and ok
        self._stop = True
        self._queue.put(None)
        self._worker.join(timeout=30)
        if final_checkpoint and self.last_error is not None:
            ok = False
        return ok

    @property
    def seq(self) -> int:
        return self._seq
