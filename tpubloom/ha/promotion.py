"""Replica→primary promotion and primary→replica demotion (ISSUE 4).

``promote_to_primary`` is the heart of the HA story: it flips a running
read-only replica into a write-accepting primary by **adopting the op
log** —

* a **chained** replica (``--replica-of`` + ``--repl-log-dir``) already
  re-appends every applied record to its own log in the upstream's seq
  space, so promotion just stops the applier and starts accepting
  writes: the log is the log. The upstream's log identity is recorded
  as an **alias** (Redis replid2 parity) valid up to the promotion
  point, so survivors of the old primary partial-resync instead of
  paying a full resync;
* a **bare** replica opens a fresh log seeded at its applied seq (the
  caller supplies ``repl_log_dir``); survivors exactly as caught up
  partial-resync through the alias, everyone else full-resyncs.

Either way the **topology epoch** bumps and persists beside the adopted
log (:class:`tpubloom.ha.topology.EpochStore`): the promotion is
fencing-grade durable — a later restart still knows it won epoch N, and
an older primary restarting with epoch < N is demoted on sight by any
sentinel (``ReplicaOf``). Passing ``epoch`` pins the value (the sentinel
quorum's agreed epoch); a stale pin is rejected with ``STALE_EPOCH``
(Raft's "term wins arguments" discipline — a vote from epoch N-1 cannot
move a node that has seen N).

``become_replica`` is the inverse (Redis ``REPLICAOF host port``): fence
writes immediately, re-point (or start) the applier, carry the cursor so
the link can partial-resync. A demoted old primary keeps its log; if it
diverged past the promotion point, the alias window check forces the
full resync that discards the divergent tail.

Fault point ``ha.promote`` fires before any state changes.
"""

from __future__ import annotations

import logging
import threading

from tpubloom import faults
from tpubloom.ha.topology import EpochStore
from tpubloom.obs import blackbox as obs_blackbox
from tpubloom.obs import counters as _counters
from tpubloom.obs import flight as obs_flight

log = logging.getLogger("tpubloom.ha")


def _role_gauges(service) -> None:
    _counters.set_gauge("ha_role", 1.0 if service.read_only else 0.0)
    _counters.set_gauge("ha_epoch", float(service.epoch))


def promote_to_primary(service, *, repl_log_dir=None, epoch=None) -> dict:
    """Flip ``service`` (a read-only replica) to primary; idempotent on
    an existing primary. See the module docstring for the contract."""
    from tpubloom.server import protocol

    faults.fire("ha.promote")
    with service._promote_lock:
        if epoch is not None and int(epoch) <= service.epoch:
            if not service.read_only and int(epoch) == service.epoch:
                # the exact promotion that made us primary, replayed
                # (sentinel retry): answer it idempotently
                return {
                    "ok": True,
                    "already_primary": True,
                    "epoch": service.epoch,
                    "log_id": service.oplog.log_id if service.oplog else None,
                }
            raise protocol.BloomServiceError(
                "STALE_EPOCH",
                f"promotion epoch {epoch} is not newer than the current "
                f"epoch {service.epoch}",
                details={"epoch": service.epoch},
            )
        if not service.read_only:
            if epoch is not None:
                service.adopt_epoch(int(epoch))
            return {
                "ok": True,
                "already_primary": True,
                "epoch": service.epoch,
                "log_id": service.oplog.log_id if service.oplog else None,
            }

        applier = service.replica_applier
        upstream_id = None
        if applier is not None:
            upstream_id = applier.log_id
            applier.stop()
        if service.storage is not None:
            # ISSUE 14: a tenant mid-eviction is in NEITHER tier — not
            # in the registry (unpublished) and its storage entry's
            # applied_seq/create_req not yet filed — so the adopted-seq
            # max below and rebuild_manifest would both miss it. Settle
            # in-flight transitions first (same discipline as
            # become_replica's demotion barrier).
            service.storage.drain_busy()
        with service._lock:
            mfs = list(service._filters.values())
        adopted = max(
            [applier.cursor or 0 if applier is not None else 0]
            + [mf.applied_seq for mf in mfs]
            + [service.oplog.last_seq if service.oplog is not None else 0]
            # paged tenants' history counts too (ISSUE 14): a bare
            # replica's fresh log must not mint seqs below an evicted
            # tenant's applied state
            + [service.storage.max_applied_seq()
               if service.storage is not None else 0]
        )

        if service.oplog is None:
            if not repl_log_dir:
                raise protocol.BloomServiceError(
                    "NO_LOG_DIR",
                    "promotion needs an op log: start the replica with "
                    "--repl-log-dir (chained) or pass repl_log_dir in the "
                    "Promote request",
                )
            from tpubloom.repl import OpLog

            service.oplog = OpLog(repl_log_dir, start_seq=adopted)
            service._manifest_dir = service.oplog.directory
            service.rebuild_manifest()
            service.oplog.set_alias(upstream_id, adopted)
        else:
            # chained: the local log IS the adopted log (same seq space
            # as the upstream) — alias its identity up to our head so
            # old-primary cursors stay partially resumable
            service.oplog.set_alias(upstream_id, service.oplog.last_seq)

        store = EpochStore(service.oplog.directory)
        new_epoch = (
            int(epoch)
            if epoch is not None
            else max(service.epoch, store.load()) + 1
        )
        service._epoch_store = store
        service.adopt_epoch(new_epoch)

        service.read_only = False
        service._stream_fed = False  # handlers own the log again
        service.replica_applier = None
        service.primary_address = None
        _counters.incr("ha_role_transitions")
        _counters.incr("ha_promotions")
        # flight recorder (ISSUE 15): role flips are the spine of any
        # failover post-mortem (note() under the promote lock only
        # touches obs.counters — the declared service.promote ->
        # obs.counters edge, same as the incrs above)
        obs_flight.note("role_change", role="primary", epoch=int(new_epoch))
        # black-box node identity (ISSUE 16): post-promotion records in
        # the mapped ring must carry the new role + epoch
        obs_blackbox.set_node_meta(role="primary", epoch=int(new_epoch))
        _role_gauges(service)
        log.info(
            "promoted to primary: epoch %d, adopted seq %d, log %s (%s)",
            new_epoch, adopted, service.oplog.directory,
            service.oplog.log_id,
        )
        service.metrics.count("ha_promotions")
        return {
            "ok": True,
            "already_primary": False,
            "epoch": new_epoch,
            "adopted_seq": adopted,
            "log_id": service.oplog.log_id,
        }


def become_replica(service, primary_address: str, *, epoch=None) -> dict:
    """Point ``service`` at ``primary_address`` as a read-only replica
    (Redis ``REPLICAOF host port``): fences writes immediately, then
    re-points (or starts) the stream applier carrying the current cursor
    so the link partial-resyncs when the new primary's log (or its
    post-promotion alias) still covers it."""
    from tpubloom.repl.replica import ReplicaApplier, ReplicaStateStore
    from tpubloom.server import protocol

    with service._promote_lock:
        if epoch is not None:
            if int(epoch) < service.epoch:
                raise protocol.BloomServiceError(
                    "STALE_EPOCH",
                    f"ReplicaOf epoch {epoch} is older than the current "
                    f"epoch {service.epoch}",
                    details={"epoch": service.epoch},
                )
            service.adopt_epoch(int(epoch))
        old = service.replica_applier
        if (
            service.read_only
            and old is not None
            and old.primary_address == primary_address
            and old.link != "stopped"
        ):
            return {
                "ok": True,
                "unchanged": True,
                "primary": primary_address,
                "epoch": service.epoch,
            }
        was_primary = not service.read_only
        # fence FIRST: from this moment every mutating RPC answers
        # READONLY (pointing at the new primary), even while the old
        # applier is still draining
        service.read_only = True
        service.primary_address = primary_address
        if was_primary:
            # drain the in-flight writers that passed the READONLY check
            # before the fence: each holds its filter lock (or the
            # registry lock for create/drop) across apply AND log, so
            # taking every lock once is a barrier — after it, every
            # acked write is in the log. Only THEN may the applier take
            # the log over (reappend preserves the upstream seq space;
            # a handler appending after that would mint a conflict).
            if service.storage is not None:
                # ISSUE 14: a write that passed the READONLY check may
                # still be WAITING on a tenant hydration — its filter
                # lock does not exist yet, so the take-every-lock
                # barrier below cannot cover it. Settle in-flight
                # hydrations/evictions first; the straggler then hits
                # the write-side fence re-check under the op lock
                # (service._op) and bounces READONLY instead of
                # applying unlogged.
                service.storage.drain_busy()
            with service._lock:
                mfs = list(service._filters.values())
            for mf in mfs:
                with mf.lock:
                    pass
            if service._coalescer is not None:
                # ISSUE 10: writes PARKED in the ingestion coalescer
                # passed the READONLY check but hold no filter lock yet,
                # so the barrier above does not cover them — drain the
                # queues so their flushes log in the old seq space
                # before the applier takes the log over
                service._coalescer.drain_parked()
        cursor = log_id = None
        if old is not None:
            old.stop()
            cursor, log_id = old.cursor, old.log_id
        elif service.oplog is not None:
            # demoted primary: its log head is exactly its state — if it
            # never diverged past the promotion point, the new primary's
            # alias lets this resume as a partial resync
            cursor, log_id = service.oplog.last_seq, service.oplog.log_id
        if service.replica_state_store is None and service.oplog is not None:
            service.replica_state_store = ReplicaStateStore(
                service.oplog.directory
            )
        applier = ReplicaApplier(
            service,
            primary_address,
            state_store=service.replica_state_store,
            listen_address=service.listen_address,
            initial_cursor=cursor,
            initial_log_id=log_id,
        ).start()
        _counters.incr("ha_role_transitions")
        if was_primary:
            _counters.incr("ha_demotions")
            service.metrics.count("ha_demotions")
        obs_flight.note(
            "role_change", role="replica", primary=primary_address,
            epoch=int(service.epoch), was_primary=bool(was_primary),
        )
        obs_blackbox.set_node_meta(role="replica", epoch=int(service.epoch))
        _role_gauges(service)
        log.info(
            "now replicating from %s (epoch %d, cursor %s, was_primary=%s)",
            primary_address, service.epoch, cursor, was_primary,
        )
        return {
            "ok": True,
            "unchanged": False,
            "primary": primary_address,
            "epoch": service.epoch,
            "was_primary": was_primary,
        }
