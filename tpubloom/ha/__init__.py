"""High-availability subsystem (ISSUE 4).

PR 3 gave tpubloom Redis's replication story (op log, ``ReplStream``,
read replicas); this package makes it survivable end to end — a process
crash no longer loses write availability:

* :mod:`tpubloom.ha.promotion` — replica→primary promotion (op-log
  adoption, identity aliasing for cheap survivor resync, persisted
  topology epoch) and primary→replica demotion (``ReplicaOf``, Redis
  ``REPLICAOF`` parity);
* :mod:`tpubloom.ha.sentinel` — the failover coordinator: a quorum of
  watcher processes that health-poll the primary, agree on
  SDOWN→ODOWN via epoch-stamped votes (Raft term discipline, no full
  Raft), promote the most-caught-up replica, re-point survivors, and
  fence stale-epoch primaries;
* :mod:`tpubloom.ha.topology` — the epoch store + the cluster-view
  struct sentinels announce and topology-aware clients cache.

Chained replicas (``--replica-of`` + ``--repl-log-dir`` together) make
promotion of a mid-chain node cheap: the replica re-appends applied
records to its own log in the upstream's seq space and serves
``ReplStream`` downstream, so its log IS the adopted log.
"""

from tpubloom.ha.promotion import become_replica, promote_to_primary
from tpubloom.ha.topology import EpochStore, Topology

__all__ = [
    "become_replica",
    "promote_to_primary",
    "EpochStore",
    "Topology",
]
