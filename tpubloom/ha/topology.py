"""Topology epoch + cluster-view primitives (ISSUE 4).

The split-brain discipline is Raft's term idea applied to a much smaller
problem: every change of WHO IS PRIMARY happens under a monotonically
increasing **topology epoch**. A promotion persists the new epoch next
to the op log it adopted; every `Promote`/`ReplicaOf` RPC is
epoch-stamped and a stale epoch is rejected (``STALE_EPOCH``); sentinels
vote at most once per epoch, so two concurrent failovers cannot both win
the same epoch; clients cache the epoch with their topology and refresh
when a server proves theirs stale. A restarted pre-failover primary
carries the OLD epoch and is therefore fenceable: any sentinel that sees
it claim ``role=primary`` below the current epoch demotes it with
``ReplicaOf`` (Redis Sentinel's ``slaveof`` fencing, with Raft's "term
wins arguments" rule deciding who moves).

:class:`EpochStore` is the persistence: a tiny CRC32C-checked JSON file
(``epoch.json``) beside the op log — corrupt/torn contents read as epoch
0 rather than a crash, because a LOWER-than-true epoch only ever makes
this node easier to fence (safe direction).
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass, field
from typing import Optional

from tpubloom.utils import crcjson

log = logging.getLogger("tpubloom.ha")

EPOCH_FILE = "epoch.json"


class EpochStore:
    """Persisted topology epoch (one integer, CRC-checked via
    :mod:`tpubloom.utils.crcjson` — corrupt reads as epoch 0, the
    fence-me-harder direction)."""

    def __init__(self, directory: str):
        self.directory = directory
        self.path = os.path.join(directory, EPOCH_FILE)

    def load(self) -> int:
        data = crcjson.load(self.path, ("epoch",))
        if data is None:
            return 0
        try:
            return int(data["epoch"])
        except (ValueError, TypeError):
            return 0

    def store(self, epoch: int) -> None:
        os.makedirs(self.directory, exist_ok=True)
        crcjson.store(self.path, {"epoch": int(epoch)})


@dataclass
class Topology:
    """One cluster view: the epoch it was established under, the primary
    address, and the known replica addresses. What sentinels agree on,
    announce to each other, and serve to topology-aware clients."""

    epoch: int = 0
    primary: Optional[str] = None
    replicas: list = field(default_factory=list)

    def to_dict(self) -> dict:
        return {
            "epoch": self.epoch,
            "primary": self.primary,
            "replicas": list(self.replicas),
        }

    @classmethod
    def from_dict(cls, data: dict) -> "Topology":
        return cls(
            epoch=int(data.get("epoch") or 0),
            primary=data.get("primary"),
            replicas=list(data.get("replicas") or ()),
        )

    def adopt(self, other: "Topology") -> bool:
        """Take ``other``'s view iff it is from a NEWER epoch (the Raft
        rule: higher term wins every argument); True iff adopted."""
        if other.epoch <= self.epoch or not other.primary:
            return False
        self.epoch = other.epoch
        self.primary = other.primary
        self.replicas = list(other.replicas)
        return True
