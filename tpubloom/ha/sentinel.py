"""Sentinel-parity failover coordinator (ISSUE 4 tentpole).

``python -m tpubloom.sentinel --watch host:port --peers ...`` runs one
watcher of a quorum of N. Each sentinel is a tiny gRPC service
(:data:`tpubloom.server.protocol.SENTINEL_SERVICE`) plus a monitor
thread:

* **health polling** — the watched primary's ``Health`` RPC every
  ``poll_s``; misses accumulate into **SDOWN** (subjectively down) after
  ``down_after_s``, Redis Sentinel's terminology and shape;
* **SDOWN→ODOWN by vote** — a subjectively-down sentinel asks its peers
  for an epoch-stamped vote (``VoteDown``). A peer grants iff it also
  sees the primary down AND has not yet voted in that epoch — the Raft
  term rule (vote once per term) without the rest of Raft: no log
  replication, just a leader lease for one failover. Majority of the
  quorum = **ODOWN** + leadership for that epoch;
* **failover** — the leader reads each known replica's ``Health`` and
  picks the most caught-up one (highest replication cursor =
  lowest ``repl_lag_seq``), sends it ``Promote {epoch}``, re-points the
  survivors with ``ReplicaOf {primary, epoch}``, and announces the new
  topology to its peers (``AnnounceTopology``);
* **fencing** — any node later observed claiming ``role=primary`` under
  an epoch OLDER than the current topology's (the restarted pre-failover
  primary) is demoted on sight with ``ReplicaOf`` — split-brain ends the
  moment a sentinel can reach the stale node;
* **discovery** — replicas are discovered from the primary's
  ``Health.replication.replicas[].listen`` announcements (Redis
  ``INFO replication`` parity); clients ask any sentinel ``Topology``
  for the current epoch/primary/replicas (``SENTINEL
  get-master-addr-by-name`` parity);
* **state persistence** (ISSUE 5 satellite) — with ``--state-dir`` the
  current topology (epoch/primary/replicas) and the newest epoch this
  sentinel has VOTED in persist to a CRC-checked
  ``sentinel_state.json`` (:mod:`tpubloom.utils.crcjson`). A
  full-quorum sentinel restart therefore does not forget failover
  history: it resumes watching the post-failover primary at the
  current epoch and keeps the one-vote-per-epoch discipline across the
  restart (Redis Sentinel's config-epoch persistence). Corruption
  reads as absent — the sentinel falls back to ``--watch`` and
  re-learns epochs from the primaries' Health answers, never crashes.

Fault point ``ha.vote`` fires in both the vote-request and vote-grant
paths, so the chaos suite can kill a failover mid-election.
"""

from __future__ import annotations

import argparse
import logging
import threading
import time
from concurrent import futures
from typing import Optional

import grpc

from tpubloom import faults
from tpubloom.ha.topology import Topology
from tpubloom.obs import blackbox as obs_blackbox
from tpubloom.obs import counters as _counters
from tpubloom.obs import flight as obs_flight
from tpubloom.obs import trace as obs_trace
from tpubloom.server import protocol
from tpubloom.utils import crcjson
from tpubloom.utils import locks

log = logging.getLogger("tpubloom.sentinel")


class SentinelStateStore:
    """Persisted sentinel memory (ISSUE 5 satellite — Redis Sentinel
    config-epoch parity): the adopted topology and the newest epoch this
    sentinel has voted in, CRC-checked so a torn write reads as "no
    state" (→ re-learn from the primaries, the safe direction)."""

    STATE_FILE = "sentinel_state.json"
    _FIELDS = ("epoch", "last_vote_epoch", "primary", "replicas", "fenced")

    def __init__(self, directory: str):
        import os

        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self.path = os.path.join(directory, self.STATE_FILE)

    def load(self):
        data = crcjson.load(self.path, self._FIELDS)
        if data is None:
            return None
        try:
            return {
                "epoch": int(data["epoch"]),
                "last_vote_epoch": int(data["last_vote_epoch"]),
                "primary": data["primary"],
                "replicas": list(data["replicas"] or ()),
                "fenced": list(data["fenced"] or ()),
            }
        except (ValueError, TypeError):
            return None

    def store(
        self, epoch: int, last_vote_epoch: int, primary, replicas, fenced
    ) -> None:
        crcjson.store(
            self.path,
            {
                "epoch": int(epoch),
                "last_vote_epoch": int(last_vote_epoch),
                "primary": primary,
                "replicas": list(replicas or ()),
                # the demoted-primary watchlist is failover memory too:
                # forget it across a full-quorum restart and a stale
                # primary that comes back is never fenced
                "fenced": sorted(fenced or ()),
            },
        )

_CHANNEL_OPTIONS = [
    ("grpc.max_receive_message_length", 64 * 1024 * 1024),
]


class Sentinel:
    """One failover watcher; run N of these (N odd) for a quorum."""

    def __init__(
        self,
        watch: str,
        peers: Optional[list] = None,
        *,
        listen: str = "127.0.0.1:0",
        quorum: Optional[int] = None,
        poll_s: float = 0.25,
        down_after_s: float = 1.5,
        rpc_timeout_s: float = 1.0,
        promote_timeout_s: Optional[float] = None,
        failover_cooldown_s: float = 2.0,
        sentinel_id: Optional[str] = None,
        state_dir: Optional[str] = None,
    ):
        import secrets

        self.peers = list(peers or ())
        total = len(self.peers) + 1
        #: votes (incl. our own) needed for ODOWN + failover leadership;
        #: default = majority, so two concurrent elections cannot both win
        self.quorum = quorum if quorum is not None else total // 2 + 1
        self.poll_s = poll_s
        self.down_after_s = down_after_s
        self.rpc_timeout_s = rpc_timeout_s
        #: Promote/ReplicaOf are heavyweight (log adoption, epoch
        #: persist, applier teardown) and MUST NOT be declared failed on
        #: a health-poll-grade deadline — a spuriously "failed" promote
        #: that lands late is how dueling co-primaries happen
        self.promote_timeout_s = (
            promote_timeout_s
            if promote_timeout_s is not None
            else max(5.0, 5 * rpc_timeout_s)
        )
        self.failover_cooldown_s = failover_cooldown_s
        self.sentinel_id = sentinel_id or secrets.token_hex(8)
        self.topology = Topology(epoch=0, primary=watch, replicas=[])
        self._lock = locks.named_lock("sentinel.state")
        #: newest epoch this sentinel has VOTED in (self-votes included):
        #: one vote per epoch is the whole split-brain argument
        self._last_vote_epoch = 0
        #: demoted-primary watchlist: addresses to fence if they come
        #: back claiming a stale primaryship
        self._fence_watch: set = set()
        #: persisted failover memory (ISSUE 5 satellite): restart with
        #: the post-failover topology + vote discipline instead of the
        #: stale --watch view
        self._state_store = (
            SentinelStateStore(state_dir) if state_dir else None
        )
        if self._state_store is not None:
            saved = self._state_store.load()
            if saved is not None:
                self._last_vote_epoch = saved["last_vote_epoch"]
                self._fence_watch.update(saved["fenced"])
                if saved["epoch"] > 0 and saved["primary"]:
                    self.topology = Topology(
                        epoch=saved["epoch"],
                        primary=saved["primary"],
                        replicas=saved["replicas"],
                    )
                    log.info(
                        "sentinel state restored: epoch %d, primary %s "
                        "(voted through epoch %d)",
                        saved["epoch"], saved["primary"],
                        self._last_vote_epoch,
                    )
        self._sdown = False
        self._first_fail: Optional[float] = None
        self._last_failover_attempt = 0.0
        #: when we last GRANTED a peer's vote: someone else is leading a
        #: failover — hold our own candidacy back so the quorum does not
        #: burn epochs on dueling elections (Redis Sentinel's
        #: failover-timeout hold-off, randomly staggered like its
        #: election delays)
        self._granted_at = 0.0
        import random as _random

        self._rand = _random.Random()
        self._election_stagger = self._rand.uniform(0, failover_cooldown_s)
        self.failovers = 0
        #: trace id of the newest election this sentinel LED (ISSUE 16
        #: satellite): every vote/promote/topology RPC of one failover
        #: records a span under this rid, so ``TraceGet``-style assembly
        #: and the black-box CLI can show the election hop by hop
        self.last_election_rid: Optional[str] = None
        self._stop = threading.Event()
        self._channels: dict = {}
        #: topology-push machinery (ISSUE 9 satellite): subscribers of
        #: the ``TopologyEvents`` stream wait on this version counter —
        #: every committed topology change bumps it (OUTSIDE the state
        #: lock, so the two locks never nest in both orders)
        self._topo_version = 0
        self._topo_cond = locks.named_condition("sentinel.topo_events")
        self._topo_subscribers = 0
        self._thread = threading.Thread(
            target=self._run, name="tpubloom-sentinel", daemon=True
        )
        self._server = grpc.server(
            futures.ThreadPoolExecutor(
                # subscribers park a worker each for their stream
                # lifetime — size past the unary handlers' needs
                max_workers=16, thread_name_prefix="sentinel-rpc"
            )
        )
        handlers = {
            m: grpc.unary_unary_rpc_method_handler(self._wrap(m))
            for m in protocol.SENTINEL_METHODS
        }
        handlers.update(
            {
                m: grpc.unary_stream_rpc_method_handler(self._wrap_stream(m))
                for m in protocol.SENTINEL_STREAM_METHODS
            }
        )
        self._server.add_generic_rpc_handlers(
            (
                grpc.method_handlers_generic_handler(
                    protocol.SENTINEL_SERVICE, handlers
                ),
            )
        )
        self.port = self._server.add_insecure_port(listen)
        host = listen.rsplit(":", 1)[0] or "127.0.0.1"
        self.address = f"{host}:{self.port}"

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "Sentinel":
        self._server.start()
        self._thread.start()
        log.info(
            "sentinel %s watching %s (quorum %d of %d, peers %s) on %s",
            self.sentinel_id, self.topology.primary, self.quorum,
            len(self.peers) + 1, self.peers, self.address,
        )
        return self

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)
        self._server.stop(grace=None)
        for ch in self._channels.values():
            ch.close()
        self._channels.clear()

    def _persist_state(self) -> None:
        """Write the failover memory through the state store (no-op
        without ``--state-dir``). Callers hold ``self._lock`` — the
        write must capture exactly the view they just committed."""
        if self._state_store is None:
            return
        try:
            self._state_store.store(
                self.topology.epoch,
                self._last_vote_epoch,
                self.topology.primary,
                self.topology.replicas,
                self._fence_watch,
            )
        except OSError:
            log.exception("sentinel state persist failed (non-fatal)")

    # -- RPC plumbing --------------------------------------------------------

    def _wrap(self, method: str):
        handler = getattr(self, "handle_" + method)

        def unary_unary(request: bytes, context) -> bytes:
            try:
                req = protocol.decode(request) if request else {}
                resp = handler(req)
            except Exception as e:  # noqa: BLE001 — surface, don't kill
                log.exception("sentinel RPC %s failed", method)
                resp = protocol.error_response(
                    "INTERNAL", f"{type(e).__name__}: {e}"
                )
            return protocol.encode(resp)

        return unary_unary

    def _wrap_stream(self, method: str):
        gen_fn = getattr(self, "stream_" + method)

        def unary_stream(request: bytes, context):
            try:
                req = protocol.decode(request) if request else {}
            except Exception:  # noqa: BLE001 — a bad frame is an empty req
                req = {}
            for msg in gen_fn(req, context):
                yield protocol.encode(msg)

        return unary_stream

    def _notify_topology(self) -> None:
        """Wake TopologyEvents subscribers. MUST be called with the
        state lock RELEASED: the stream generator takes the condition
        then the state lock, so taking them here in the opposite order
        would be a lock-order cycle (the runtime tracker enforces
        this)."""
        with self._topo_cond:
            self._topo_version += 1
            self._topo_cond.notify_all()
        _counters.incr("sentinel_topology_pushes")

    #: cap on concurrent TopologyEvents subscribers: each one parks a
    #: gRPC worker for its stream lifetime, and the pool is shared with
    #: VoteDown/Topology — unbounded subscribers would starve the very
    #: election RPCs the push exists to announce. Rejected subscribers
    #: get an error frame and fall back to refresh-on-error.
    MAX_TOPO_SUBSCRIBERS = 8

    def stream_TopologyEvents(
        self, req: dict, context, *, heartbeat_s: float = 1.0
    ):
        """Server-stream behind ``TopologyEvents`` (ISSUE 9 satellite):
        the current view immediately, a fresh ``topology`` frame on
        every change, heartbeats while idle — subscribed clients
        re-point on failover without a refresh-on-error round trip."""
        with self._topo_cond:
            if self._topo_subscribers >= self.MAX_TOPO_SUBSCRIBERS:
                full = True
            else:
                full = False
                self._topo_subscribers += 1
        if full:
            yield {
                "kind": "error",
                "ok": False,
                "code": "SUBSCRIBERS_FULL",
                "message": "TopologyEvents subscriber cap reached on this "
                "sentinel; subscribe elsewhere or poll Topology",
            }
            return
        try:
            last = -1
            while context.is_active() and not self._stop.is_set():
                with self._topo_cond:
                    if self._topo_version == last:
                        self._topo_cond.wait(heartbeat_s)
                    version = self._topo_version
                if version != last:
                    last = version
                    with self._lock:
                        view = self.topology.to_dict()
                    yield {"kind": "topology", "ok": True, **view}
                else:
                    yield {"kind": "heartbeat", "epoch": self.topology.epoch}
        finally:
            with self._topo_cond:
                self._topo_subscribers -= 1

    def _channel(self, address: str):
        ch = self._channels.get(address)
        if ch is None:
            ch = grpc.insecure_channel(address, options=_CHANNEL_OPTIONS)
            self._channels[address] = ch
        return ch

    def _call(
        self,
        address: str,
        path: str,
        req: dict,
        timeout: Optional[float] = None,
    ) -> dict:
        locks.note_blocking("sentinel.rpc")
        raw = self._channel(address).unary_unary(
            path,
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b,
        )(protocol.encode(req), timeout=timeout or self.rpc_timeout_s)
        return protocol.decode(raw)

    def _node(
        self,
        address: str,
        method: str,
        req: dict,
        timeout: Optional[float] = None,
    ) -> dict:
        return self._call(
            address, protocol.method_path(method), req, timeout=timeout
        )

    def _peer(self, address: str, method: str, req: dict) -> dict:
        return self._call(address, protocol.sentinel_method_path(method), req)

    # -- sentinel RPC handlers ------------------------------------------------

    def handle_Ping(self, req: dict) -> dict:
        return {
            "ok": True,
            "sentinel_id": self.sentinel_id,
            "epoch": self.topology.epoch,
            "sdown": self._sdown,
        }

    def handle_Topology(self, req: dict) -> dict:
        """Client-facing discovery (SENTINEL get-master-addr parity)."""
        with self._lock:
            return {"ok": True, **self.topology.to_dict()}

    def handle_VoteDown(self, req: dict) -> dict:
        """Epoch-stamped leader vote: granted iff we ALSO see that
        primary down (our own SDOWN — the ODOWN agreement) and we have
        not voted in this epoch yet (the term discipline)."""
        faults.fire("ha.vote")
        epoch = int(req.get("epoch") or 0)
        primary = req.get("primary")
        with self._lock:
            granted = (
                primary == self.topology.primary
                and self._sdown
                and epoch > self.topology.epoch
                and epoch > self._last_vote_epoch
            )
            if granted:
                self._last_vote_epoch = epoch
                self._granted_at = time.monotonic()
                _counters.incr("sentinel_votes_granted")
                # the vote is a PROMISE (one per epoch) — it must
                # survive a restart or a rebooted sentinel could hand
                # the same epoch to a second candidate
                self._persist_state()
        return {
            "ok": True,
            "granted": granted,
            "epoch": self.topology.epoch,
            "sdown": self._sdown,
        }

    def handle_AnnounceTopology(self, req: dict) -> dict:
        """A failover leader announcing its result; adopt if newer."""
        incoming = Topology.from_dict(req)
        with self._lock:
            adopted = self.topology.adopt(incoming)
            if adopted:
                self._sdown = False
                self._first_fail = None
                old = req.get("fenced")
                if old:
                    self._fence_watch.add(old)
                self._persist_state()
                log.info(
                    "adopted topology epoch %d (primary %s) from peer",
                    incoming.epoch, incoming.primary,
                )
        if adopted:
            self._notify_topology()
        return {"ok": True, "adopted": adopted, "epoch": self.topology.epoch}

    # -- the monitor loop ----------------------------------------------------

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                self._poll_primary()
                self._fence_stale_primaries()
                now = time.monotonic()
                # a granted vote means a peer is leading a failover that
                # may legitimately take up to promote_timeout — hold our
                # own candidacy back at least that long
                grant_holdoff = max(
                    4 * self.failover_cooldown_s,
                    self.failover_cooldown_s + self.promote_timeout_s,
                )
                if (
                    self._sdown
                    and now - self._last_failover_attempt
                    >= self.failover_cooldown_s + self._election_stagger
                    and now - self._granted_at >= grant_holdoff
                ):
                    self._last_failover_attempt = now
                    # re-roll the stagger per attempt so two sentinels
                    # whose retry slots collided once do not collide on
                    # every retry
                    self._election_stagger = self._rand.uniform(
                        0, self.failover_cooldown_s
                    )
                    self._attempt_failover()
            except Exception:  # noqa: BLE001 — the watcher must survive
                log.exception("sentinel monitor tick failed")

    def _poll_primary(self) -> None:
        with self._lock:
            primary = self.topology.primary
        try:
            h = self._node(primary, "Health", {})
        except grpc.RpcError:
            now = time.monotonic()
            if self._first_fail is None:
                self._first_fail = now
            if not self._sdown and now - self._first_fail >= self.down_after_s:
                self._sdown = True
                # start the election clock HERE: every sentinel reaches
                # SDOWN within one poll period of the others, so an
                # immediately-eligible first attempt is a guaranteed
                # three-way self-vote tie. The staggered delay gives one
                # sentinel a clean head start instead (Redis Sentinel's
                # randomized failover start delay).
                self._last_failover_attempt = now
                _counters.incr("sentinel_sdown_entered")
                log.warning(
                    "sentinel %s: %s is subjectively DOWN",
                    self.sentinel_id, primary,
                )
            _counters.set_gauge("sentinel_sdown", 1.0 if self._sdown else 0.0)
            return
        self._first_fail = None
        if self._sdown:
            log.info("sentinel %s: %s is back", self.sentinel_id, primary)
        self._sdown = False
        _counters.set_gauge("sentinel_sdown", 0.0)
        changed = False
        with self._lock:
            node_epoch = int(h.get("epoch") or 0)
            if node_epoch > self.topology.epoch:
                self.topology.epoch = node_epoch
                self._persist_state()
                changed = True
            if h.get("role") == "replica":
                # the watched node was demoted behind our back (manual
                # REPLICAOF / a failover we missed): follow its view
                upstream = (h.get("replication") or {}).get("primary")
                if upstream and upstream != primary:
                    log.warning(
                        "watched node %s is now a replica of %s; following",
                        primary, upstream,
                    )
                    self._fence_watch.discard(upstream)
                    if primary not in self.topology.replicas:
                        self.topology.replicas.append(primary)
                    self.topology.primary = upstream
                    self._persist_state()
                    changed = True
            else:
                # discover announced replicas (INFO replication parity)
                sessions = (h.get("replication") or {}).get("replicas") or ()
                listens = [s.get("listen") for s in sessions if s.get("listen")]
                for addr in listens:
                    if addr not in self.topology.replicas:
                        self.topology.replicas.append(addr)
                        changed = True
                if changed:
                    self._persist_state()
                _counters.set_gauge(
                    "sentinel_known_replicas", len(self.topology.replicas)
                )
        if changed:
            self._notify_topology()

    def _fence_stale_primaries(self) -> None:
        """Demote any watched-for node that reappears claiming a stale
        primaryship — the restarted pre-failover primary."""
        with self._lock:
            watch = list(self._fence_watch)
            epoch, primary = self.topology.epoch, self.topology.primary
        for addr in watch:
            if addr == primary:
                with self._lock:
                    self._fence_watch.discard(addr)
                    self._persist_state()
                continue
            try:
                h = self._node(addr, "Health", {})
            except grpc.RpcError:
                continue
            if h.get("role") == "primary" and int(h.get("epoch") or 0) < epoch:
                log.warning(
                    "fencing stale primary %s (epoch %s < %d): demoting "
                    "to replica of %s",
                    addr, h.get("epoch"), epoch, primary,
                )
                try:
                    self._node(
                        addr,
                        "ReplicaOf",
                        {"primary": primary, "epoch": epoch},
                        timeout=self.promote_timeout_s,
                    )
                    _counters.incr("sentinel_fenced")
                except grpc.RpcError:
                    continue
            # demoted (by us or already a replica): back into the pool
            with self._lock:
                self._fence_watch.discard(addr)
                if (
                    addr != self.topology.primary
                    and addr not in self.topology.replicas
                ):
                    self.topology.replicas.append(addr)
                self._persist_state()
            self._notify_topology()

    # -- failover ------------------------------------------------------------

    def _adopt_completed_failover(self) -> bool:
        """Before spending an epoch on an election, look for a failover
        that ALREADY happened: a known replica claiming primaryship
        under a newer epoch means some leader finished while this
        sentinel was still counting misses (its AnnounceTopology may be
        in flight, or lost). Adopting it is cheaper than dueling — and
        dueling elections under load are exactly how a quorum burns
        epochs re-promoting the same node."""
        with self._lock:
            candidates = list(self.topology.replicas)
            epoch = self.topology.epoch
            old_primary = self.topology.primary
        for addr in candidates:
            try:
                h = self._node(addr, "Health", {})
            except grpc.RpcError:
                continue
            if h.get("role") == "primary" and int(h.get("epoch") or 0) > epoch:
                incoming = Topology(
                    epoch=int(h["epoch"]),
                    primary=addr,
                    replicas=[a for a in candidates if a != addr],
                )
                with self._lock:
                    if self.topology.adopt(incoming):
                        self._sdown = False
                        self._first_fail = None
                        self._fence_watch.add(old_primary)
                        self._persist_state()
                self._notify_topology()
                log.info(
                    "adopted completed failover: %s is primary at epoch %d",
                    addr, incoming.epoch,
                )
                _counters.incr("sentinel_failovers_adopted")
                return True
        return False

    def _attempt_failover(self) -> None:
        if self._adopt_completed_failover():
            return
        with self._lock:
            new_epoch = max(self.topology.epoch, self._last_vote_epoch) + 1
            primary = self.topology.primary
            # vote for ourselves (term discipline: once per epoch) —
            # persisted like any granted vote
            self._last_vote_epoch = new_epoch
            self._persist_state()
        faults.fire("ha.vote")
        # election trace id (ISSUE 16 satellite): deterministic per
        # (epoch, sentinel), so two sentinels dueling the same epoch
        # still produce distinguishable traces. Every RPC span of this
        # election spills to the black box — elections are crash
        # forensics by definition.
        rid = f"election-{new_epoch}-{self.sentinel_id[:8]}"
        self.last_election_rid = rid
        tracing = obs_trace.enabled()
        votes = 1
        for peer in self.peers:
            w0 = time.time()
            t0 = time.perf_counter()
            granted = ok = False
            try:
                resp = self._peer(
                    peer,
                    "VoteDown",
                    {"epoch": new_epoch, "primary": primary,
                     "candidate": self.sentinel_id},
                )
                ok = True
                granted = bool(resp.get("granted"))
            except grpc.RpcError:
                pass
            finally:
                if tracing:
                    obs_trace.record_span(
                        "sentinel.vote_down",
                        rid=rid,
                        start=w0,
                        duration_s=time.perf_counter() - t0,
                        attrs={"peer": peer, "epoch": new_epoch,
                               "ok": ok, "granted": granted},
                        spill=True,
                    )
            if granted:
                votes += 1
        _counters.set_gauge("sentinel_last_election_votes", votes)
        if votes < self.quorum:
            log.info(
                "sentinel %s: election for epoch %d got %d/%d votes; "
                "will retry",
                self.sentinel_id, new_epoch, votes, self.quorum,
            )
            return
        _counters.incr("sentinel_odown_agreed")
        log.warning(
            "sentinel %s: %s is objectively DOWN (%d/%d votes) — leading "
            "failover epoch %d",
            self.sentinel_id, primary, votes, self.quorum, new_epoch,
        )
        self._do_failover(new_epoch, primary, rid=rid)

    def _verify_promoted(self, addr: str, epoch: int) -> bool:
        """Did a Promote that timed out client-side land anyway? Poll the
        candidate's Health briefly for ``role=primary`` at (or past) the
        election epoch."""
        deadline = time.monotonic() + self.promote_timeout_s
        while time.monotonic() < deadline:
            try:
                h = self._node(addr, "Health", {})
                if (
                    h.get("role") == "primary"
                    and int(h.get("epoch") or 0) >= epoch
                ):
                    return True
            except grpc.RpcError:
                pass
            time.sleep(min(0.2, self.poll_s))
        return False

    def _replica_cursor(self, addr: str) -> Optional[int]:
        """Catch-up metric for candidate ranking: the replica's applied
        cursor (higher = fresher; lowest repl_lag_seq by construction)."""
        try:
            h = self._node(addr, "Health", {})
        except grpc.RpcError:
            return None
        repl = h.get("replication") or {}
        cursor = repl.get("cursor")
        return int(cursor) if cursor is not None else 0

    def _do_failover(
        self, epoch: int, old_primary: str, rid: Optional[str] = None
    ) -> None:
        tracing = obs_trace.enabled() and rid is not None
        with self._lock:
            candidates = [
                a for a in self.topology.replicas if a != old_primary
            ]
        ranked = sorted(
            (
                (cursor, addr)
                for addr in candidates
                if (cursor := self._replica_cursor(addr)) is not None
            ),
            key=lambda t: (-t[0], t[1]),
        )
        if not ranked:
            log.error(
                "failover epoch %d: no reachable replica to promote", epoch
            )
            return
        for cursor, winner in ranked:
            w0 = time.time()
            t0 = time.perf_counter()
            try:
                resp = self._node(
                    winner,
                    "Promote",
                    {"epoch": epoch},
                    timeout=self.promote_timeout_s,
                )
            except grpc.RpcError as e:
                # a timed-out Promote may still have LANDED (it is not
                # idempotent to just try the next candidate — that is
                # how co-primaries duel). Verify before moving on.
                if self._verify_promoted(winner, epoch):
                    resp = {"ok": True}
                else:
                    if tracing:
                        obs_trace.record_span(
                            "sentinel.promote",
                            rid=rid,
                            start=w0,
                            duration_s=time.perf_counter() - t0,
                            attrs={"candidate": winner, "epoch": epoch,
                                   "ok": False},
                            spill=True,
                        )
                    log.warning(
                        "failover epoch %d: promoting %s failed (%s); "
                        "trying the next candidate",
                        epoch, winner, getattr(e, "code", lambda: e)(),
                    )
                    continue
            if tracing:
                obs_trace.record_span(
                    "sentinel.promote",
                    rid=rid,
                    start=w0,
                    duration_s=time.perf_counter() - t0,
                    attrs={"candidate": winner, "epoch": epoch,
                           "ok": bool(resp.get("ok"))},
                    spill=True,
                )
            if not resp.get("ok"):
                log.warning(
                    "failover epoch %d: %s refused promotion: %s",
                    epoch, winner, resp.get("error"),
                )
                continue
            survivors = [a for a in candidates if a != winner]
            with self._lock:
                self.topology = Topology(
                    epoch=epoch, primary=winner, replicas=list(survivors)
                )
                self._sdown = False
                self._first_fail = None
                self._fence_watch.add(old_primary)
                self._persist_state()
            self._notify_topology()
            self.failovers += 1
            _counters.incr("sentinel_failovers")
            # flight recorder (ISSUE 15): the completed election is the
            # anchor event every failover post-mortem is built around
            obs_flight.note(
                "election", epoch=int(epoch), winner=winner,
                old_primary=old_primary, survivors=len(survivors),
            )
            log.warning(
                "failover epoch %d: promoted %s (cursor %s); re-pointing "
                "%d survivor(s)",
                epoch, winner, cursor, len(survivors),
            )
            for addr in survivors:
                try:
                    self._node(
                        addr,
                        "ReplicaOf",
                        {"primary": winner, "epoch": epoch},
                        timeout=self.promote_timeout_s,
                    )
                except grpc.RpcError:
                    log.warning(
                        "failover epoch %d: could not re-point %s (it "
                        "will be fenced/re-pointed when reachable)",
                        epoch, addr,
                    )
                    with self._lock:
                        self._fence_watch.add(addr)
                        self._persist_state()
            announce = {
                **self.topology.to_dict(),
                "fenced": old_primary,
                "leader": self.sentinel_id,
            }
            for peer in self.peers:
                w0 = time.time()
                t0 = time.perf_counter()
                pushed = False
                try:
                    self._peer(peer, "AnnounceTopology", announce)
                    pushed = True
                except grpc.RpcError:
                    pass
                if tracing:
                    obs_trace.record_span(
                        "sentinel.topology",
                        rid=rid,
                        start=w0,
                        duration_s=time.perf_counter() - t0,
                        attrs={"peer": peer, "epoch": epoch,
                               "ok": pushed},
                        spill=True,
                    )
            return
        log.error("failover epoch %d: every candidate refused", epoch)


def main(argv: Optional[list] = None) -> None:
    """``python -m tpubloom.sentinel --watch HOST:PORT [--peers A B ...]
    [--port N] [--quorum N] [--down-after S] [--poll S]``"""
    import sys as _sys

    parser = argparse.ArgumentParser(
        prog="tpubloom.sentinel",
        description="tpubloom failover watcher (Redis Sentinel parity)",
    )
    parser.add_argument(
        "--watch", required=True, metavar="HOST:PORT",
        help="the primary to monitor",
    )
    parser.add_argument(
        "--peers", nargs="*", default=[], metavar="HOST:PORT",
        help="the other sentinels of the quorum",
    )
    parser.add_argument(
        "--port", type=int, default=26379,
        help="this sentinel's gRPC port (default 26379)",
    )
    parser.add_argument(
        "--quorum", type=int, default=None,
        help="votes needed for ODOWN+failover (default: majority)",
    )
    parser.add_argument(
        "--down-after", type=float, default=1.5,
        help="seconds of failed polls before SDOWN (default 1.5)",
    )
    parser.add_argument(
        "--poll", type=float, default=0.25,
        help="health poll interval in seconds (default 0.25)",
    )
    parser.add_argument(
        "--state-dir", default=None,
        help="persist failover memory (topology epoch + vote discipline) "
        "to a CRC-checked sentinel_state.json in this directory, so a "
        "restart resumes at the post-failover view (default: in-memory "
        "only)",
    )
    args = parser.parse_args(
        list(_sys.argv[1:]) if argv is None else list(argv)
    )
    logging.basicConfig(level=logging.INFO)
    faults.load_env()
    if args.state_dir:
        # crash-forensics black box (ISSUE 16): a sentinel with durable
        # state gets durable forensics too — its election spans spill
        # into <state-dir>/blackbox/ and the boot event anchors which
        # process wrote them. Tracing arms at sample 0.0: only the
        # explicit election spans record, nothing else pays.
        obs_blackbox.configure(
            args.state_dir, node={"addr": f"0.0.0.0:{args.port}"}
        )
        obs_trace.ensure_enabled()
        obs_flight.note(
            "boot", role="sentinel", epoch=0, addr=f"0.0.0.0:{args.port}"
        )
    sentinel = Sentinel(
        args.watch,
        args.peers,
        listen=f"0.0.0.0:{args.port}",
        quorum=args.quorum,
        poll_s=args.poll,
        down_after_s=args.down_after,
        state_dir=args.state_dir,
    ).start()
    log.info("sentinel serving on :%d", sentinel.port)
    stop = threading.Event()
    import signal

    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda signum, frame: stop.set())
    stop.wait()
    sentinel.stop()
