"""Sentinel-parity failover coordinator (ISSUE 4 tentpole).

``python -m tpubloom.sentinel --watch host:port --peers ...`` runs one
watcher of a quorum of N. Each sentinel is a tiny gRPC service
(:data:`tpubloom.server.protocol.SENTINEL_SERVICE`) plus a monitor
thread:

* **health polling** — the watched primary's ``Health`` RPC every
  ``poll_s``; misses accumulate into **SDOWN** (subjectively down) after
  ``down_after_s``, Redis Sentinel's terminology and shape;
* **SDOWN→ODOWN by vote** — a subjectively-down sentinel asks its peers
  for an epoch-stamped vote (``VoteDown``). A peer grants iff it also
  sees the primary down AND has not yet voted in that epoch — the Raft
  term rule (vote once per term) without the rest of Raft: no log
  replication, just a leader lease for one failover. Majority of the
  quorum = **ODOWN** + leadership for that epoch;
* **failover** — the leader reads each known replica's ``Health`` and
  picks the most caught-up one (highest replication cursor =
  lowest ``repl_lag_seq``), sends it ``Promote {epoch}``, re-points the
  survivors with ``ReplicaOf {primary, epoch}``, and announces the new
  topology to its peers (``AnnounceTopology``);
* **fencing** — any node later observed claiming ``role=primary`` under
  an epoch OLDER than the current topology's (the restarted pre-failover
  primary) is demoted on sight with ``ReplicaOf`` — split-brain ends the
  moment a sentinel can reach the stale node;
* **discovery** — replicas are discovered from the primary's
  ``Health.replication.replicas[].listen`` announcements (Redis
  ``INFO replication`` parity); clients ask any sentinel ``Topology``
  for the current epoch/primary/replicas (``SENTINEL
  get-master-addr-by-name`` parity).

Fault point ``ha.vote`` fires in both the vote-request and vote-grant
paths, so the chaos suite can kill a failover mid-election.
"""

from __future__ import annotations

import argparse
import logging
import threading
import time
from concurrent import futures
from typing import Optional

import grpc

from tpubloom import faults
from tpubloom.ha.topology import Topology
from tpubloom.obs import counters as _counters
from tpubloom.server import protocol

log = logging.getLogger("tpubloom.sentinel")

_CHANNEL_OPTIONS = [
    ("grpc.max_receive_message_length", 64 * 1024 * 1024),
]


class Sentinel:
    """One failover watcher; run N of these (N odd) for a quorum."""

    def __init__(
        self,
        watch: str,
        peers: Optional[list] = None,
        *,
        listen: str = "127.0.0.1:0",
        quorum: Optional[int] = None,
        poll_s: float = 0.25,
        down_after_s: float = 1.5,
        rpc_timeout_s: float = 1.0,
        promote_timeout_s: Optional[float] = None,
        failover_cooldown_s: float = 2.0,
        sentinel_id: Optional[str] = None,
    ):
        import secrets

        self.peers = list(peers or ())
        total = len(self.peers) + 1
        #: votes (incl. our own) needed for ODOWN + failover leadership;
        #: default = majority, so two concurrent elections cannot both win
        self.quorum = quorum if quorum is not None else total // 2 + 1
        self.poll_s = poll_s
        self.down_after_s = down_after_s
        self.rpc_timeout_s = rpc_timeout_s
        #: Promote/ReplicaOf are heavyweight (log adoption, epoch
        #: persist, applier teardown) and MUST NOT be declared failed on
        #: a health-poll-grade deadline — a spuriously "failed" promote
        #: that lands late is how dueling co-primaries happen
        self.promote_timeout_s = (
            promote_timeout_s
            if promote_timeout_s is not None
            else max(5.0, 5 * rpc_timeout_s)
        )
        self.failover_cooldown_s = failover_cooldown_s
        self.sentinel_id = sentinel_id or secrets.token_hex(8)
        self.topology = Topology(epoch=0, primary=watch, replicas=[])
        self._lock = threading.Lock()
        #: newest epoch this sentinel has VOTED in (self-votes included):
        #: one vote per epoch is the whole split-brain argument
        self._last_vote_epoch = 0
        self._sdown = False
        self._first_fail: Optional[float] = None
        self._last_failover_attempt = 0.0
        #: when we last GRANTED a peer's vote: someone else is leading a
        #: failover — hold our own candidacy back so the quorum does not
        #: burn epochs on dueling elections (Redis Sentinel's
        #: failover-timeout hold-off, randomly staggered like its
        #: election delays)
        self._granted_at = 0.0
        import random as _random

        self._rand = _random.Random()
        self._election_stagger = self._rand.uniform(0, failover_cooldown_s)
        #: demoted-primary watchlist: addresses to fence if they come
        #: back claiming a stale primaryship
        self._fence_watch: set = set()
        self.failovers = 0
        self._stop = threading.Event()
        self._channels: dict = {}
        self._thread = threading.Thread(
            target=self._run, name="tpubloom-sentinel", daemon=True
        )
        self._server = grpc.server(
            futures.ThreadPoolExecutor(
                max_workers=4, thread_name_prefix="sentinel-rpc"
            )
        )
        handlers = {
            m: grpc.unary_unary_rpc_method_handler(self._wrap(m))
            for m in protocol.SENTINEL_METHODS
        }
        self._server.add_generic_rpc_handlers(
            (
                grpc.method_handlers_generic_handler(
                    protocol.SENTINEL_SERVICE, handlers
                ),
            )
        )
        self.port = self._server.add_insecure_port(listen)
        host = listen.rsplit(":", 1)[0] or "127.0.0.1"
        self.address = f"{host}:{self.port}"

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "Sentinel":
        self._server.start()
        self._thread.start()
        log.info(
            "sentinel %s watching %s (quorum %d of %d, peers %s) on %s",
            self.sentinel_id, self.topology.primary, self.quorum,
            len(self.peers) + 1, self.peers, self.address,
        )
        return self

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)
        self._server.stop(grace=None)
        for ch in self._channels.values():
            ch.close()
        self._channels.clear()

    # -- RPC plumbing --------------------------------------------------------

    def _wrap(self, method: str):
        handler = getattr(self, "handle_" + method)

        def unary_unary(request: bytes, context) -> bytes:
            try:
                req = protocol.decode(request) if request else {}
                resp = handler(req)
            except Exception as e:  # noqa: BLE001 — surface, don't kill
                log.exception("sentinel RPC %s failed", method)
                resp = protocol.error_response(
                    "INTERNAL", f"{type(e).__name__}: {e}"
                )
            return protocol.encode(resp)

        return unary_unary

    def _channel(self, address: str):
        ch = self._channels.get(address)
        if ch is None:
            ch = grpc.insecure_channel(address, options=_CHANNEL_OPTIONS)
            self._channels[address] = ch
        return ch

    def _call(
        self,
        address: str,
        path: str,
        req: dict,
        timeout: Optional[float] = None,
    ) -> dict:
        raw = self._channel(address).unary_unary(
            path,
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b,
        )(protocol.encode(req), timeout=timeout or self.rpc_timeout_s)
        return protocol.decode(raw)

    def _node(
        self,
        address: str,
        method: str,
        req: dict,
        timeout: Optional[float] = None,
    ) -> dict:
        return self._call(
            address, protocol.method_path(method), req, timeout=timeout
        )

    def _peer(self, address: str, method: str, req: dict) -> dict:
        return self._call(address, protocol.sentinel_method_path(method), req)

    # -- sentinel RPC handlers ------------------------------------------------

    def handle_Ping(self, req: dict) -> dict:
        return {
            "ok": True,
            "sentinel_id": self.sentinel_id,
            "epoch": self.topology.epoch,
            "sdown": self._sdown,
        }

    def handle_Topology(self, req: dict) -> dict:
        """Client-facing discovery (SENTINEL get-master-addr parity)."""
        with self._lock:
            return {"ok": True, **self.topology.to_dict()}

    def handle_VoteDown(self, req: dict) -> dict:
        """Epoch-stamped leader vote: granted iff we ALSO see that
        primary down (our own SDOWN — the ODOWN agreement) and we have
        not voted in this epoch yet (the term discipline)."""
        faults.fire("ha.vote")
        epoch = int(req.get("epoch") or 0)
        primary = req.get("primary")
        with self._lock:
            granted = (
                primary == self.topology.primary
                and self._sdown
                and epoch > self.topology.epoch
                and epoch > self._last_vote_epoch
            )
            if granted:
                self._last_vote_epoch = epoch
                self._granted_at = time.monotonic()
                _counters.incr("sentinel_votes_granted")
        return {
            "ok": True,
            "granted": granted,
            "epoch": self.topology.epoch,
            "sdown": self._sdown,
        }

    def handle_AnnounceTopology(self, req: dict) -> dict:
        """A failover leader announcing its result; adopt if newer."""
        incoming = Topology.from_dict(req)
        with self._lock:
            adopted = self.topology.adopt(incoming)
            if adopted:
                self._sdown = False
                self._first_fail = None
                old = req.get("fenced")
                if old:
                    self._fence_watch.add(old)
                log.info(
                    "adopted topology epoch %d (primary %s) from peer",
                    incoming.epoch, incoming.primary,
                )
        return {"ok": True, "adopted": adopted, "epoch": self.topology.epoch}

    # -- the monitor loop ----------------------------------------------------

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                self._poll_primary()
                self._fence_stale_primaries()
                now = time.monotonic()
                # a granted vote means a peer is leading a failover that
                # may legitimately take up to promote_timeout — hold our
                # own candidacy back at least that long
                grant_holdoff = max(
                    4 * self.failover_cooldown_s,
                    self.failover_cooldown_s + self.promote_timeout_s,
                )
                if (
                    self._sdown
                    and now - self._last_failover_attempt
                    >= self.failover_cooldown_s + self._election_stagger
                    and now - self._granted_at >= grant_holdoff
                ):
                    self._last_failover_attempt = now
                    # re-roll the stagger per attempt so two sentinels
                    # whose retry slots collided once do not collide on
                    # every retry
                    self._election_stagger = self._rand.uniform(
                        0, self.failover_cooldown_s
                    )
                    self._attempt_failover()
            except Exception:  # noqa: BLE001 — the watcher must survive
                log.exception("sentinel monitor tick failed")

    def _poll_primary(self) -> None:
        with self._lock:
            primary = self.topology.primary
        try:
            h = self._node(primary, "Health", {})
        except grpc.RpcError:
            now = time.monotonic()
            if self._first_fail is None:
                self._first_fail = now
            if not self._sdown and now - self._first_fail >= self.down_after_s:
                self._sdown = True
                # start the election clock HERE: every sentinel reaches
                # SDOWN within one poll period of the others, so an
                # immediately-eligible first attempt is a guaranteed
                # three-way self-vote tie. The staggered delay gives one
                # sentinel a clean head start instead (Redis Sentinel's
                # randomized failover start delay).
                self._last_failover_attempt = now
                _counters.incr("sentinel_sdown_entered")
                log.warning(
                    "sentinel %s: %s is subjectively DOWN",
                    self.sentinel_id, primary,
                )
            _counters.set_gauge("sentinel_sdown", 1.0 if self._sdown else 0.0)
            return
        self._first_fail = None
        if self._sdown:
            log.info("sentinel %s: %s is back", self.sentinel_id, primary)
        self._sdown = False
        _counters.set_gauge("sentinel_sdown", 0.0)
        with self._lock:
            self.topology.epoch = max(
                self.topology.epoch, int(h.get("epoch") or 0)
            )
            if h.get("role") == "replica":
                # the watched node was demoted behind our back (manual
                # REPLICAOF / a failover we missed): follow its view
                upstream = (h.get("replication") or {}).get("primary")
                if upstream and upstream != primary:
                    log.warning(
                        "watched node %s is now a replica of %s; following",
                        primary, upstream,
                    )
                    self._fence_watch.discard(upstream)
                    if primary not in self.topology.replicas:
                        self.topology.replicas.append(primary)
                    self.topology.primary = upstream
                return
            # discover announced replicas (INFO replication parity)
            sessions = (h.get("replication") or {}).get("replicas") or ()
            listens = [s.get("listen") for s in sessions if s.get("listen")]
            for addr in listens:
                if addr not in self.topology.replicas:
                    self.topology.replicas.append(addr)
            _counters.set_gauge(
                "sentinel_known_replicas", len(self.topology.replicas)
            )

    def _fence_stale_primaries(self) -> None:
        """Demote any watched-for node that reappears claiming a stale
        primaryship — the restarted pre-failover primary."""
        with self._lock:
            watch = list(self._fence_watch)
            epoch, primary = self.topology.epoch, self.topology.primary
        for addr in watch:
            if addr == primary:
                with self._lock:
                    self._fence_watch.discard(addr)
                continue
            try:
                h = self._node(addr, "Health", {})
            except grpc.RpcError:
                continue
            if h.get("role") == "primary" and int(h.get("epoch") or 0) < epoch:
                log.warning(
                    "fencing stale primary %s (epoch %s < %d): demoting "
                    "to replica of %s",
                    addr, h.get("epoch"), epoch, primary,
                )
                try:
                    self._node(
                        addr,
                        "ReplicaOf",
                        {"primary": primary, "epoch": epoch},
                        timeout=self.promote_timeout_s,
                    )
                    _counters.incr("sentinel_fenced")
                except grpc.RpcError:
                    continue
            # demoted (by us or already a replica): back into the pool
            with self._lock:
                self._fence_watch.discard(addr)
                if (
                    addr != self.topology.primary
                    and addr not in self.topology.replicas
                ):
                    self.topology.replicas.append(addr)

    # -- failover ------------------------------------------------------------

    def _adopt_completed_failover(self) -> bool:
        """Before spending an epoch on an election, look for a failover
        that ALREADY happened: a known replica claiming primaryship
        under a newer epoch means some leader finished while this
        sentinel was still counting misses (its AnnounceTopology may be
        in flight, or lost). Adopting it is cheaper than dueling — and
        dueling elections under load are exactly how a quorum burns
        epochs re-promoting the same node."""
        with self._lock:
            candidates = list(self.topology.replicas)
            epoch = self.topology.epoch
            old_primary = self.topology.primary
        for addr in candidates:
            try:
                h = self._node(addr, "Health", {})
            except grpc.RpcError:
                continue
            if h.get("role") == "primary" and int(h.get("epoch") or 0) > epoch:
                incoming = Topology(
                    epoch=int(h["epoch"]),
                    primary=addr,
                    replicas=[a for a in candidates if a != addr],
                )
                with self._lock:
                    if self.topology.adopt(incoming):
                        self._sdown = False
                        self._first_fail = None
                        self._fence_watch.add(old_primary)
                log.info(
                    "adopted completed failover: %s is primary at epoch %d",
                    addr, incoming.epoch,
                )
                _counters.incr("sentinel_failovers_adopted")
                return True
        return False

    def _attempt_failover(self) -> None:
        if self._adopt_completed_failover():
            return
        with self._lock:
            new_epoch = max(self.topology.epoch, self._last_vote_epoch) + 1
            primary = self.topology.primary
            # vote for ourselves (term discipline: once per epoch)
            self._last_vote_epoch = new_epoch
        faults.fire("ha.vote")
        votes = 1
        for peer in self.peers:
            try:
                resp = self._peer(
                    peer,
                    "VoteDown",
                    {"epoch": new_epoch, "primary": primary,
                     "candidate": self.sentinel_id},
                )
            except grpc.RpcError:
                continue
            if resp.get("granted"):
                votes += 1
        _counters.set_gauge("sentinel_last_election_votes", votes)
        if votes < self.quorum:
            log.info(
                "sentinel %s: election for epoch %d got %d/%d votes; "
                "will retry",
                self.sentinel_id, new_epoch, votes, self.quorum,
            )
            return
        _counters.incr("sentinel_odown_agreed")
        log.warning(
            "sentinel %s: %s is objectively DOWN (%d/%d votes) — leading "
            "failover epoch %d",
            self.sentinel_id, primary, votes, self.quorum, new_epoch,
        )
        self._do_failover(new_epoch, primary)

    def _verify_promoted(self, addr: str, epoch: int) -> bool:
        """Did a Promote that timed out client-side land anyway? Poll the
        candidate's Health briefly for ``role=primary`` at (or past) the
        election epoch."""
        deadline = time.monotonic() + self.promote_timeout_s
        while time.monotonic() < deadline:
            try:
                h = self._node(addr, "Health", {})
                if (
                    h.get("role") == "primary"
                    and int(h.get("epoch") or 0) >= epoch
                ):
                    return True
            except grpc.RpcError:
                pass
            time.sleep(min(0.2, self.poll_s))
        return False

    def _replica_cursor(self, addr: str) -> Optional[int]:
        """Catch-up metric for candidate ranking: the replica's applied
        cursor (higher = fresher; lowest repl_lag_seq by construction)."""
        try:
            h = self._node(addr, "Health", {})
        except grpc.RpcError:
            return None
        repl = h.get("replication") or {}
        cursor = repl.get("cursor")
        return int(cursor) if cursor is not None else 0

    def _do_failover(self, epoch: int, old_primary: str) -> None:
        with self._lock:
            candidates = [
                a for a in self.topology.replicas if a != old_primary
            ]
        ranked = sorted(
            (
                (cursor, addr)
                for addr in candidates
                if (cursor := self._replica_cursor(addr)) is not None
            ),
            key=lambda t: (-t[0], t[1]),
        )
        if not ranked:
            log.error(
                "failover epoch %d: no reachable replica to promote", epoch
            )
            return
        for cursor, winner in ranked:
            try:
                resp = self._node(
                    winner,
                    "Promote",
                    {"epoch": epoch},
                    timeout=self.promote_timeout_s,
                )
            except grpc.RpcError as e:
                # a timed-out Promote may still have LANDED (it is not
                # idempotent to just try the next candidate — that is
                # how co-primaries duel). Verify before moving on.
                if self._verify_promoted(winner, epoch):
                    resp = {"ok": True}
                else:
                    log.warning(
                        "failover epoch %d: promoting %s failed (%s); "
                        "trying the next candidate",
                        epoch, winner, getattr(e, "code", lambda: e)(),
                    )
                    continue
            if not resp.get("ok"):
                log.warning(
                    "failover epoch %d: %s refused promotion: %s",
                    epoch, winner, resp.get("error"),
                )
                continue
            survivors = [a for a in candidates if a != winner]
            with self._lock:
                self.topology = Topology(
                    epoch=epoch, primary=winner, replicas=list(survivors)
                )
                self._sdown = False
                self._first_fail = None
                self._fence_watch.add(old_primary)
            self.failovers += 1
            _counters.incr("sentinel_failovers")
            log.warning(
                "failover epoch %d: promoted %s (cursor %s); re-pointing "
                "%d survivor(s)",
                epoch, winner, cursor, len(survivors),
            )
            for addr in survivors:
                try:
                    self._node(
                        addr,
                        "ReplicaOf",
                        {"primary": winner, "epoch": epoch},
                        timeout=self.promote_timeout_s,
                    )
                except grpc.RpcError:
                    log.warning(
                        "failover epoch %d: could not re-point %s (it "
                        "will be fenced/re-pointed when reachable)",
                        epoch, addr,
                    )
                    with self._lock:
                        self._fence_watch.add(addr)
            announce = {
                **self.topology.to_dict(),
                "fenced": old_primary,
                "leader": self.sentinel_id,
            }
            for peer in self.peers:
                try:
                    self._peer(peer, "AnnounceTopology", announce)
                except grpc.RpcError:
                    pass
            return
        log.error("failover epoch %d: every candidate refused", epoch)


def main(argv: Optional[list] = None) -> None:
    """``python -m tpubloom.sentinel --watch HOST:PORT [--peers A B ...]
    [--port N] [--quorum N] [--down-after S] [--poll S]``"""
    import sys as _sys

    parser = argparse.ArgumentParser(
        prog="tpubloom.sentinel",
        description="tpubloom failover watcher (Redis Sentinel parity)",
    )
    parser.add_argument(
        "--watch", required=True, metavar="HOST:PORT",
        help="the primary to monitor",
    )
    parser.add_argument(
        "--peers", nargs="*", default=[], metavar="HOST:PORT",
        help="the other sentinels of the quorum",
    )
    parser.add_argument(
        "--port", type=int, default=26379,
        help="this sentinel's gRPC port (default 26379)",
    )
    parser.add_argument(
        "--quorum", type=int, default=None,
        help="votes needed for ODOWN+failover (default: majority)",
    )
    parser.add_argument(
        "--down-after", type=float, default=1.5,
        help="seconds of failed polls before SDOWN (default 1.5)",
    )
    parser.add_argument(
        "--poll", type=float, default=0.25,
        help="health poll interval in seconds (default 0.25)",
    )
    args = parser.parse_args(
        list(_sys.argv[1:]) if argv is None else list(argv)
    )
    logging.basicConfig(level=logging.INFO)
    faults.load_env()
    sentinel = Sentinel(
        args.watch,
        args.peers,
        listen=f"0.0.0.0:{args.port}",
        quorum=args.quorum,
        poll_s=args.poll,
        down_after_s=args.down_after,
    ).start()
    log.info("sentinel serving on :%d", sentinel.port)
    stop = threading.Event()
    import signal

    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda signum, frame: stop.set())
    stop.wait()
    sentinel.stop()
