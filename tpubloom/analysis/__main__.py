"""Unified whole-system invariant driver (ISSUE 13).

``python -m tpubloom.analysis [--json]`` is the one CI entry point for
the static half of the correctness tooling: it runs

* the full static tree lint (:mod:`tpubloom.analysis.lint` — all
  checks, tree mode on), and
* the lock-ORDER manifest diff (:mod:`tpubloom.analysis.lock_order`)
  over every collected ``lockcheck-*.json`` runtime report it can find
  (``--reports`` paths, else ``$TPUBLOOM_LOCK_CHECK_DIR``),

and folds both into ONE exit code: 0 = the tree is clean AND every
observed runtime acquisition edge is declared; 1 = anything, anywhere,
drifted. The chaos shards upload their report dirs as artifacts and the
``analysis`` CI job replays them through this driver — so a lock edge
minted on the chaos runner fails the same gate a bad suppression does.

Report collection is OPTIONAL by design: with no reports given and no
``$TPUBLOOM_LOCK_CHECK_DIR``, the driver runs the static half alone
(the common local invocation). An explicitly given but unreadable
report path IS a finding — a CI wiring rot must not look like a pass.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Optional

from tpubloom.analysis import lint, lock_order


def _collect_report_paths(reports: Optional[list]) -> tuple:
    """(paths, explicit): expand files/dirs; ``explicit`` is True when
    the operator passed ``--reports`` AT ALL — including with zero
    values (the classic ``--reports $DIR`` with ``$DIR`` unset CI
    wiring rot), so an empty expansion is a finding."""
    explicit = reports is not None
    reports = list(reports or ())
    if not explicit:
        env_dir = os.environ.get("TPUBLOOM_LOCK_CHECK_DIR", "")
        reports = [env_dir] if env_dir else []
    paths: list = []
    for p in reports:
        if os.path.isdir(p):
            paths.extend(sorted(glob.glob(os.path.join(p, "lockcheck-*.json"))))
        elif p and (explicit or os.path.exists(p)):
            # a merely-INHERITED env dir that does not exist yet is not
            # a finding (no run has collected anything); an explicitly
            # named missing path is — see the module docstring
            paths.append(p)
    return paths, explicit


def run(
    lint_paths: Optional[list] = None,
    reports: Optional[list] = None,
    repo_root: Optional[str] = None,
) -> dict:
    """Library entry: ``{"lint": [...], "lock_order": [...],
    "reports_checked": N}`` — finding lists empty on a clean system."""
    repo_root = repo_root or lint._repo_root()
    targets = lint_paths or [os.path.join(repo_root, "tpubloom")]
    config = lint.LintConfig(repo_root=repo_root)
    lint_findings = lint.lint_paths(targets, config)

    # None = not requested (env fallback); [] = requested with nothing
    # to expand, which IS a finding
    paths, explicit = _collect_report_paths(reports)
    lock_findings: list = []
    if explicit and not paths:
        lock_findings.append(
            {
                "kind": "no-reports",
                "message": "report paths given but no lockcheck-*.json "
                "found — the runtime gate did not actually run",
            }
        )
    n_reports = 0
    for path in paths:
        try:
            with open(path) as f:
                report = json.load(f)
        except (OSError, ValueError) as e:
            lock_findings.append(
                {"kind": "unreadable-report", "message": f"{path}: {e}"}
            )
            continue
        n_reports += 1
        for v in report.get("violations", ()):
            lock_findings.append(
                {
                    "kind": f"runtime-{v.get('kind', 'violation')}",
                    "message": v.get("message", ""),
                    "report": path,
                }
            )
        for finding in lock_order.check_report(report):
            lock_findings.append({**finding, "report": path})
    return {
        "lint": [f.to_dict() for f in lint_findings],
        "lock_order": lock_findings,
        "reports_checked": n_reports,
    }


def main(argv: Optional[list] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tpubloom.analysis",
        description="unified invariant analyzer: static tree lint + "
        "lock-order manifest diff over collected runtime reports, one "
        "exit code",
    )
    parser.add_argument(
        "paths", nargs="*", default=None,
        help="files/directories to lint (default: the tpubloom package)",
    )
    parser.add_argument(
        "--reports", nargs="*", default=None, metavar="PATH",
        help="lockcheck-*.json reports or directories of them (default: "
        "$TPUBLOOM_LOCK_CHECK_DIR when set; omitted entirely otherwise)",
    )
    parser.add_argument("--json", action="store_true", dest="as_json")
    args = parser.parse_args(argv)
    result = run(lint_paths=args.paths or None, reports=args.reports)
    findings = result["lint"] + result["lock_order"]
    if args.as_json:
        print(json.dumps(result, indent=2))
    else:
        for f in result["lint"]:
            print(f"{f['path']}:{f['line']}: [{f['check']}] {f['message']}")
        for f in result["lock_order"]:
            print(
                f"[{f['kind']}] {f['message']}"
                + (f"  ({f['report']})" if "report" in f else "")
            )
        print(
            f"tpubloom.analysis: {len(findings)} finding(s) "
            f"({len(result['lint'])} static, {len(result['lock_order'])} "
            f"lock-order) across {result['reports_checked']} runtime "
            f"report(s)"
        )
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
