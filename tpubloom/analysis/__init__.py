"""Project-specific correctness tooling (ISSUE 6, grown in ISSUE 13).

Three layers keep the concurrent subsystems honest:

* :mod:`tpubloom.analysis.lint` — static AST checkers encoding the
  invariants review kept re-finding as PRs 3-12 grew the stack: no
  blocking calls under the registry/filter locks, quorum barriers
  outside every lock, op-log append ordered before ``notify_inserts``,
  no use-after-donate on device buffers, every mutating handler
  replay-cached (or argued safe), and the protocol/fault-point/metric/
  phase registries closed under cross-reference — including that every
  declared fault point is actually ARMED by some test.
* :mod:`tpubloom.analysis.lock_order` — the declared lock-ORDER
  manifest every armed chaos module's runtime acquisition graph is
  diffed against at teardown (all five: faults, ha, sync_repl,
  cluster, ingest).
* :mod:`tpubloom.utils.locks` — runtime lock-order and
  held-while-blocking analysis behind the ``TPUBLOOM_LOCK_CHECK`` env
  var, armed in the chaos suites; its exit reports feed the manifest
  diff.

``python -m tpubloom.analysis [--json]`` is the unified driver: the
full static lint plus the manifest diff over collected
``lockcheck-*.json`` reports, one exit code (see
:mod:`tpubloom.analysis.__main__`).
"""
