"""Project-specific correctness tooling (ISSUE 6).

Two layers keep the concurrent subsystems honest:

* :mod:`tpubloom.analysis.lint` — static AST checkers encoding the
  invariants review kept re-finding in PRs 3-5 (no blocking calls under
  the registry/filter locks, op-log append ordered before
  ``notify_inserts``, protocol/fault-point/metric-name registries
  closed under cross-reference). Run ``python -m tpubloom.analysis.lint``.
* :mod:`tpubloom.utils.locks` — runtime lock-order and
  held-while-blocking analysis behind the ``TPUBLOOM_LOCK_CHECK`` env
  var, armed in the chaos suites.
"""
