"""Static concurrency/consistency lint for the tpubloom tree (ISSUE 6).

``python -m tpubloom.analysis.lint [paths...]`` (default: the installed
``tpubloom/`` package) runs AST-based checkers that encode the
project-specific invariants hand-review kept re-finding while PRs 3-5
grew the replication stack. Zero dependencies beyond the stdlib; exit
status 0 = clean, 1 = findings.

Checks
======

``blocking-under-lock``
    No blocking call — gRPC stubs (``_rpc``/``_call``/``_node``/
    ``_peer``/``grpc.insecure_channel``), ``Condition.wait`` without a
    timeout, fsync/flush/checkpoint IO (``os.fsync``, ``.flush()``,
    ``ckpt.restore``/``_tracked_restore``, ``checkpointer.close``),
    quorum waits (``wait_acked``, ``commit_barrier``), ``time.sleep``,
    thread/worker ``join``, ``Future.result`` — lexically inside a
    ``with`` on a registry/filter/admission mutex or a lock-named
    condition (attributes like ``lock``, ``_lock``, ``_cond``,
    ``_admit_lock`` ...). The runtime half of this check is
    :func:`tpubloom.utils.locks.note_blocking`.

``notify-before-append``
    In any function that both appends to the op log (``_log_op`` /
    ``_log_create`` / ``oplog.append``) and calls
    ``checkpointer.notify_inserts``, every notify must come AFTER the
    first append: a checkpoint triggered by its own batch must stamp
    that batch's seq (the PR-3 crash-replay bug class).

``fault-registry``
    Every literal fault-point string passed to ``faults.fire`` /
    ``arm`` / ``is_armed`` is declared in ``faults.KNOWN_POINTS`` —
    and (tree mode) every declared point appears as a literal somewhere
    outside the registry, so the vocabulary cannot rot.

``metric-registry``
    Every literal counter/gauge name emitted via ``counters.incr`` /
    ``metrics.count`` / ``counters.set_gauge`` is declared in
    :mod:`tpubloom.obs.names` under the right kind; (tree mode) every
    declared name is emitted at least once, and no name is declared
    twice or under both kinds.

``protocol-coverage``
    (tree mode) Every ``protocol.METHODS`` entry has a ``BloomService``
    handler, a client call site, and a golden-wire test; streaming
    methods are registered in the service behavior maps and golden-
    tested.

``ruby-parity``
    (tree mode) The Ruby drivers track the protocol too (ROADMAP item
    6 — two Ruby drivers now): every ``protocol.METHODS`` entry appears
    as a quoted call-site literal somewhere in ``clients/ruby``'s
    driver files, the base driver's ``METHODS`` registry constant
    matches the protocol list exactly (no drift in either direction),
    and the registry lists nothing the protocol dropped.

Suppressions
============

A finding is allowlisted inline, on the flagged line or its enclosing
``with`` line::

    mf.checkpointer.close()  # lint: allow(blocking-under-lock): unpublished

The reason is mandatory: an empty reason is itself a finding
(``suppression-reason``), as are suppressions naming unknown checks
(``unknown-suppression``) and suppressions that no longer match any
finding (``unused-suppression``) — allowlists cannot rot either.
"""

from __future__ import annotations

import argparse
import ast
import json
import os
import re
import sys
from dataclasses import dataclass, field
from typing import Iterable, Optional

CHECKS = (
    "blocking-under-lock",
    "notify-before-append",
    "fault-registry",
    "metric-registry",
    "protocol-coverage",
    "ruby-parity",
    "suppression-reason",
    "unknown-suppression",
    "unused-suppression",
)

#: ``with`` context attributes treated as "a lock is held inside".
LOCK_ATTRS = frozenset(
    {
        "lock",
        "_lock",
        "_cond",
        "_admit_lock",
        "_promote_lock",
        "_dedup_lock",
        "_trigger_lock",
        "_call_lock",
    }
)

#: Method names that are blocking wherever they appear.
BLOCKING_METHOD_NAMES = frozenset(
    {"wait_acked", "commit_barrier", "_tracked_restore",
     "_rpc", "_node", "_peer", "result", "flush"}
)

#: Fully dotted calls that are blocking.
BLOCKING_DOTTED = frozenset(
    {
        "os.fsync",
        "time.sleep",
        "subprocess.run",
        "subprocess.check_call",
        "subprocess.check_output",
        "grpc.insecure_channel",
    }
)

SUPPRESS_RE = re.compile(
    r"#\s*lint:\s*allow\(\s*(?P<checks>[a-z-]+(?:\s*,\s*[a-z-]+)*)\s*\)\s*"
    r"(?::\s*(?P<reason>.*))?$"
)


@dataclass
class Finding:
    check: str
    path: str
    line: int
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}: [{self.check}] {self.message}"

    def to_dict(self) -> dict:
        return {
            "check": self.check,
            "path": self.path,
            "line": self.line,
            "message": self.message,
        }


@dataclass
class LintConfig:
    """Knobs for testability: the seeded-violation fixtures inject tiny
    registries instead of the real ones, and disable tree mode."""

    #: declared fault points (None = parse ``tpubloom/faults``)
    known_fault_points: Optional[frozenset] = None
    #: declared metric names (None = parse ``tpubloom/obs/names.py``)
    counters: Optional[frozenset] = None
    gauges: Optional[frozenset] = None
    #: run the cross-file tree checks (protocol coverage + reverse
    #: registry checks) against ``repo_root``
    tree_checks: bool = True
    repo_root: Optional[str] = None
    #: check names to skip entirely
    disable: frozenset = field(default_factory=frozenset)


def _repo_root() -> str:
    return os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )


def _dotted(node: ast.AST) -> str:
    """Best-effort dotted rendering of a call target ('self.mf.lock')."""
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover - unparse is total on 3.9+
        return "?"


# -- suppression handling -----------------------------------------------------


class _Suppressions:
    """Inline ``# lint: allow(check): reason`` comments for one file.
    Parsed from real COMMENT tokens (``tokenize``), so a docstring that
    merely *shows* the syntax is not a suppression."""

    def __init__(self, path: str, source: str, findings: list):
        import io
        import tokenize

        #: line -> {check -> reason}
        self.by_line: dict = {}
        self.used: set = set()
        comments = []
        try:
            for tok in tokenize.generate_tokens(io.StringIO(source).readline):
                if tok.type == tokenize.COMMENT:
                    comments.append((tok.start[0], tok.string))
        except tokenize.TokenizeError:  # pragma: no cover - parse already ran
            pass
        for lineno, text in comments:
            m = SUPPRESS_RE.search(text)
            if not m:
                continue
            checks = [c.strip() for c in m.group("checks").split(",")]
            reason = (m.group("reason") or "").strip()
            for check in checks:
                if check not in CHECKS:
                    findings.append(
                        Finding(
                            "unknown-suppression", path, lineno,
                            f"allow({check}) names no known check "
                            f"(known: {', '.join(CHECKS)})",
                        )
                    )
                    continue
                if not reason:
                    findings.append(
                        Finding(
                            "suppression-reason", path, lineno,
                            f"allow({check}) carries no reason — every "
                            f"suppression must say why it is safe",
                        )
                    )
                    continue
                self.by_line.setdefault(lineno, {})[check] = reason

    def matches(self, check: str, *lines: int) -> bool:
        for line in lines:
            if check in self.by_line.get(line, {}):
                self.used.add((line, check))
                return True
        return False

    def unused(self, path: str) -> list:
        out = []
        for line, checks in sorted(self.by_line.items()):
            for check in checks:
                if (line, check) not in self.used:
                    out.append(
                        Finding(
                            "unused-suppression", path, line,
                            f"allow({check}) matches no finding on this "
                            f"line — remove it or fix the anchor",
                        )
                    )
        return out


# -- per-file checkers --------------------------------------------------------


def _is_lock_with_item(item: ast.withitem) -> Optional[str]:
    ctx = item.context_expr
    if isinstance(ctx, ast.Attribute) and ctx.attr in LOCK_ATTRS:
        return _dotted(ctx)
    return None


def _blocking_reason(call: ast.Call) -> Optional[str]:
    func = call.func
    if isinstance(func, ast.Attribute):
        attr = func.attr
        recv = _dotted(func.value)
        dotted = f"{recv}.{attr}"
        if dotted in BLOCKING_DOTTED:
            return f"{dotted}() blocks on IO/sleep"
        if attr in BLOCKING_METHOD_NAMES:
            return f"{dotted}() is a blocking call"
        low = recv.lower()
        if attr in ("wait", "wait_for") and (
            "cond" in low or low.endswith("condition")
        ):
            has_timeout = any(k.arg == "timeout" for k in call.keywords)
            n_args = len(call.args)
            bounded = has_timeout or (
                n_args >= (2 if attr == "wait_for" else 1)
            )
            if not bounded:
                return f"{dotted}() waits without a timeout"
            return None  # a bounded wait on the cond's own lock is fine
        if attr == "close" and "checkpointer" in low:
            return f"{dotted}() flushes + joins the checkpoint worker"
        if attr == "restore" and ("ckpt" in low or "checkpoint" in low):
            return f"{dotted}() reads checkpoint blobs from the sink"
        if attr == "join" and any(
            t in low for t in ("thread", "worker", "proc")
        ):
            return f"{dotted}() joins a thread"
    elif isinstance(func, ast.Name) and func.id in ("fsync", "sleep"):
        return f"{func.id}() blocks on IO/sleep"
    return None


class _FileVisitor(ast.NodeVisitor):
    """Single pass per file: lock-region blocking calls, notify-vs-append
    ordering, and literal fault/metric usage collection."""

    def __init__(self, path: str, config: LintConfig):
        self.path = path
        self.config = config
        self.findings: list = []
        #: stack of (lock_expr, with_lineno) for enclosing lock withs
        self._locks: list = []
        #: per-function ordering state stack
        self._funcs: list = []
        #: (name, kind, line) literal metric emissions
        self.metric_uses: list = []
        #: (point, line) literal fault-point usages
        self.fault_uses: list = []
        #: every string constant in the file (reverse fault check)
        self.str_constants: set = set()

    # -- traversal ----------------------------------------------------------

    def visit_With(self, node: ast.With) -> None:
        pushed = 0
        for item in node.items:
            lock = _is_lock_with_item(item)
            if lock is not None:
                self._locks.append((lock, node.lineno))
                pushed += 1
        self.generic_visit(node)
        for _ in range(pushed):
            self._locks.pop()

    def _visit_func(self, node) -> None:
        self._funcs.append({"appends": [], "notifies": []})
        # a nested function does not inherit the enclosing lock region:
        # it runs when CALLED, not where it is defined
        saved, self._locks = self._locks, []
        self.generic_visit(node)
        self._locks = saved
        state = self._funcs.pop()
        first_append = min(state["appends"], default=None)
        for line in state["notifies"]:
            if first_append is not None and line < first_append:
                f = Finding(
                    "notify-before-append", self.path, line,
                    "notify_inserts before the op-log append: a "
                    "checkpoint triggered by this batch would stamp a "
                    "repl_seq that misses the batch's own record "
                    "(crash-replay double-apply)",
                )
                if not self._suppressed(f):
                    self.findings.append(f)

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def visit_Constant(self, node: ast.Constant) -> None:
        if isinstance(node.value, str):
            self.str_constants.add(node.value)

    def visit_Call(self, node: ast.Call) -> None:
        self._check_blocking(node)
        self._collect_ordering(node)
        self._collect_fault_use(node)
        self._collect_metric_use(node)
        self.generic_visit(node)

    # -- checks -------------------------------------------------------------

    def _suppressed(self, finding: Finding, extra_lines: Iterable[int] = ()) -> bool:
        # resolved later, once the suppression table exists — buffer the
        # candidate lines on the finding
        finding._lines = (finding.line, *extra_lines)  # type: ignore[attr-defined]
        return False

    def _check_blocking(self, node: ast.Call) -> None:
        if not self._locks:
            return
        reason = _blocking_reason(node)
        if reason is None:
            return
        lock, with_line = self._locks[-1]
        f = Finding(
            "blocking-under-lock", self.path, node.lineno,
            f"{reason} while holding {lock!r} (with at line {with_line})",
        )
        self._suppressed(f, (with_line,))
        self.findings.append(f)

    def _collect_ordering(self, node: ast.Call) -> None:
        if not self._funcs or not isinstance(node.func, ast.Attribute):
            return
        attr = node.func.attr
        recv = _dotted(node.func.value).lower()
        state = self._funcs[-1]
        if attr in ("_log_op", "_log_create") or (
            attr in ("append", "append_record") and "log" in recv
        ):
            state["appends"].append(node.lineno)
        elif attr == "notify_inserts":
            state["notifies"].append(node.lineno)

    def _collect_fault_use(self, node: ast.Call) -> None:
        if not isinstance(node.func, ast.Attribute):
            return
        if node.func.attr not in ("fire", "arm", "is_armed"):
            return
        recv = _dotted(node.func.value)
        if "faults" not in recv:
            return
        if node.args and isinstance(node.args[0], ast.Constant) and isinstance(
            node.args[0].value, str
        ):
            self.fault_uses.append((node.args[0].value, node.lineno))

    def _collect_metric_use(self, node: ast.Call) -> None:
        if not isinstance(node.func, ast.Attribute):
            return
        attr = node.func.attr
        if attr not in ("incr", "count", "set_gauge"):
            return
        recv = _dotted(node.func.value).lower()
        if attr == "incr" and "counter" not in recv:
            return
        if attr == "count" and "metrics" not in recv:
            return
        if not (
            node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            return  # dynamic name: declared via DYNAMIC_PREFIXES instead
        kind = "gauge" if attr == "set_gauge" else "counter"
        self.metric_uses.append((node.args[0].value, kind, node.lineno))


def _apply_registry_checks(
    visitor: _FileVisitor, config: LintConfig
) -> None:
    """Turn collected fault/metric literal uses into findings against
    the declared registries."""
    if config.known_fault_points is not None:
        known = config.known_fault_points
        for point, line in visitor.fault_uses:
            if point not in known:
                f = Finding(
                    "fault-registry", visitor.path, line,
                    f"fault point {point!r} is not declared in "
                    f"faults.KNOWN_POINTS — a typo'd chaos config would "
                    f"silently inject nothing",
                )
                f._lines = (line,)  # type: ignore[attr-defined]
                visitor.findings.append(f)
    if config.counters is not None and config.gauges is not None:
        for name, kind, line in visitor.metric_uses:
            declared = config.counters if kind == "counter" else config.gauges
            other = config.gauges if kind == "counter" else config.counters
            if name in declared:
                continue
            if name in other:
                msg = (
                    f"metric {name!r} is emitted as a {kind} but declared "
                    f"as the other kind in tpubloom.obs.names"
                )
            else:
                msg = (
                    f"metric {name!r} is not declared in tpubloom.obs.names "
                    f"— every counter/gauge name is registered exactly once"
                )
            f = Finding("metric-registry", visitor.path, line, msg)
            f._lines = (line,)  # type: ignore[attr-defined]
            visitor.findings.append(f)


def lint_file(path: str, config: LintConfig) -> tuple:
    """Lint one file; returns (findings, visitor) — the visitor carries
    the literal collections the tree checks aggregate."""
    with open(path, "r", encoding="utf-8") as f:
        source = f.read()
    findings: list = []
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        findings.append(
            Finding("blocking-under-lock", path, e.lineno or 0,
                    f"file does not parse: {e.msg}")
        )
        return findings, None
    visitor = _FileVisitor(path, config)
    visitor.visit(tree)
    _apply_registry_checks(visitor, config)
    sup = _Suppressions(path, source, findings)
    for f in visitor.findings:
        lines = getattr(f, "_lines", (f.line,))
        if f.check in config.disable:
            continue
        if not sup.matches(f.check, *lines):
            findings.append(f)
    findings.extend(sup.unused(path))
    return [f for f in findings if f.check not in config.disable], visitor


# -- registry parsing (AST, no heavyweight imports) ---------------------------


def _parse_string_collection(path: str, target_names: Iterable[str]) -> dict:
    """``{name: [literals...]}`` for module-level assignments of string
    tuples/sets/lists named in ``target_names`` (duplicates preserved)."""
    out: dict = {}
    with open(path, "r", encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    wanted = set(target_names)
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        for t in node.targets:
            if isinstance(t, ast.Name) and t.id in wanted and isinstance(
                node.value, (ast.Tuple, ast.Set, ast.List)
            ):
                out[t.id] = [
                    e.value
                    for e in node.value.elts
                    if isinstance(e, ast.Constant) and isinstance(e.value, str)
                ]
    return out


def load_fault_points(repo_root: str) -> frozenset:
    path = os.path.join(repo_root, "tpubloom", "faults", "__init__.py")
    return frozenset(
        _parse_string_collection(path, ("KNOWN_POINTS",)).get(
            "KNOWN_POINTS", ()
        )
    )


def load_metric_names(repo_root: str) -> tuple:
    """(counters, gauges, duplicate-findings) from obs/names.py."""
    path = os.path.join(repo_root, "tpubloom", "obs", "names.py")
    decls = _parse_string_collection(path, ("COUNTERS", "GAUGES"))
    counters = decls.get("COUNTERS", [])
    gauges = decls.get("GAUGES", [])
    findings = []
    for kind, names in (("COUNTERS", counters), ("GAUGES", gauges)):
        seen: set = set()
        for n in names:
            if n in seen:
                findings.append(
                    Finding(
                        "metric-registry", path, 0,
                        f"{n!r} is declared twice in {kind} — registered "
                        f"exactly once means once",
                    )
                )
            seen.add(n)
    for n in sorted(set(counters) & set(gauges)):
        findings.append(
            Finding(
                "metric-registry", path, 0,
                f"{n!r} is declared as both a counter and a gauge",
            )
        )
    return frozenset(counters), frozenset(gauges), findings


# -- tree checks --------------------------------------------------------------


def _literal_set(path: str) -> set:
    """Every string constant in a file (cheap containment probe)."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=path)
    except (OSError, SyntaxError):
        return set()
    return {
        n.value
        for n in ast.walk(tree)
        if isinstance(n, ast.Constant) and isinstance(n.value, str)
    }


def _service_handlers(path: str) -> tuple:
    """(method defs on BloomService, keys of the stream behavior maps)."""
    with open(path, "r", encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    handlers: set = set()
    behaviors: set = set()
    for node in tree.body:
        if isinstance(node, ast.ClassDef) and node.name == "BloomService":
            handlers = {
                n.name
                for n in node.body
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            }
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Dict):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id in (
                    "_STREAM_BEHAVIORS", "_CLIENT_STREAM_BEHAVIORS"
                ):
                    behaviors |= {
                        k.value
                        for k in node.value.keys
                        if isinstance(k, ast.Constant)
                    }
    return handlers, behaviors


def check_protocol_coverage(repo_root: str) -> list:
    """Every METHODS entry: handler + client call + golden test; every
    streaming method: behavior registration + golden test."""
    proto_path = os.path.join(repo_root, "tpubloom", "server", "protocol.py")
    decls = _parse_string_collection(
        proto_path, ("METHODS", "STREAM_METHODS", "CLIENT_STREAM_METHODS")
    )
    service_path = os.path.join(repo_root, "tpubloom", "server", "service.py")
    client_path = os.path.join(repo_root, "tpubloom", "server", "client.py")
    golden_path = os.path.join(repo_root, "tests", "test_protocol_golden.py")
    handlers, behaviors = _service_handlers(service_path)
    client_lits = _literal_set(client_path)
    golden_lits = _literal_set(golden_path)
    findings = []

    def miss(method: str, what: str) -> None:
        findings.append(
            Finding(
                "protocol-coverage", proto_path, 0,
                f"protocol method {method!r} has no {what}",
            )
        )

    for m in decls.get("METHODS", ()):
        if m not in handlers:
            miss(m, "BloomService handler (def in service.py)")
        if m not in client_lits:
            miss(m, "client call site (literal in client.py)")
        if m not in golden_lits:
            miss(m, "golden wire test (literal in test_protocol_golden.py)")
    for m in list(decls.get("STREAM_METHODS", ())) + list(
        decls.get("CLIENT_STREAM_METHODS", ())
    ):
        if m not in behaviors:
            miss(m, "service behavior registration (_*_BEHAVIORS map)")
        if m not in golden_lits:
            miss(m, "golden wire test (literal in test_protocol_golden.py)")
    return findings


#: where the Ruby drivers live, relative to the repo root.
RUBY_DRIVER_DIR = os.path.join(
    "clients", "ruby", "lib", "redis-bloomfilter", "driver"
)

_RUBY_METHODS_RE = re.compile(r"METHODS\s*=\s*%w\[([^\]]*)\]")


def check_ruby_parity(repo_root: str) -> list:
    """Every ``protocol.METHODS`` entry covered by the Ruby drivers
    (ISSUE 12 satellite, ROADMAP item 6): a quoted call-site literal in
    the union of the driver files, plus registry/protocol set equality
    for the base driver's ``METHODS`` constant — so protocol growth
    that forgets the Ruby side fails CI the same way a missing Python
    handler does."""
    proto_path = os.path.join(repo_root, "tpubloom", "server", "protocol.py")
    decls = _parse_string_collection(proto_path, ("METHODS",))
    methods = list(decls.get("METHODS", ()))
    driver_dir = os.path.join(repo_root, RUBY_DRIVER_DIR)
    findings: list = []
    if not methods or not os.path.isdir(driver_dir):
        return findings
    sources: dict[str, str] = {}
    for fn in sorted(os.listdir(driver_dir)):
        if fn.endswith(".rb"):
            path = os.path.join(driver_dir, fn)
            try:
                with open(path, "r", encoding="utf-8") as f:
                    sources[path] = f.read()
            except OSError:
                continue
    if not sources:
        return findings
    all_src = "\n".join(sources.values())
    # only the BASE driver carries the METHODS registry constant the
    # equality check applies to (the cluster driver subclasses it)
    base_path = os.path.join(driver_dir, "jax.rb")
    base_registry = {
        m
        for block in _RUBY_METHODS_RE.findall(sources.get(base_path, ""))
        for m in block.split()
    }
    for m in methods:
        if f'"{m}"' not in all_src and f"'{m}'" not in all_src:
            findings.append(Finding(
                "ruby-parity", base_path, 0,
                f"protocol method {m!r} has no call site in any Ruby "
                f"driver (clients/ruby)",
            ))
        if base_registry and m not in base_registry:
            findings.append(Finding(
                "ruby-parity", base_path, 0,
                f"protocol method {m!r} missing from the Ruby driver's "
                f"METHODS registry",
            ))
    for extra in sorted(base_registry - set(methods)):
        findings.append(Finding(
            "ruby-parity", base_path, 0,
            f"Ruby METHODS registry lists {extra!r}, which is not a "
            f"protocol method — stale registry entry",
        ))
    return findings


def iter_py_files(paths: Iterable[str]) -> list:
    out = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = [d for d in dirs if d != "__pycache__"]
            out.extend(
                os.path.join(root, fn) for fn in sorted(files)
                if fn.endswith(".py")
            )
    return out


def lint_paths(paths: Iterable[str], config: Optional[LintConfig] = None) -> list:
    config = config or LintConfig()
    repo_root = config.repo_root or _repo_root()
    findings: list = []
    if config.known_fault_points is None:
        config.known_fault_points = load_fault_points(repo_root)
    if config.counters is None or config.gauges is None:
        counters, gauges, dup_findings = load_metric_names(repo_root)
        config.counters = counters
        config.gauges = gauges
        if config.tree_checks:
            findings.extend(dup_findings)

    fault_literal_seen: set = set()
    metric_literal_seen: set = set()
    fault_registry_path = os.path.join(
        repo_root, "tpubloom", "faults", "__init__.py"
    )
    names_path = os.path.join(repo_root, "tpubloom", "obs", "names.py")
    for path in iter_py_files(paths):
        file_findings, visitor = lint_file(path, config)
        findings.extend(file_findings)
        if visitor is None:
            continue
        if os.path.abspath(path) != os.path.abspath(fault_registry_path):
            fault_literal_seen |= visitor.str_constants
        if os.path.abspath(path) != os.path.abspath(names_path):
            metric_literal_seen |= {n for n, _, _ in visitor.metric_uses}

    if config.tree_checks:
        findings.extend(check_protocol_coverage(repo_root))
        findings.extend(check_ruby_parity(repo_root))
        for point in sorted(config.known_fault_points - fault_literal_seen):
            findings.append(
                Finding(
                    "fault-registry", fault_registry_path, 0,
                    f"declared fault point {point!r} is never referenced "
                    f"outside the registry — dead vocabulary",
                )
            )
        for name in sorted(
            (config.counters | config.gauges) - metric_literal_seen
        ):
            findings.append(
                Finding(
                    "metric-registry", names_path, 0,
                    f"declared metric {name!r} is never emitted in the "
                    f"linted tree — stale catalog entry",
                )
            )
    return [f for f in findings if f.check not in config.disable]


def main(argv: Optional[list] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tpubloom.analysis.lint",
        description="tpubloom project lint: concurrency + registry invariants",
    )
    parser.add_argument(
        "paths", nargs="*", default=None,
        help="files/directories to lint (default: the tpubloom package)",
    )
    parser.add_argument(
        "--no-tree-checks", action="store_true",
        help="skip the cross-file checks (protocol coverage, reverse "
        "registry checks)",
    )
    parser.add_argument("--json", action="store_true", dest="as_json")
    args = parser.parse_args(argv)
    repo_root = _repo_root()
    paths = args.paths or [os.path.join(repo_root, "tpubloom")]
    # expand once: iter_py_files passes plain files through, so the
    # resolved list is also a valid `paths` for lint_paths
    files = iter_py_files(paths)
    config = LintConfig(tree_checks=not args.no_tree_checks)
    findings = lint_paths(files, config)
    if args.as_json:
        print(json.dumps([f.to_dict() for f in findings], indent=2))
    else:
        for f in findings:
            print(f.format())
        print(
            f"tpubloom.analysis.lint: {len(findings)} finding(s) in "
            f"{len(files)} file(s)"
        )
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
