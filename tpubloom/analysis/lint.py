"""Static concurrency/consistency lint for the tpubloom tree (ISSUE 6).

``python -m tpubloom.analysis.lint [paths...]`` (default: the installed
``tpubloom/`` package) runs AST-based checkers that encode the
project-specific invariants hand-review kept re-finding while PRs 3-5
grew the replication stack. Zero dependencies beyond the stdlib; exit
status 0 = clean, 1 = findings.

Checks
======

``blocking-under-lock``
    No blocking call — gRPC stubs (``_rpc``/``_call``/``_node``/
    ``_peer``/``grpc.insecure_channel``), ``Condition.wait`` without a
    timeout, fsync/flush/checkpoint IO (``os.fsync``, ``.flush()``,
    ``ckpt.restore``/``_tracked_restore``, ``checkpointer.close``),
    ``time.sleep``, thread/worker ``join``, ``Future.result`` —
    lexically inside a ``with`` on a registry/filter/admission mutex
    or a lock-named condition (attributes like ``lock``, ``_lock``,
    ``_cond``, ``_admit_lock`` ...). Quorum waits moved to their own
    ``barrier-outside-lock`` check in ISSUE 13. The runtime half of
    this check is :func:`tpubloom.utils.locks.note_blocking`.

``notify-before-append``
    In any function that both appends to the op log (``_log_op`` /
    ``_log_create`` / ``oplog.append``) and calls
    ``checkpointer.notify_inserts``, every notify must come AFTER the
    first append: a checkpoint triggered by its own batch must stamp
    that batch's seq (the PR-3 crash-replay bug class).

``fault-registry``
    Every literal fault-point string passed to ``faults.fire`` /
    ``arm`` / ``is_armed`` is declared in ``faults.KNOWN_POINTS`` —
    and (tree mode) every declared point appears as a literal somewhere
    outside the registry, so the vocabulary cannot rot.

``metric-registry``
    Every literal counter/gauge name emitted via ``counters.incr`` /
    ``metrics.count`` / ``counters.set_gauge`` is declared in
    :mod:`tpubloom.obs.names` under the right kind; (tree mode) every
    declared name is emitted at least once, and no name is declared
    twice or under both kinds.

``protocol-coverage``
    (tree mode) Every ``protocol.METHODS`` entry has a ``BloomService``
    handler, a client call site, and a golden-wire test; streaming
    methods are registered in the service behavior maps and golden-
    tested.

``ruby-parity``
    (tree mode) The Ruby drivers track the protocol too (ROADMAP item
    6 — two Ruby drivers now): every ``protocol.METHODS`` entry appears
    as a quoted call-site literal somewhere in ``clients/ruby``'s
    driver files, the base driver's ``METHODS`` registry constant
    matches the protocol list exactly (no drift in either direction),
    and the registry lists nothing the protocol dropped.

``donation-safety``
    A name passed at a donated position of a donating call — a callable
    built by ``jax.jit(..., donate_argnums=...)`` or ``pl.pallas_call(
    ..., input_output_aliases=...)`` — must not be referenced after the
    call in the same function unless it was rebound first: donation
    deletes the buffer on device, so a later use raises (best case) or
    reads freed memory through a stale handle (the PR-10 ``InFlight``
    fence bug class, found live when a later donating kernel deleted
    the fenced handle).

``replay-safety``
    (tree mode) Every ``protocol.MUTATING_METHODS`` handler on
    ``BloomService`` must touch the rid→response dedup cache
    (``_dedup_get``/``_dedup_put``) — a mutating response that is not
    replay-cached turns a client retry into a second apply (the
    PR-9/10 double-apply class: counting filters double-increment,
    presence replays report the batch's own keys). Handlers whose
    replay provably converges carry a reasoned suppression on the
    ``def`` line instead.

``barrier-outside-lock``
    ``commit_barrier`` / ``wait_acked`` lexically under a registry/
    filter/admission lock ``with``. The PR-5 invariant, previously
    prose: the commit barrier runs in the RPC wrapper AFTER the
    handler, outside every lock — a quorum wait under the filter lock
    would stall every other writer (and the ack path it waits on) for
    the full barrier budget.

``chaos-coverage``
    (tree mode) Every ``faults.KNOWN_POINTS`` entry is ARMED by literal
    in at least one test or benchmark harness — via ``faults.arm(
    "point", ...)`` or a ``TPUBLOOM_FAULTS``-syntax string
    (``"point=policy"``) in ``tests/`` or ``benchmarks/`` (ISSUE 15
    closed the ROADMAP item 6 seam: arming that lives in a load
    harness rather than pytest counts). A declared-but-never-armed
    point is dead chaos surface: the failure path it guards has never
    actually been driven. Suppress (with a reason) on the point's
    ``KNOWN_POINTS`` line.

``phase-registry``
    Every literal phase name passed to ``obs.phase(...)`` /
    ``ctx.add_phase(...)`` is declared in
    :data:`tpubloom.obs.names.PHASES`; dynamic (f-string) phase names
    must start with a declared :data:`tpubloom.obs.names.
    PHASE_DYNAMIC_PREFIXES` prefix (``kernel_shard<i>``); (tree mode)
    every declared phase/prefix is emitted somewhere — the PR-6
    counter-registry pattern extended to the phase vocabulary so
    dashboards and the slowlog keep lining up.

``trace-registry``
    (ISSUE 15) The same closure for the distributed-tracing span
    vocabulary and the flight-recorder event vocabulary: every literal
    name at a ``trace.span(...)`` / ``trace.record_span(...)`` site is
    declared in :data:`tpubloom.obs.names.SPANS` (f-string heads must
    match :data:`tpubloom.obs.names.SPAN_DYNAMIC_PREFIXES` —
    ``rpc.<Method>``, ``phase.<name>``), every ``flight.note(...)``
    kind is declared in :data:`tpubloom.obs.names.EVENTS`, and (tree
    mode) every declared span/prefix/event has an emit site — a
    TraceGet tree and a flight dump must never contain a name the
    catalog cannot explain, and the catalog cannot rot.

Suppressions
============

A finding is allowlisted inline, on the flagged line or its enclosing
``with`` line::

    mf.checkpointer.close()  # lint: allow(blocking-under-lock): unpublished

The reason is mandatory: an empty reason is itself a finding
(``suppression-reason``), as are suppressions naming unknown checks
(``unknown-suppression``) and suppressions that no longer match any
finding (``unused-suppression``) — allowlists cannot rot either.
"""

from __future__ import annotations

import argparse
import ast
import json
import os
import re
import sys
from dataclasses import dataclass, field
from typing import Iterable, Optional

CHECKS = (
    "blocking-under-lock",
    "notify-before-append",
    "fault-registry",
    "metric-registry",
    "protocol-coverage",
    "ruby-parity",
    "donation-safety",
    "replay-safety",
    "barrier-outside-lock",
    "chaos-coverage",
    "phase-registry",
    "trace-registry",
    "suppression-reason",
    "unknown-suppression",
    "unused-suppression",
)

#: ``with`` context attributes treated as "a lock is held inside".
LOCK_ATTRS = frozenset(
    {
        "lock",
        "_lock",
        "_cond",
        "_admit_lock",
        "_promote_lock",
        "_dedup_lock",
        "_trigger_lock",
        "_call_lock",
    }
)

#: Method names that are blocking wherever they appear.
BLOCKING_METHOD_NAMES = frozenset(
    {"_tracked_restore", "_rpc", "_node", "_peer", "result", "flush"}
)

#: Quorum-barrier calls: under a lock these get their own check
#: (``barrier-outside-lock`` — the PR-5 invariant, formerly prose and
#: formerly folded into blocking-under-lock): the commit barrier runs
#: in the RPC wrapper AFTER the handler, outside every lock.
BARRIER_METHOD_NAMES = frozenset({"wait_acked", "commit_barrier"})

#: Fully dotted calls that are blocking.
BLOCKING_DOTTED = frozenset(
    {
        "os.fsync",
        "time.sleep",
        "subprocess.run",
        "subprocess.check_call",
        "subprocess.check_output",
        "grpc.insecure_channel",
    }
)

SUPPRESS_RE = re.compile(
    r"#\s*lint:\s*allow\(\s*(?P<checks>[a-z-]+(?:\s*,\s*[a-z-]+)*)\s*\)\s*"
    r"(?::\s*(?P<reason>.*))?$"
)


@dataclass
class Finding:
    check: str
    path: str
    line: int
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}: [{self.check}] {self.message}"

    def to_dict(self) -> dict:
        return {
            "check": self.check,
            "path": self.path,
            "line": self.line,
            "message": self.message,
        }


@dataclass
class LintConfig:
    """Knobs for testability: the seeded-violation fixtures inject tiny
    registries instead of the real ones, and disable tree mode."""

    #: declared fault points (None = parse ``tpubloom/faults``)
    known_fault_points: Optional[frozenset] = None
    #: declared metric names (None = parse ``tpubloom/obs/names.py``)
    counters: Optional[frozenset] = None
    gauges: Optional[frozenset] = None
    #: declared phase vocabulary (None = parse ``tpubloom/obs/names.py``)
    phases: Optional[frozenset] = None
    phase_prefixes: Optional[tuple] = None
    #: declared span/event vocabularies (ISSUE 15 ``trace-registry``;
    #: None = parse ``tpubloom/obs/names.py``)
    spans: Optional[frozenset] = None
    span_prefixes: Optional[tuple] = None
    events: Optional[frozenset] = None
    #: run the cross-file tree checks (protocol coverage + reverse
    #: registry checks) against ``repo_root``
    tree_checks: bool = True
    repo_root: Optional[str] = None
    #: check names to skip entirely
    disable: frozenset = field(default_factory=frozenset)


def _repo_root() -> str:
    return os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )


def _dotted(node: ast.AST) -> str:
    """Best-effort dotted rendering of a call target ('self.mf.lock')."""
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover - unparse is total on 3.9+
        return "?"


# -- suppression handling -----------------------------------------------------


class _Suppressions:
    """Inline ``# lint: allow(check): reason`` comments for one file.
    Parsed from real COMMENT tokens (``tokenize``), so a docstring that
    merely *shows* the syntax is not a suppression."""

    def __init__(self, path: str, source: str, findings: list):
        import io
        import tokenize

        #: line -> {check -> reason}
        self.by_line: dict = {}
        self.used: set = set()
        comments = []
        try:
            for tok in tokenize.generate_tokens(io.StringIO(source).readline):
                if tok.type == tokenize.COMMENT:
                    comments.append((tok.start[0], tok.string))
        except tokenize.TokenizeError:  # pragma: no cover - parse already ran
            pass
        for lineno, text in comments:
            m = SUPPRESS_RE.search(text)
            if not m:
                continue
            checks = [c.strip() for c in m.group("checks").split(",")]
            reason = (m.group("reason") or "").strip()
            for check in checks:
                if check not in CHECKS:
                    findings.append(
                        Finding(
                            "unknown-suppression", path, lineno,
                            f"allow({check}) names no known check "
                            f"(known: {', '.join(CHECKS)})",
                        )
                    )
                    continue
                if not reason:
                    findings.append(
                        Finding(
                            "suppression-reason", path, lineno,
                            f"allow({check}) carries no reason — every "
                            f"suppression must say why it is safe",
                        )
                    )
                    continue
                self.by_line.setdefault(lineno, {})[check] = reason

    def matches(self, check: str, *lines: int) -> bool:
        for line in lines:
            if check in self.by_line.get(line, {}):
                self.used.add((line, check))
                return True
        return False

    def unused(self, path: str) -> list:
        out = []
        for line, checks in sorted(self.by_line.items()):
            for check in checks:
                if (line, check) not in self.used:
                    out.append(
                        Finding(
                            "unused-suppression", path, line,
                            f"allow({check}) matches no finding on this "
                            f"line — remove it or fix the anchor",
                        )
                    )
        return out


# -- per-file checkers --------------------------------------------------------


def _is_lock_with_item(item: ast.withitem) -> Optional[str]:
    ctx = item.context_expr
    if isinstance(ctx, ast.Attribute) and ctx.attr in LOCK_ATTRS:
        return _dotted(ctx)
    return None


def _barrier_name(call: ast.Call) -> Optional[str]:
    """Dotted rendering of a quorum-barrier call, else None."""
    func = call.func
    if isinstance(func, ast.Attribute) and func.attr in BARRIER_METHOD_NAMES:
        return f"{_dotted(func.value)}.{func.attr}"
    if isinstance(func, ast.Name) and func.id in BARRIER_METHOD_NAMES:
        return func.id
    return None


def _blocking_reason(call: ast.Call) -> Optional[str]:
    func = call.func
    if isinstance(func, ast.Attribute):
        attr = func.attr
        recv = _dotted(func.value)
        dotted = f"{recv}.{attr}"
        if dotted in BLOCKING_DOTTED:
            return f"{dotted}() blocks on IO/sleep"
        if attr in BLOCKING_METHOD_NAMES:
            return f"{dotted}() is a blocking call"
        low = recv.lower()
        if attr in ("wait", "wait_for") and (
            "cond" in low or low.endswith("condition")
        ):
            has_timeout = any(k.arg == "timeout" for k in call.keywords)
            n_args = len(call.args)
            bounded = has_timeout or (
                n_args >= (2 if attr == "wait_for" else 1)
            )
            if not bounded:
                return f"{dotted}() waits without a timeout"
            return None  # a bounded wait on the cond's own lock is fine
        if attr == "close" and "checkpointer" in low:
            return f"{dotted}() flushes + joins the checkpoint worker"
        if attr == "restore" and ("ckpt" in low or "checkpoint" in low):
            return f"{dotted}() reads checkpoint blobs from the sink"
        if attr == "join" and any(
            t in low for t in ("thread", "worker", "proc")
        ):
            return f"{dotted}() joins a thread"
    elif isinstance(func, ast.Name) and func.id in ("fsync", "sleep"):
        return f"{func.id}() blocks on IO/sleep"
    return None


class _FileVisitor(ast.NodeVisitor):
    """Single pass per file: lock-region blocking calls, notify-vs-append
    ordering, and literal fault/metric usage collection."""

    def __init__(self, path: str, config: LintConfig):
        self.path = path
        self.config = config
        self.findings: list = []
        #: stack of (lock_expr, with_lineno) for enclosing lock withs
        self._locks: list = []
        #: per-function ordering state stack
        self._funcs: list = []
        #: (name, kind, line) literal metric emissions
        self.metric_uses: list = []
        #: (point, line) literal fault-point usages
        self.fault_uses: list = []
        #: (name, line) literal phase emissions (obs.phase / add_phase)
        self.phase_uses: list = []
        #: (literal-prefix, line) dynamic (f-string) phase emissions
        self.phase_dynamic_uses: list = []
        #: (name, line) literal span emissions (trace.span /
        #: trace.record_span — incl. trace.py's own bare record_span
        #: calls) — ISSUE 15 ``trace-registry``
        self.span_uses: list = []
        #: (literal-prefix, line) dynamic (f-string) span emissions
        self.span_dynamic_uses: list = []
        #: (kind, line) literal flight-recorder events (flight.note)
        self.event_uses: list = []
        #: every string constant in the file (reverse fault check)
        self.str_constants: set = set()

    # -- traversal ----------------------------------------------------------

    def visit_With(self, node: ast.With) -> None:
        pushed = 0
        for item in node.items:
            lock = _is_lock_with_item(item)
            if lock is not None:
                self._locks.append((lock, node.lineno))
                pushed += 1
        self.generic_visit(node)
        for _ in range(pushed):
            self._locks.pop()

    def _visit_func(self, node) -> None:
        self._funcs.append({"appends": [], "notifies": []})
        # a nested function does not inherit the enclosing lock region:
        # it runs when CALLED, not where it is defined
        saved, self._locks = self._locks, []
        self.generic_visit(node)
        self._locks = saved
        state = self._funcs.pop()
        first_append = min(state["appends"], default=None)
        for line in state["notifies"]:
            if first_append is not None and line < first_append:
                f = Finding(
                    "notify-before-append", self.path, line,
                    "notify_inserts before the op-log append: a "
                    "checkpoint triggered by this batch would stamp a "
                    "repl_seq that misses the batch's own record "
                    "(crash-replay double-apply)",
                )
                if not self._suppressed(f):
                    self.findings.append(f)

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def visit_Constant(self, node: ast.Constant) -> None:
        if isinstance(node.value, str):
            self.str_constants.add(node.value)

    def visit_Call(self, node: ast.Call) -> None:
        self._check_blocking(node)
        self._collect_ordering(node)
        self._collect_fault_use(node)
        self._collect_metric_use(node)
        self._collect_phase_use(node)
        self._collect_trace_use(node)
        self.generic_visit(node)

    # -- checks -------------------------------------------------------------

    def _suppressed(self, finding: Finding, extra_lines: Iterable[int] = ()) -> bool:
        # resolved later, once the suppression table exists — buffer the
        # candidate lines on the finding
        finding._lines = (finding.line, *extra_lines)  # type: ignore[attr-defined]
        return False

    def _check_blocking(self, node: ast.Call) -> None:
        if not self._locks:
            return
        lock, with_line = self._locks[-1]
        barrier = _barrier_name(node)
        if barrier is not None:
            f = Finding(
                "barrier-outside-lock", self.path, node.lineno,
                f"{barrier}() runs a quorum barrier while holding "
                f"{lock!r} (with at line {with_line}) — the PR-5 "
                f"invariant: commit barriers run in the RPC wrapper "
                f"AFTER the handler, outside every lock, or one slow "
                f"quorum stalls every other writer on this filter",
            )
            self._suppressed(f, (with_line,))
            self.findings.append(f)
            return
        reason = _blocking_reason(node)
        if reason is None:
            return
        f = Finding(
            "blocking-under-lock", self.path, node.lineno,
            f"{reason} while holding {lock!r} (with at line {with_line})",
        )
        self._suppressed(f, (with_line,))
        self.findings.append(f)

    def _collect_ordering(self, node: ast.Call) -> None:
        if not self._funcs or not isinstance(node.func, ast.Attribute):
            return
        attr = node.func.attr
        recv = _dotted(node.func.value).lower()
        state = self._funcs[-1]
        if attr in ("_log_op", "_log_create") or (
            attr in ("append", "append_record") and "log" in recv
        ):
            state["appends"].append(node.lineno)
        elif attr == "notify_inserts":
            state["notifies"].append(node.lineno)

    def _collect_fault_use(self, node: ast.Call) -> None:
        if not isinstance(node.func, ast.Attribute):
            return
        if node.func.attr not in ("fire", "arm", "is_armed"):
            return
        recv = _dotted(node.func.value)
        if "faults" not in recv:
            return
        if node.args and isinstance(node.args[0], ast.Constant) and isinstance(
            node.args[0].value, str
        ):
            self.fault_uses.append((node.args[0].value, node.lineno))

    def _collect_metric_use(self, node: ast.Call) -> None:
        if not isinstance(node.func, ast.Attribute):
            return
        attr = node.func.attr
        if attr not in ("incr", "count", "set_gauge"):
            return
        recv = _dotted(node.func.value).lower()
        if attr == "incr" and "counter" not in recv:
            return
        if attr == "count" and "metrics" not in recv:
            return
        if not (
            node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            return  # dynamic name: declared via DYNAMIC_PREFIXES instead
        kind = "gauge" if attr == "set_gauge" else "counter"
        self.metric_uses.append((node.args[0].value, kind, node.lineno))

    def _collect_phase_use(self, node: ast.Call) -> None:
        """Literal/dynamic phase names at ``obs.phase(...)`` /
        ``ctx.add_phase(...)`` sites (ISSUE 13 ``phase-registry``)."""
        if not isinstance(node.func, ast.Attribute):
            return
        if node.func.attr not in ("phase", "add_phase") or not node.args:
            return
        arg = node.args[0]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            self.phase_uses.append((arg.value, node.lineno))
        elif isinstance(arg, ast.JoinedStr):
            head = ""
            if arg.values and isinstance(arg.values[0], ast.Constant):
                head = str(arg.values[0].value)
            self.phase_dynamic_uses.append((head, node.lineno))

    def _collect_trace_use(self, node: ast.Call) -> None:
        """Literal/dynamic span names at ``trace.span(...)`` /
        ``trace.record_span(...)`` sites and event kinds at
        ``flight.note(...)`` sites (ISSUE 15 ``trace-registry``). The
        trace module's own internal minting calls ``record_span`` as a
        bare name — accepted too (the name is distinctive), so the
        ``rpc.``/``phase.`` prefixes have visible emit sites."""
        func = node.func
        if isinstance(func, ast.Attribute):
            attr = func.attr
            recv = _dotted(func.value).lower()
        elif isinstance(func, ast.Name):
            attr = func.id
            recv = None
        else:
            return
        if not node.args:
            return
        arg = node.args[0]
        if attr in ("span", "record_span"):
            if recv is not None and "trace" not in recv:
                return
            if recv is None and attr != "record_span":
                return  # a bare span() is too generic to claim
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                self.span_uses.append((arg.value, node.lineno))
            elif isinstance(arg, ast.JoinedStr):
                head = ""
                if arg.values and isinstance(arg.values[0], ast.Constant):
                    head = str(arg.values[0].value)
                self.span_dynamic_uses.append((head, node.lineno))
        elif attr == "note":
            if recv is None or "flight" not in recv:
                return
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                self.event_uses.append((arg.value, node.lineno))


# -- donation safety (ISSUE 13) ----------------------------------------------


def _donated_indices(call: ast.Call) -> tuple:
    """Donated positional-arg indices declared on a ``jax.jit(...,
    donate_argnums=...)`` / ``pl.pallas_call(..., input_output_aliases=
    {in_idx: out_idx, ...})`` construction, else ``()``."""
    for kw in call.keywords:
        if kw.arg == "input_output_aliases" and isinstance(kw.value, ast.Dict):
            return tuple(
                k.value
                for k in kw.value.keys
                if isinstance(k, ast.Constant) and isinstance(k.value, int)
            )
        if kw.arg == "donate_argnums":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return (v.value,)
            if isinstance(v, (ast.Tuple, ast.List)):
                return tuple(
                    e.value
                    for e in v.elts
                    if isinstance(e, ast.Constant) and isinstance(e.value, int)
                )
    return ()


def _collect_donating_callees(tree: ast.AST) -> dict:
    """``{dotted-callee: (donated indices,)}`` for every assignment in
    the file whose value is a donating construction — ``fn = pl.
    pallas_call(..., input_output_aliases=...)`` in a kernel builder,
    ``self._insert = jax.jit(..., donate_argnums=0)`` in a filter class.
    Keyed on the rendered target (``fn``, ``self._insert``) so calls
    through the same spelling anywhere in the file resolve."""
    out: dict = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign) or not isinstance(
            node.value, ast.Call
        ):
            continue
        idxs = _donated_indices(node.value)
        if not idxs:
            continue
        for t in node.targets:
            if isinstance(t, (ast.Name, ast.Attribute)):
                out[_dotted(t)] = idxs
    return out


def _binding_lines(func: ast.AST, expr: str) -> list:
    """Line numbers where ``expr`` (a dotted name) is (re)bound inside
    ``func`` — assignment targets incl. tuple unpacking, aug-assign,
    for-loop targets, ``with ... as`` — i.e. the points after which a
    previously donated buffer name holds a FRESH value again."""
    lines = []

    def targets_of(node):
        if isinstance(node, ast.Assign):
            return node.targets
        if isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            return [node.target]
        if isinstance(node, ast.For):
            return [node.target]
        if isinstance(node, ast.withitem) and node.optional_vars is not None:
            return [node.optional_vars]
        return []

    for node in ast.walk(func):
        for t in targets_of(node):
            for sub in ast.walk(t):
                if isinstance(sub, (ast.Name, ast.Attribute)) and (
                    _dotted(sub) == expr
                ):
                    lines.append(node.lineno)
    return lines


def check_donation_safety(tree: ast.AST, path: str) -> list:
    """Use-after-donate: a name passed at a donated position and read
    again later in the same function without a rebind in between. The
    donated device buffer is DELETED by the call — the PR-10 bug class
    where a later donating kernel consumed the handle an in-flight
    fence still held."""
    donating = _collect_donating_callees(tree)
    if not donating:
        return []
    findings: list = []
    funcs = [
        n
        for n in ast.walk(tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]
    for func in funcs:
        for call in ast.walk(func):
            if not isinstance(call, ast.Call):
                continue
            callee = _dotted(call.func)
            idxs = donating.get(callee)
            if not idxs:
                continue
            call_end = call.end_lineno or call.lineno
            for i in idxs:
                if i >= len(call.args):
                    continue
                arg = call.args[i]
                if not isinstance(arg, (ast.Name, ast.Attribute)):
                    continue
                expr = _dotted(arg)
                rebinds = _binding_lines(func, expr)
                for node in ast.walk(func):
                    if not isinstance(node, (ast.Name, ast.Attribute)):
                        continue
                    if not isinstance(getattr(node, "ctx", None), ast.Load):
                        continue
                    if node.lineno <= call_end or _dotted(node) != expr:
                        continue
                    if any(
                        call.lineno <= rb <= node.lineno for rb in rebinds
                    ):
                        continue
                    f = Finding(
                        "donation-safety", path, node.lineno,
                        f"{expr!r} was donated to {callee}() at line "
                        f"{call.lineno} (donated arg {i}) and is read "
                        f"again here without a rebind — donation deletes "
                        f"the device buffer, so this read raises or "
                        f"races freed memory (the PR-10 InFlight fence "
                        f"class)",
                    )
                    f._lines = (node.lineno, call.lineno)  # type: ignore[attr-defined]
                    findings.append(f)
                    break  # one finding per donated arg per call
    return findings


def _apply_registry_checks(
    visitor: _FileVisitor, config: LintConfig
) -> None:
    """Turn collected fault/metric literal uses into findings against
    the declared registries."""
    if config.known_fault_points is not None:
        known = config.known_fault_points
        for point, line in visitor.fault_uses:
            if point not in known:
                f = Finding(
                    "fault-registry", visitor.path, line,
                    f"fault point {point!r} is not declared in "
                    f"faults.KNOWN_POINTS — a typo'd chaos config would "
                    f"silently inject nothing",
                )
                f._lines = (line,)  # type: ignore[attr-defined]
                visitor.findings.append(f)
    if config.counters is not None and config.gauges is not None:
        for name, kind, line in visitor.metric_uses:
            declared = config.counters if kind == "counter" else config.gauges
            other = config.gauges if kind == "counter" else config.counters
            if name in declared:
                continue
            if name in other:
                msg = (
                    f"metric {name!r} is emitted as a {kind} but declared "
                    f"as the other kind in tpubloom.obs.names"
                )
            else:
                msg = (
                    f"metric {name!r} is not declared in tpubloom.obs.names "
                    f"— every counter/gauge name is registered exactly once"
                )
            f = Finding("metric-registry", visitor.path, line, msg)
            f._lines = (line,)  # type: ignore[attr-defined]
            visitor.findings.append(f)
    if config.phases is not None:
        prefixes = tuple(config.phase_prefixes or ())
        for name, line in visitor.phase_uses:
            if name in config.phases or any(
                name.startswith(p) for p in prefixes
            ):
                continue
            f = Finding(
                "phase-registry", visitor.path, line,
                f"phase {name!r} is not declared in tpubloom.obs.names."
                f"PHASES — the phase vocabulary is central so dashboards, "
                f"bench.py and the slowlog line up",
            )
            f._lines = (line,)  # type: ignore[attr-defined]
            visitor.findings.append(f)
        for head, line in visitor.phase_dynamic_uses:
            if head and any(head.startswith(p) for p in prefixes):
                continue
            f = Finding(
                "phase-registry", visitor.path, line,
                f"dynamic phase name with literal head {head!r} matches "
                f"no declared PHASE_DYNAMIC_PREFIXES entry in "
                f"tpubloom.obs.names — minted phase series need a "
                f"declared shape",
            )
            f._lines = (line,)  # type: ignore[attr-defined]
            visitor.findings.append(f)
    if config.spans is not None:
        sprefixes = tuple(config.span_prefixes or ())
        for name, line in visitor.span_uses:
            if name in config.spans or any(
                name.startswith(p) for p in sprefixes
            ):
                continue
            f = Finding(
                "trace-registry", visitor.path, line,
                f"span {name!r} is not declared in tpubloom.obs.names."
                f"SPANS — the trace vocabulary is central so TraceGet "
                f"trees, /trace and dashboards line up",
            )
            f._lines = (line,)  # type: ignore[attr-defined]
            visitor.findings.append(f)
        for head, line in visitor.span_dynamic_uses:
            if head and any(head.startswith(p) for p in sprefixes):
                continue
            f = Finding(
                "trace-registry", visitor.path, line,
                f"dynamic span name with literal head {head!r} matches "
                f"no declared SPAN_DYNAMIC_PREFIXES entry in "
                f"tpubloom.obs.names — minted span series need a "
                f"declared shape",
            )
            f._lines = (line,)  # type: ignore[attr-defined]
            visitor.findings.append(f)
    if config.events is not None:
        for kind, line in visitor.event_uses:
            if kind in config.events:
                continue
            f = Finding(
                "trace-registry", visitor.path, line,
                f"flight-recorder event {kind!r} is not declared in "
                f"tpubloom.obs.names.EVENTS — a typo'd kind silently "
                f"mints a series no post-mortem tooling knows",
            )
            f._lines = (line,)  # type: ignore[attr-defined]
            visitor.findings.append(f)


def lint_file(path: str, config: LintConfig) -> tuple:
    """Lint one file; returns (findings, visitor, suppressions). The
    visitor carries the literal collections the tree checks aggregate;
    the suppression table is returned UNRESOLVED for unused-allow
    accounting because tree-level checks (``chaos-coverage``,
    ``replay-safety``) may still claim a file's suppressions after
    every file has been read — :func:`lint_paths` settles them."""
    with open(path, "r", encoding="utf-8") as f:
        source = f.read()
    findings: list = []
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        findings.append(
            Finding("blocking-under-lock", path, e.lineno or 0,
                    f"file does not parse: {e.msg}")
        )
        return findings, None, None
    visitor = _FileVisitor(path, config)
    visitor.visit(tree)
    _apply_registry_checks(visitor, config)
    visitor.findings.extend(check_donation_safety(tree, path))
    sup = _Suppressions(path, source, findings)
    for f in visitor.findings:
        lines = getattr(f, "_lines", (f.line,))
        # claim the suppression BEFORE the disable filter: disabling a
        # check must not orphan its reasoned allows into
        # unused-suppression findings
        if sup.matches(f.check, *lines):
            continue
        if f.check in config.disable:
            continue
        findings.append(f)
    return (
        [f for f in findings if f.check not in config.disable],
        visitor,
        sup,
    )


# -- registry parsing (AST, no heavyweight imports) ---------------------------


def _collection_node(value: ast.AST) -> Optional[ast.AST]:
    """Unwrap ``frozenset({...})`` / ``set([...])`` / ``tuple((...))``
    wrappers down to the literal collection node, if any."""
    if isinstance(value, (ast.Tuple, ast.Set, ast.List)):
        return value
    if (
        isinstance(value, ast.Call)
        and isinstance(value.func, ast.Name)
        and value.func.id in ("frozenset", "set", "tuple", "list")
        and len(value.args) == 1
    ):
        return _collection_node(value.args[0])
    return None


def _parse_string_collection(path: str, target_names: Iterable[str]) -> dict:
    """``{name: [literals...]}`` for module-level assignments of string
    tuples/sets/lists named in ``target_names`` (duplicates preserved;
    ``frozenset({...})``-style wrappers unwrapped)."""
    return {
        name: [v for v, _line in items]
        for name, items in _parse_string_collection_lines(
            path, target_names
        ).items()
    }


def _parse_string_collection_lines(
    path: str, target_names: Iterable[str]
) -> dict:
    """Like :func:`_parse_string_collection` but each entry is
    ``(literal, lineno)`` — tree checks anchor findings (and accept
    suppressions) on the declaration line itself."""
    out: dict = {}
    with open(path, "r", encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    wanted = set(target_names)
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        coll = _collection_node(node.value)
        if coll is None:
            continue
        for t in node.targets:
            if isinstance(t, ast.Name) and t.id in wanted:
                out[t.id] = [
                    (e.value, e.lineno)
                    for e in coll.elts
                    if isinstance(e, ast.Constant) and isinstance(e.value, str)
                ]
    return out


def load_fault_points(repo_root: str) -> frozenset:
    path = os.path.join(repo_root, "tpubloom", "faults", "__init__.py")
    return frozenset(
        _parse_string_collection(path, ("KNOWN_POINTS",)).get(
            "KNOWN_POINTS", ()
        )
    )


def _parse_prefix_heads(path: str, target_name: str) -> tuple:
    """The literal prefix heads of a ``((prefix, why), ...)``-shaped
    module-level assignment (the *_DYNAMIC_PREFIXES declarations)."""
    prefixes = []
    with open(path, "r", encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        for t in node.targets:
            if isinstance(t, ast.Name) and t.id == target_name:
                coll = _collection_node(node.value)
                for e in (coll.elts if coll is not None else ()):
                    inner = _collection_node(e)
                    if (
                        inner is not None
                        and inner.elts
                        and isinstance(inner.elts[0], ast.Constant)
                        and isinstance(inner.elts[0].value, str)
                    ):
                        prefixes.append(inner.elts[0].value)
    return tuple(prefixes)


def load_phase_names(repo_root: str) -> tuple:
    """(phases, dynamic prefixes) from obs/names.py (ISSUE 13); empty
    when the catalog is absent (partial fixture trees)."""
    path = os.path.join(repo_root, "tpubloom", "obs", "names.py")
    if not os.path.isfile(path):
        return frozenset(), ()
    decls = _parse_string_collection(path, ("PHASES",))
    phases = frozenset(decls.get("PHASES", ()))
    return phases, _parse_prefix_heads(path, "PHASE_DYNAMIC_PREFIXES")


def load_trace_names(repo_root: str) -> tuple:
    """(spans, span prefixes, events) from obs/names.py (ISSUE 15);
    empty when the catalog is absent (partial fixture trees)."""
    path = os.path.join(repo_root, "tpubloom", "obs", "names.py")
    if not os.path.isfile(path):
        return frozenset(), (), frozenset()
    decls = _parse_string_collection(path, ("SPANS", "EVENTS"))
    return (
        frozenset(decls.get("SPANS", ())),
        _parse_prefix_heads(path, "SPAN_DYNAMIC_PREFIXES"),
        frozenset(decls.get("EVENTS", ())),
    )


def load_metric_names(repo_root: str) -> tuple:
    """(counters, gauges, duplicate-findings) from obs/names.py."""
    path = os.path.join(repo_root, "tpubloom", "obs", "names.py")
    decls = _parse_string_collection(path, ("COUNTERS", "GAUGES"))
    counters = decls.get("COUNTERS", [])
    gauges = decls.get("GAUGES", [])
    findings = []
    for kind, names in (("COUNTERS", counters), ("GAUGES", gauges)):
        seen: set = set()
        for n in names:
            if n in seen:
                findings.append(
                    Finding(
                        "metric-registry", path, 0,
                        f"{n!r} is declared twice in {kind} — registered "
                        f"exactly once means once",
                    )
                )
            seen.add(n)
    for n in sorted(set(counters) & set(gauges)):
        findings.append(
            Finding(
                "metric-registry", path, 0,
                f"{n!r} is declared as both a counter and a gauge",
            )
        )
    return frozenset(counters), frozenset(gauges), findings


# -- tree checks --------------------------------------------------------------


def _literal_set(path: str) -> set:
    """Every string constant in a file (cheap containment probe)."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=path)
    except (OSError, SyntaxError):
        return set()
    return {
        n.value
        for n in ast.walk(tree)
        if isinstance(n, ast.Constant) and isinstance(n.value, str)
    }


def _service_handlers(path: str) -> tuple:
    """(method defs on BloomService, keys of the stream behavior maps)."""
    with open(path, "r", encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    handlers: set = set()
    behaviors: set = set()
    for node in tree.body:
        if isinstance(node, ast.ClassDef) and node.name == "BloomService":
            handlers = {
                n.name
                for n in node.body
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            }
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Dict):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id in (
                    "_STREAM_BEHAVIORS", "_CLIENT_STREAM_BEHAVIORS",
                    "_BIDI_STREAM_BEHAVIORS",
                ):
                    behaviors |= {
                        k.value
                        for k in node.value.keys
                        if isinstance(k, ast.Constant)
                    }
    return handlers, behaviors


def check_protocol_coverage(repo_root: str) -> list:
    """Every METHODS entry: handler + client call + golden test; every
    streaming method: behavior registration + golden test."""
    proto_path = os.path.join(repo_root, "tpubloom", "server", "protocol.py")
    service_path = os.path.join(repo_root, "tpubloom", "server", "service.py")
    if not os.path.isfile(proto_path) or not os.path.isfile(service_path):
        return []  # partial fixture tree: nothing to cross-reference
    decls = _parse_string_collection(
        proto_path,
        (
            "METHODS", "STREAM_METHODS", "CLIENT_STREAM_METHODS",
            "BIDI_STREAM_METHODS",
        ),
    )
    client_path = os.path.join(repo_root, "tpubloom", "server", "client.py")
    golden_path = os.path.join(repo_root, "tests", "test_protocol_golden.py")
    handlers, behaviors = _service_handlers(service_path)
    client_lits = _literal_set(client_path)
    golden_lits = _literal_set(golden_path)
    findings = []

    def miss(method: str, what: str) -> None:
        findings.append(
            Finding(
                "protocol-coverage", proto_path, 0,
                f"protocol method {method!r} has no {what}",
            )
        )

    for m in decls.get("METHODS", ()):
        if m not in handlers:
            miss(m, "BloomService handler (def in service.py)")
        if m not in client_lits:
            miss(m, "client call site (literal in client.py)")
        if m not in golden_lits:
            miss(m, "golden wire test (literal in test_protocol_golden.py)")
    for m in list(decls.get("STREAM_METHODS", ())) + list(
        decls.get("CLIENT_STREAM_METHODS", ())
    ):
        if m not in behaviors:
            miss(m, "service behavior registration (_*_BEHAVIORS map)")
        if m not in golden_lits:
            miss(m, "golden wire test (literal in test_protocol_golden.py)")
    # bidi streams (ISSUE 18) additionally require a Python client call
    # site — unlike ReplStream/ReplAck they are a user-facing surface
    for m in decls.get("BIDI_STREAM_METHODS", ()):
        if m not in behaviors:
            miss(m, "service behavior registration (_*_BEHAVIORS map)")
        if m not in client_lits:
            miss(m, "client call site (literal in client.py)")
        if m not in golden_lits:
            miss(m, "golden wire test (literal in test_protocol_golden.py)")
    return findings


#: where the Ruby drivers live, relative to the repo root.
RUBY_DRIVER_DIR = os.path.join(
    "clients", "ruby", "lib", "redis-bloomfilter", "driver"
)

_RUBY_METHODS_RE = re.compile(r"METHODS\s*=\s*%w\[([^\]]*)\]")


def check_ruby_parity(repo_root: str) -> list:
    """Every ``protocol.METHODS`` entry covered by the Ruby drivers
    (ISSUE 12 satellite, ROADMAP item 6): a quoted call-site literal in
    the union of the driver files, plus registry/protocol set equality
    for the base driver's ``METHODS`` constant — so protocol growth
    that forgets the Ruby side fails CI the same way a missing Python
    handler does."""
    proto_path = os.path.join(repo_root, "tpubloom", "server", "protocol.py")
    if not os.path.isfile(proto_path):
        return []  # partial fixture tree
    decls = _parse_string_collection(
        proto_path, ("METHODS", "BIDI_STREAM_METHODS")
    )
    methods = list(decls.get("METHODS", ()))
    bidi = list(decls.get("BIDI_STREAM_METHODS", ()))
    driver_dir = os.path.join(repo_root, RUBY_DRIVER_DIR)
    findings: list = []
    if not methods or not os.path.isdir(driver_dir):
        return findings
    sources: dict[str, str] = {}
    for fn in sorted(os.listdir(driver_dir)):
        if fn.endswith(".rb"):
            path = os.path.join(driver_dir, fn)
            try:
                with open(path, "r", encoding="utf-8") as f:
                    sources[path] = f.read()
            except OSError:
                continue
    if not sources:
        return findings
    all_src = "\n".join(sources.values())
    # only the BASE driver carries the METHODS registry constant the
    # equality check applies to (the cluster driver subclasses it)
    base_path = os.path.join(driver_dir, "jax.rb")
    base_registry = {
        m
        for block in _RUBY_METHODS_RE.findall(sources.get(base_path, ""))
        for m in block.split()
    }
    for m in methods:
        if f'"{m}"' not in all_src and f"'{m}'" not in all_src:
            findings.append(Finding(
                "ruby-parity", base_path, 0,
                f"protocol method {m!r} has no call site in any Ruby "
                f"driver (clients/ruby)",
            ))
        if base_registry and m not in base_registry:
            findings.append(Finding(
                "ruby-parity", base_path, 0,
                f"protocol method {m!r} missing from the Ruby driver's "
                f"METHODS registry",
            ))
    for extra in sorted(base_registry - set(methods)):
        findings.append(Finding(
            "ruby-parity", base_path, 0,
            f"Ruby METHODS registry lists {extra!r}, which is not a "
            f"protocol method — stale registry entry",
        ))
    # bidi stream methods (ISSUE 18): a call-site literal is required
    # (the registry equality stays METHODS-only — streams dial
    # bidi_streamer paths, not the unary rpc_once table)
    for m in bidi:
        if f'"{m}"' not in all_src and f"'{m}'" not in all_src:
            findings.append(Finding(
                "ruby-parity", base_path, 0,
                f"bidi stream method {m!r} has no call site in any Ruby "
                f"driver (clients/ruby)",
            ))
    return findings


def check_replay_safety(repo_root: str) -> list:
    """Every ``protocol.MUTATING_METHODS`` handler on ``BloomService``
    touches the rid→response dedup cache (``_dedup_get``/``_dedup_put``)
    — the PR-9/10 double-apply class: a mutating response that is not
    replay-cached turns a same-rid client retry into a second apply
    (counting filters double-increment, presence replays report the
    batch's own keys as pre-existing). Handlers whose replay provably
    CONVERGES instead carry a reasoned ``# lint: allow(replay-safety)``
    on the ``def`` line — the reason documents the convergence
    argument, which is exactly what hand-review kept re-deriving."""
    proto_path = os.path.join(repo_root, "tpubloom", "server", "protocol.py")
    service_path = os.path.join(repo_root, "tpubloom", "server", "service.py")
    if not os.path.isfile(proto_path) or not os.path.isfile(service_path):
        return []  # partial fixture tree
    mutating = set(
        _parse_string_collection(proto_path, ("MUTATING_METHODS",)).get(
            "MUTATING_METHODS", ()
        )
    )
    findings: list = []
    if not mutating:
        return findings
    with open(service_path, "r", encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=service_path)
    for node in tree.body:
        if not (isinstance(node, ast.ClassDef) and node.name == "BloomService"):
            continue
        for fn in node.body:
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if fn.name not in mutating:
                continue
            touches_dedup = any(
                isinstance(c, ast.Call)
                and isinstance(c.func, ast.Attribute)
                and c.func.attr in ("_dedup_get", "_dedup_put")
                for c in ast.walk(fn)
            )
            if touches_dedup:
                continue
            f = Finding(
                "replay-safety", service_path, fn.lineno,
                f"mutating handler {fn.name}() never touches the rid "
                f"dedup cache (_dedup_get/_dedup_put) — a same-rid retry "
                f"of a response that was lost in flight re-applies the "
                f"op (the PR-9/10 double-apply class); cache the "
                f"response, or suppress with the convergence argument",
            )
            f._lines = (fn.lineno,)  # type: ignore[attr-defined]
            findings.append(f)
    return findings


#: Where the chaos-coverage check looks for arming sites. Benchmarks
#: count too (ISSUE 15 satellite — the ROADMAP item 6 seam): a fault
#: point driven only by a benchmark harness's ``TPUBLOOM_FAULTS``
#: string (or a direct ``faults.arm``) is covered, not dead surface.
TESTS_DIR = "tests"
BENCHMARKS_DIR = "benchmarks"

_FAULT_ENV_RE = re.compile(r"([a-z_]+(?:\.[a-z_]+)+)\s*=")


def _collect_armed_points(dirs, known: frozenset) -> set:
    """Fault points armed by literal anywhere under the given
    directories: a ``faults.arm("point", ...)`` call, or a
    ``TPUBLOOM_FAULTS``-syntax string constant
    (``"point=policy[,point=policy...]"``)."""
    armed: set = set()
    if isinstance(dirs, str):
        dirs = [dirs]
    for path in iter_py_files(list(dirs)):
        try:
            with open(path, "r", encoding="utf-8") as f:
                tree = ast.parse(f.read(), filename=path)
        except (OSError, SyntaxError):
            continue
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "arm"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                armed.add(node.args[0].value)
            elif isinstance(node, ast.Constant) and isinstance(
                node.value, str
            ):
                for m in _FAULT_ENV_RE.finditer(node.value):
                    if m.group(1) in known:
                        armed.add(m.group(1))
    return armed


def check_chaos_coverage(repo_root: str) -> list:
    """Every declared fault point is ARMED in at least one test
    (ISSUE 13): a ``KNOWN_POINTS`` entry nobody ever arms is dead chaos
    surface — the failure path it guards compiles, fires a counter, and
    has never once been driven. Findings anchor on the point's
    declaration line so a reasoned suppression lives next to the
    vocabulary it covers."""
    faults_path = os.path.join(
        repo_root, "tpubloom", "faults", "__init__.py"
    )
    if not os.path.isfile(faults_path):
        return []  # partial fixture tree
    decls = _parse_string_collection_lines(
        faults_path, ("KNOWN_POINTS",)
    ).get("KNOWN_POINTS", [])
    if not decls:
        return []
    known = frozenset(p for p, _ in decls)
    armed = _collect_armed_points(
        [
            os.path.join(repo_root, TESTS_DIR),
            os.path.join(repo_root, BENCHMARKS_DIR),
        ],
        known,
    )
    findings = []
    for point, line in decls:
        if point in armed:
            continue
        f = Finding(
            "chaos-coverage", faults_path, line,
            f"fault point {point!r} is declared but never armed in any "
            f"test or benchmark harness (no faults.arm literal, no "
            f"TPUBLOOM_FAULTS string) — dead chaos surface: add an "
            f"armed test or suppress here with the reason the path is "
            f"covered another way",
        )
        f._lines = (line,)  # type: ignore[attr-defined]
        findings.append(f)
    return findings


def iter_py_files(paths: Iterable[str]) -> list:
    out = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = [d for d in dirs if d != "__pycache__"]
            out.extend(
                os.path.join(root, fn) for fn in sorted(files)
                if fn.endswith(".py")
            )
    return out


def _load_suppressions(path: str) -> Optional[_Suppressions]:
    """On-demand suppression table for a file tree checks anchor in but
    the linted path set did not cover (grammar findings dropped — the
    file is not being linted, only consulted)."""
    if not path.endswith(".py") or not os.path.isfile(path):
        return None
    try:
        with open(path, "r", encoding="utf-8") as f:
            return _Suppressions(path, f.read(), [])
    except (OSError, SyntaxError):
        return None


def lint_paths(paths: Iterable[str], config: Optional[LintConfig] = None) -> list:
    config = config or LintConfig()
    repo_root = config.repo_root or _repo_root()
    findings: list = []
    if config.known_fault_points is None:
        config.known_fault_points = load_fault_points(repo_root)
    if config.counters is None or config.gauges is None:
        counters, gauges, dup_findings = load_metric_names(repo_root)
        config.counters = counters
        config.gauges = gauges
        if config.tree_checks:
            findings.extend(dup_findings)
    if config.phases is None:
        config.phases, config.phase_prefixes = load_phase_names(repo_root)
    if config.spans is None or config.events is None:
        spans, span_prefixes, events = load_trace_names(repo_root)
        if config.spans is None:
            config.spans, config.span_prefixes = spans, span_prefixes
        if config.events is None:
            config.events = events

    fault_literal_seen: set = set()
    metric_literal_seen: set = set()
    phase_literal_seen: set = set()
    phase_prefix_seen: set = set()
    span_literal_seen: set = set()
    span_prefix_seen: set = set()
    event_literal_seen: set = set()
    fault_registry_path = os.path.join(
        repo_root, "tpubloom", "faults", "__init__.py"
    )
    names_path = os.path.join(repo_root, "tpubloom", "obs", "names.py")
    #: abspath -> (display path, _Suppressions), settled only after the
    #: tree checks ran; linted_paths bounds unused-allow accounting to
    #: files that actually went through the per-file checks
    sups: dict = {}
    linted_paths: set = set()
    for path in iter_py_files(paths):
        file_findings, visitor, sup = lint_file(path, config)
        findings.extend(file_findings)
        if visitor is None:
            continue
        sups[os.path.abspath(path)] = (path, sup)
        linted_paths.add(os.path.abspath(path))
        if os.path.abspath(path) != os.path.abspath(fault_registry_path):
            fault_literal_seen |= visitor.str_constants
        if os.path.abspath(path) != os.path.abspath(names_path):
            metric_literal_seen |= {n for n, _, _ in visitor.metric_uses}
        phase_literal_seen |= {n for n, _ in visitor.phase_uses}
        phase_prefix_seen |= {h for h, _ in visitor.phase_dynamic_uses if h}
        span_literal_seen |= {n for n, _ in visitor.span_uses}
        span_prefix_seen |= {h for h, _ in visitor.span_dynamic_uses if h}
        event_literal_seen |= {k for k, _ in visitor.event_uses}

    if config.tree_checks:
        tree_findings: list = []
        tree_findings.extend(check_protocol_coverage(repo_root))
        tree_findings.extend(check_ruby_parity(repo_root))
        tree_findings.extend(check_replay_safety(repo_root))
        tree_findings.extend(check_chaos_coverage(repo_root))
        for point in sorted(config.known_fault_points - fault_literal_seen):
            tree_findings.append(
                Finding(
                    "fault-registry", fault_registry_path, 0,
                    f"declared fault point {point!r} is never referenced "
                    f"outside the registry — dead vocabulary",
                )
            )
        for name in sorted(
            (config.counters | config.gauges) - metric_literal_seen
        ):
            tree_findings.append(
                Finding(
                    "metric-registry", names_path, 0,
                    f"declared metric {name!r} is never emitted in the "
                    f"linted tree — stale catalog entry",
                )
            )
        for name in sorted(config.phases - phase_literal_seen):
            tree_findings.append(
                Finding(
                    "phase-registry", names_path, 0,
                    f"declared phase {name!r} is never emitted in the "
                    f"linted tree — stale vocabulary entry",
                )
            )
        for prefix in config.phase_prefixes or ():
            if not any(
                h.startswith(prefix) or prefix.startswith(h)
                for h in phase_prefix_seen
            ) and not any(
                n.startswith(prefix) for n in phase_literal_seen
            ):
                tree_findings.append(
                    Finding(
                        "phase-registry", names_path, 0,
                        f"declared dynamic phase prefix {prefix!r} has no "
                        f"emit site in the linted tree — stale "
                        f"vocabulary entry",
                    )
                )
        for name in sorted((config.spans or frozenset()) - span_literal_seen):
            tree_findings.append(
                Finding(
                    "trace-registry", names_path, 0,
                    f"declared span {name!r} is never emitted in the "
                    f"linted tree — stale vocabulary entry",
                )
            )
        for prefix in config.span_prefixes or ():
            if not any(
                h.startswith(prefix) or prefix.startswith(h)
                for h in span_prefix_seen
            ) and not any(
                n.startswith(prefix) for n in span_literal_seen
            ):
                tree_findings.append(
                    Finding(
                        "trace-registry", names_path, 0,
                        f"declared dynamic span prefix {prefix!r} has no "
                        f"emit site in the linted tree — stale "
                        f"vocabulary entry",
                    )
                )
        for kind in sorted(
            (config.events or frozenset()) - event_literal_seen
        ):
            tree_findings.append(
                Finding(
                    "trace-registry", names_path, 0,
                    f"declared flight-recorder event {kind!r} is never "
                    f"emitted in the linted tree — stale vocabulary "
                    f"entry",
                )
            )
        # tree findings honor inline suppressions at their anchor line
        # (the declaration/def they point at), same grammar as per-file
        for f in tree_findings:
            key = os.path.abspath(f.path)
            entry = sups.get(key)
            if entry is None:
                loaded = _load_suppressions(f.path)
                if loaded is not None:
                    entry = (f.path, loaded)
                    sups[key] = entry
            lines = getattr(f, "_lines", (f.line,))
            # claim BEFORE the disable filter (see lint_file): a
            # disabled check's reasoned allows must not rot into
            # unused-suppression findings
            if entry is not None and entry[1].matches(f.check, *lines):
                continue
            if f.check in config.disable:
                continue
            findings.append(f)
    # unused-allow accounting settles LAST, after tree checks had their
    # chance to claim a file's suppressions — and only for files that
    # actually went through the per-file checks (a merely-consulted
    # file's allows can target checks this run never applied to it)
    for abspath in sorted(linted_paths):
        display, sup = sups[abspath]
        findings.extend(sup.unused(display))
    return [f for f in findings if f.check not in config.disable]


def main(argv: Optional[list] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tpubloom.analysis.lint",
        description="tpubloom project lint: concurrency + registry invariants",
    )
    parser.add_argument(
        "paths", nargs="*", default=None,
        help="files/directories to lint (default: the tpubloom package)",
    )
    parser.add_argument(
        "--no-tree-checks", action="store_true",
        help="skip the cross-file checks (protocol coverage, reverse "
        "registry checks)",
    )
    parser.add_argument("--json", action="store_true", dest="as_json")
    args = parser.parse_args(argv)
    repo_root = _repo_root()
    paths = args.paths or [os.path.join(repo_root, "tpubloom")]
    # expand once: iter_py_files passes plain files through, so the
    # resolved list is also a valid `paths` for lint_paths
    files = iter_py_files(paths)
    config = LintConfig(tree_checks=not args.no_tree_checks)
    findings = lint_paths(files, config)
    if args.as_json:
        print(json.dumps([f.to_dict() for f in findings], indent=2))
    else:
        for f in findings:
            print(f.format())
        print(
            f"tpubloom.analysis.lint: {len(findings)} finding(s) in "
            f"{len(files)} file(s)"
        )
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
