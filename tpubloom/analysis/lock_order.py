"""Declared lock-ORDER manifest vs. the runtime acquisition graph.

PR 6 made lock ordering *observable*: the armed tracker
(:mod:`tpubloom.utils.locks`) records every ``a → b`` acquisition edge
and flags cycles. But a cycle only appears once BOTH orders exist — a
brand-new edge that will deadlock against next month's code lands
silently. This module closes that gap (ISSUE 9 satellite, ROADMAP item
7): the project's intended lock ordering is DECLARED here, and any
runtime edge outside the manifest is a finding — new nesting is a
reviewed design decision, not an accident discovered at 3am.

The manifest is a set of ``(outer, inner)`` lock-CLASS pairs (the names
given to :func:`tpubloom.utils.locks.named_lock` and friends), seeded
from the edges the chaos suites actually drive — including the new
``cluster.*`` ranks the slot-migration paths mint (``cluster.state`` is
a leaf: nothing may be acquired under it except the tracker's own
bookkeeping, because migration forwards do network IO).

Checking:

* :func:`diff_edges` / :func:`check_report` — library API
  (``tests/test_cluster.py`` runs it over the armed chaos module's
  tracker + subprocess reports at teardown);
* ``python -m tpubloom.analysis.lock_order [report.json|dir ...]`` —
  operator CLI over ``lockcheck-*.json`` exit reports
  (``$TPUBLOOM_LOCK_CHECK_DIR``); exit 1 on undeclared edges. ``--list``
  prints the manifest.

Growing the manifest is the point, not a failure: when a new edge is
legitimate, add it here IN THE SAME PR with the code that mints it.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Iterable, Optional

#: The declared acquisition order: (outer, inner) = "inner may be
#: acquired while outer is held". Everything else is a finding.
ALLOWED_EDGES = frozenset(
    {
        # -- op-log commit points (PR 3): the log append happens under
        #    the lock its op committed under
        ("filter.op", "repl.oplog"),
        ("service.registry", "repl.oplog"),
        # the checkpoint-keyed truncation sweep (every 64 appends) runs
        # from _log_op — i.e. under the committing filter's op lock —
        # and snapshots the registry. The REVERSE order must never be
        # declared: registry holders always release before taking an op
        # lock (create/drop/gauge walks), which is what keeps this a DAG
        ("filter.op", "service.registry"),
        # create/drop maintain the manifest + checkpoint trigger state
        # under their commit locks
        ("filter.op", "ckpt.trigger"),
        ("service.registry", "ckpt.trigger"),
        ("repl.oplog", "ckpt.trigger"),
        # filter construction may trigger the native kernel build cache
        ("filter.op", "native.build"),
        ("service.registry", "native.build"),
        # gauge snapshots read per-filter state under the op lock
        ("filter.op", "obs.metrics"),
        ("service.registry", "obs.metrics"),
        ("filter.op", "obs.counters"),
        ("service.registry", "obs.counters"),
        ("repl.oplog", "obs.counters"),
        ("ckpt.trigger", "obs.counters"),
        ("ckpt.redis_sink", "obs.counters"),
        ("service.admit", "obs.counters"),
        ("service.dedup", "obs.counters"),
        ("obs.metrics", "obs.counters"),
        ("obs.slowlog", "obs.counters"),
        ("faults.registry", "obs.counters"),
        ("client.breaker", "obs.counters"),
        ("client.topology", "obs.counters"),
        ("repl.sessions", "obs.counters"),
        ("repl.monitor_hub", "obs.counters"),
        ("repl.ack_sender", "obs.counters"),
        ("repl.applier_call", "obs.counters"),
        ("sentinel.state", "obs.counters"),
        ("sentinel.topo_events", "obs.counters"),
        ("cluster.state", "obs.counters"),
        ("cluster.client", "obs.counters"),
        # fault points fire inside commit sections
        ("filter.op", "faults.registry"),
        ("service.registry", "faults.registry"),
        ("repl.oplog", "faults.registry"),
        ("repl.applier_call", "faults.registry"),
        ("repl.ack_sender", "faults.registry"),
        # replication: the applier serializes its call/ack plumbing, and
        # record apply walks the normal commit locks
        ("repl.applier_call", "repl.ack_sender"),
        ("repl.applier_call", "repl.oplog"),
        ("repl.applier_call", "filter.op"),
        ("repl.applier_call", "service.registry"),
        ("repl.applier_call", "ckpt.trigger"),
        ("repl.applier_call", "obs.counters"),
        # promotion / demotion re-plumb the service under the promote
        # lock (PR 4)
        ("service.promote", "service.registry"),
        ("service.promote", "filter.op"),
        ("service.promote", "repl.oplog"),
        ("service.promote", "repl.sessions"),
        ("service.promote", "repl.applier_call"),
        ("service.promote", "repl.ack_sender"),
        ("service.promote", "ckpt.trigger"),
        ("service.promote", "obs.counters"),
        # become_replica counts ha_demotions while still holding the
        # promote lock (pre-existing; first DIFFED by test_ingest's
        # in-process demotion test — test_ha demotes subprocesses)
        ("service.promote", "obs.metrics"),
        ("service.promote", "faults.registry"),
        # primary-side streaming reads sessions + log state
        ("repl.sessions", "repl.oplog"),
        ("repl.oplog", "obs.metrics"),
        # -- cluster mode (ISSUE 9): the migration driver snapshots
        #    under the filter lock and arms the dual-write there;
        #    cluster.state itself is a LEAF apart from gauge updates —
        #    node→node RPCs always run outside it
        ("filter.op", "cluster.state"),
        ("service.registry", "cluster.state"),
        ("cluster.client", "client.breaker"),
        # -- ingestion coalescer (ISSUE 10): the queue condition is a
        #    LEAF apart from the parked-keys gauge — the dispatcher
        #    drops it before touching any filter/registry/log lock, and
        #    the flush itself mints only the existing filter.op edges.
        #    ISSUE 11 (sharded filters through the coalescer) adds NO
        #    new edges by design: the per-shard chaos surface is fault
        #    POINTS (shard.*), not locks — the staged launches fire
        #    them under the existing filter.op -> faults.registry edge,
        #    and the replicated H2D staging is lock-free (verified by
        #    the armed test_ingest module's manifest diff)
        ("ingest.queue", "obs.counters"),
        # the demotion barrier drains parked coalesced writes under the
        # promote lock (become_replica — see ingest.drain_parked, which
        # deliberately POLLS instead of waiting on the condition)
        ("service.promote", "ingest.queue"),
    }
)


def diff_edges(edges: Iterable[tuple]) -> list:
    """Runtime edges not covered by the manifest, as finding dicts."""
    findings = []
    for edge in sorted(set(map(tuple, edges))):
        if tuple(edge) not in ALLOWED_EDGES:
            findings.append(
                {
                    "kind": "undeclared-lock-edge",
                    "edge": list(edge),
                    "message": (
                        f"runtime acquisition {edge[0]!r} -> {edge[1]!r} "
                        f"is not in the declared lock-order manifest "
                        f"(tpubloom/analysis/lock_order.py) — declare it "
                        f"deliberately or fix the nesting"
                    ),
                }
            )
    return findings


def edges_of_report(report: dict) -> list:
    """``[(from, to), ...]`` out of one lockcheck report dict (the
    :func:`tpubloom.utils.locks.report` shape / exit-report JSON)."""
    return [(e["from"], e["to"]) for e in report.get("edges", ())]


def check_report(report: dict) -> list:
    return diff_edges(edges_of_report(report))


def check_live() -> list:
    """Diff the in-process tracker's graph (armed test sessions)."""
    from tpubloom.utils import locks

    return check_report(locks.report())


def _iter_report_paths(paths: Iterable[str]) -> list:
    out = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(glob.glob(os.path.join(p, "lockcheck-*.json"))))
        else:
            out.append(p)
    return out


def main(argv: Optional[list] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tpubloom.analysis.lock_order",
        description="diff runtime lock-acquisition graphs against the "
        "declared lock-order manifest",
    )
    parser.add_argument(
        "paths", nargs="*",
        help="lockcheck-*.json reports (or directories of them); default: "
        "$TPUBLOOM_LOCK_CHECK_DIR",
    )
    parser.add_argument(
        "--list", action="store_true", dest="list_manifest",
        help="print the declared manifest and exit",
    )
    parser.add_argument("--json", action="store_true", dest="as_json")
    args = parser.parse_args(argv)
    if args.list_manifest:
        for outer, inner in sorted(ALLOWED_EDGES):
            print(f"{outer} -> {inner}")
        return 0
    paths = args.paths or [os.environ.get("TPUBLOOM_LOCK_CHECK_DIR", "")]
    paths = [p for p in paths if p]
    if not paths:
        parser.error("no report paths given and TPUBLOOM_LOCK_CHECK_DIR unset")
    findings: list = []
    n_reports = 0
    for path in _iter_report_paths(paths):
        try:
            with open(path) as f:
                report = json.load(f)
        except (OSError, ValueError) as e:
            findings.append(
                {
                    "kind": "unreadable-report",
                    "message": f"{path}: {e}",
                }
            )
            continue
        n_reports += 1
        for finding in check_report(report):
            findings.append({**finding, "report": path})
    if args.as_json:
        print(json.dumps(findings, indent=2))
    else:
        for f in findings:
            print(f"[{f['kind']}] {f['message']}"
                  + (f"  ({f['report']})" if "report" in f else ""))
        print(
            f"tpubloom.analysis.lock_order: {len(findings)} finding(s) "
            f"across {n_reports} report(s)"
        )
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
