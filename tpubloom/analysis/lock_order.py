"""Declared lock-ORDER manifest vs. the runtime acquisition graph.

PR 6 made lock ordering *observable*: the armed tracker
(:mod:`tpubloom.utils.locks`) records every ``a → b`` acquisition edge
and flags cycles. But a cycle only appears once BOTH orders exist — a
brand-new edge that will deadlock against next month's code lands
silently. This module closes that gap (ISSUE 9 satellite, ROADMAP item
7): the project's intended lock ordering is DECLARED here, and any
runtime edge outside the manifest is a finding — new nesting is a
reviewed design decision, not an accident discovered at 3am.

The manifest is a set of ``(outer, inner)`` lock-CLASS pairs (the names
given to :func:`tpubloom.utils.locks.named_lock` and friends). ISSUE 13
re-harvested it against the FULL armed fleet — all five chaos modules
(faults, ha, sync_repl, cluster, ingest) now gate their teardown on
this diff via the shared ``lock_order_manifest`` fixture in
tests/conftest.py, closing the ROADMAP-6 seam — and every declared
edge carries the one-line reason (the minting code path) it exists.
``cluster.state`` stays a leaf apart from its gauge updates: nothing
may be acquired under it, because migration forwards do network IO.

Checking:

* :func:`diff_edges` / :func:`check_report` — library API (the shared
  conftest fixture runs it over every armed chaos module's tracker +
  subprocess reports at teardown);
* ``python -m tpubloom.analysis.lock_order [report.json|dir ...]`` —
  operator CLI over ``lockcheck-*.json`` exit reports
  (``$TPUBLOOM_LOCK_CHECK_DIR``); exit 1 on undeclared edges. ``--list``
  prints the manifest;
* ``python -m tpubloom.analysis`` — the ISSUE-13 unified driver folds
  this diff and the static tree lint into one exit code (what CI's
  ``analysis`` job runs over the chaos shard's uploaded reports).

Growing the manifest is the point, not a failure: when a new edge is
legitimate, add it here IN THE SAME PR with the code that mints it.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Iterable, Optional

#: The declared acquisition order: (outer, inner) = "inner may be
#: acquired while outer is held". Everything else is a finding.
#:
#: ISSUE 13 re-harvested this manifest against the full armed fleet
#: (faults/ha/sync_repl joined cluster/ingest behind the shared
#: ``lock_order_manifest`` teardown gate) and PRUNED 25 edges whose
#: minting code path no longer exists — the PR-9 lesson applied at
#: scale: a speculatively-declared edge is a place a real cycle can
#: hide. Every surviving edge carries the one-line reason it exists
#: (the code path that mints it); an edge you cannot annotate is an
#: edge you should not declare. Notable removals: the five
#: ``repl.applier_call -> {filter.op, repl.oplog, service.registry,
#: ckpt.trigger, faults.registry}`` edges (records now apply OUTSIDE
#: the applier's call lock — it guards only the stream/ack handles),
#: ``repl.sessions -> repl.oplog`` (ReplStream reads the log head
#: BEFORE entering the sessions condition), and a family of
#: ``X -> obs.counters`` edges whose counters moved outside their
#: lock regions as the hot paths were slimmed (faults.registry,
#: obs.slowlog, service.dedup, ckpt.trigger, ckpt.redis_sink,
#: repl.monitor_hub, repl.ack_sender, sentinel.topo_events,
#: cluster.client). The same audit DECLARED one latent edge no suite
#: had driven yet: ``filter.op -> repl.sessions`` (the truncation
#: sweep's replica-cursor floor — see below).
ALLOWED_EDGES = frozenset(
    {
        # -- op-log commit points (PR 3): the append happens under the
        #    lock its op committed under
        # handlers append from _log_op inside `with mf.lock`
        ("filter.op", "repl.oplog"),
        # _log_create / DropFilter append inside the registry lock (a
        # concurrent create/drop of the same name must serialize with
        # the record order)
        ("service.registry", "repl.oplog"),
        # -- the checkpoint-keyed truncation sweep (every 64 appends,
        #    _maybe_truncate_log) runs from _log_op — i.e. under the
        #    committing filter's op lock — and:
        # ...snapshots the registry for the per-filter landed floors.
        # The REVERSE order must never be declared: registry holders
        # always release before taking an op lock (create/drop/gauge
        # walks), which is what keeps this a DAG
        ("filter.op", "service.registry"),
        # ...bounds GC by the slowest replica's cursor —
        # repl_sessions.min_cursor() takes the sessions condition.
        # Declared by the ISSUE-13 audit: reachable on every 64th
        # append, but no armed module had crossed the boundary on one
        # filter yet — the closure had a latent hole
        ("filter.op", "repl.sessions"),
        # ...counts repl_log_truncations via Metrics.count (obs.metrics
        # lock) while the op lock is still held
        ("filter.op", "obs.metrics"),
        # notify_inserts/trigger take the trigger lock at the handler
        # commit point, under the filter's op lock
        ("filter.op", "ckpt.trigger"),
        # first insert/query on a filter may build the native key-pack
        # extension (utils.packing -> native.build cache) under the op
        # lock
        ("filter.op", "native.build"),
        # -- counters under commit/bookkeeping locks (each one a
        #    deliberate "incr while held" site, not a blanket allowance)
        # handlers count keys/dedup hits + log-append errors while the
        # op lock is held
        ("filter.op", "obs.counters"),
        # create/drop count filters_created etc. inside the registry
        ("service.registry", "obs.counters"),
        # registry-held walks (gauge_snapshot) file per-filter gauges
        ("service.registry", "obs.metrics"),
        # OpLog._update_gauges_locked sets repl_log_* gauges inside the
        # log condition on every append/truncate
        ("repl.oplog", "obs.counters"),
        # shed/admission accounting inside the admit lock
        ("service.admit", "obs.counters"),
        # Metrics methods (count/observe/snapshot) read global counters
        # while holding the metrics registry lock
        ("obs.metrics", "obs.counters"),
        # the client breaker counts state flips inside its lock
        ("client.breaker", "obs.counters"),
        # topology adoption counts pushes/refreshes under client.topology
        ("client.topology", "obs.counters"),
        # wait_acked maintains the wait_blocked_current gauge inside the
        # sessions condition (PR 5)
        ("repl.sessions", "obs.counters"),
        # repl_ack_stream_reopened incremented under the applier's call
        # lock when the ack stream is found broken (PR 5)
        ("repl.applier_call", "obs.counters"),
        # sentinel SDOWN/vote/failover accounting under sentinel.state
        ("sentinel.state", "obs.counters"),
        # slot-ownership gauges set inside cluster.state (PR 9)
        ("cluster.state", "obs.counters"),
        # parked-request gauge + coalesce counters inside the queue
        # condition (PR 10)
        ("ingest.queue", "obs.counters"),
        # -- fault points firing inside commit sections (the fire()
        #    armed-path takes faults.registry to consume the policy
        #    budget; reachable whenever a point is armed under a held
        #    commit lock — chaos suites do exactly that)
        # shard.*/ingest fault points fire under the filter op lock
        ("filter.op", "faults.registry"),
        # OpLog.append fires repl.append inside the log condition
        ("repl.oplog", "faults.registry"),
        # registry-held appends (_log_create, DropFilter) transit the
        # same repl.append firing with the registry still held
        ("service.registry", "faults.registry"),
        # -- replication plumbing (PR 5): the applier's call lock
        #    guards the stream/ack HANDLES (records apply outside it)
        # opening/closing an _AckSender under the call lock touches the
        # ack sender's coalescing condition
        ("repl.applier_call", "repl.ack_sender"),
        # -- promotion / demotion re-plumb the service under the
        #    promote lock (PR 4)
        # rebuild_manifest + epoch adoption walk the registry
        ("service.promote", "service.registry"),
        # become_replica's take-every-lock write fence
        ("service.promote", "filter.op"),
        # op-log adoption (OpLog open/set_alias) under the promote lock
        ("service.promote", "repl.oplog"),
        # demotion stops / promotion starts the applier (its call lock)
        ("service.promote", "repl.applier_call"),
        # ...and the applier teardown closes the ack sender
        ("service.promote", "repl.ack_sender"),
        # role transitions count promotions/demotions while still
        # holding the promote lock
        ("service.promote", "obs.counters"),
        # become_replica counts ha_demotions through Metrics (the
        # obs.metrics lock) while still holding the promote lock
        # (pre-existing; first DIFFED by test_ingest's in-process
        # demotion test — test_ha demotes subprocesses)
        ("service.promote", "obs.metrics"),
        # the demotion barrier drains parked coalesced writes under the
        # promote lock (become_replica — see ingest.drain_parked, which
        # deliberately POLLS instead of waiting on the condition)
        ("service.promote", "ingest.queue"),
        # -- storage tier (ISSUE 14): the residency manager's
        #    bookkeeping lock is a LEAF apart from counter/gauge
        #    updates — it is never held across a filter/registry lock,
        #    a device launch, or blob IO (hydration waiters block on a
        #    plain event holding nothing; the eviction critical section
        #    reuses the pre-existing filter.op -> service.registry
        #    unpublish edge)
        # gauge/counter updates inside _update_gauges_locked /
        # _trim_warm_locked run under storage.state
        ("storage.state", "obs.counters"),
        # the checkpoint-keyed truncation sweep (under the committing
        # filter's op lock, see filter.op -> service.registry above)
        # reads the PAGED tenants' durable floor too
        ("filter.op", "storage.state"),
        # _create/_drop re-check "does the storage tier still know this
        # tenant" UNDER the registry lock (the hydrate-then-create
        # TOCTOU guard: an eviction between the caller's hydrate and
        # this lock must retry, not rebuild fresh over paged state).
        # Cycle-free: storage code never acquires the registry while
        # holding storage.state (publishes/unpublishes happen outside
        # its bookkeeping lock)
        ("service.registry", "storage.state"),
        # become_replica's demotion barrier drains in-flight
        # hydrations/evictions (drain_busy — polls on purpose), the
        # promotion path folds paged tenants into rebuild_manifest and
        # the adopted-seq computation — all under service.promote
        ("service.promote", "storage.state"),
        # -- cluster mode (ISSUE 9): the migration driver snapshots
        #    under the filter lock and arms the dual-write there;
        #    cluster.state itself is a LEAF apart from gauge updates —
        #    node→node RPCs always run outside it.
        #    ISSUE 11 (sharded filters through the coalescer) adds NO
        #    new edges by design: the per-shard chaos surface is fault
        #    POINTS (shard.*), not locks — the staged launches fire
        #    them under the existing filter.op -> faults.registry edge,
        #    and the replicated H2D staging is lock-free (verified by
        #    the armed test_ingest module's manifest diff)
        ("filter.op", "cluster.state"),
    }
)


def diff_edges(edges: Iterable[tuple]) -> list:
    """Runtime edges not covered by the manifest, as finding dicts."""
    findings = []
    for edge in sorted(set(map(tuple, edges))):
        if tuple(edge) not in ALLOWED_EDGES:
            findings.append(
                {
                    "kind": "undeclared-lock-edge",
                    "edge": list(edge),
                    "message": (
                        f"runtime acquisition {edge[0]!r} -> {edge[1]!r} "
                        f"is not in the declared lock-order manifest "
                        f"(tpubloom/analysis/lock_order.py) — declare it "
                        f"deliberately or fix the nesting"
                    ),
                }
            )
    return findings


def edges_of_report(report: dict) -> list:
    """``[(from, to), ...]`` out of one lockcheck report dict (the
    :func:`tpubloom.utils.locks.report` shape / exit-report JSON)."""
    return [(e["from"], e["to"]) for e in report.get("edges", ())]


def check_report(report: dict) -> list:
    return diff_edges(edges_of_report(report))


def check_live() -> list:
    """Diff the in-process tracker's graph (armed test sessions)."""
    from tpubloom.utils import locks

    return check_report(locks.report())


def _iter_report_paths(paths: Iterable[str]) -> list:
    out = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(glob.glob(os.path.join(p, "lockcheck-*.json"))))
        else:
            out.append(p)
    return out


def main(argv: Optional[list] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tpubloom.analysis.lock_order",
        description="diff runtime lock-acquisition graphs against the "
        "declared lock-order manifest",
    )
    parser.add_argument(
        "paths", nargs="*",
        help="lockcheck-*.json reports (or directories of them); default: "
        "$TPUBLOOM_LOCK_CHECK_DIR",
    )
    parser.add_argument(
        "--list", action="store_true", dest="list_manifest",
        help="print the declared manifest and exit",
    )
    parser.add_argument("--json", action="store_true", dest="as_json")
    args = parser.parse_args(argv)
    if args.list_manifest:
        for outer, inner in sorted(ALLOWED_EDGES):
            print(f"{outer} -> {inner}")
        return 0
    paths = args.paths or [os.environ.get("TPUBLOOM_LOCK_CHECK_DIR", "")]
    paths = [p for p in paths if p]
    if not paths:
        parser.error("no report paths given and TPUBLOOM_LOCK_CHECK_DIR unset")
    findings: list = []
    n_reports = 0
    for path in _iter_report_paths(paths):
        try:
            with open(path) as f:
                report = json.load(f)
        except (OSError, ValueError) as e:
            findings.append(
                {
                    "kind": "unreadable-report",
                    "message": f"{path}: {e}",
                }
            )
            continue
        n_reports += 1
        for finding in check_report(report):
            findings.append({**finding, "report": path})
    if args.as_json:
        print(json.dumps(findings, indent=2))
    else:
        for f in findings:
            print(f"[{f['kind']}] {f['message']}"
                  + (f"  ({f['report']})" if "report" in f else ""))
        print(
            f"tpubloom.analysis.lock_order: {len(findings)} finding(s) "
            f"across {n_reports} report(s)"
        )
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
