"""Distributed communication backend: ICI/DCN via JAX, not NCCL/MPI.

Parity: the reference's "distributed backend" is RESP over TCP to a single
Redis (SURVEY.md §5 "Distributed comm backend"). The TPU-native answer has
three tiers:

* **intra-pod (ICI)**: XLA collectives emitted by ``shard_map`` — the
  ``psum`` all-reduce-OR in :mod:`tpubloom.parallel.sharded`. Nothing to
  initialize; the mesh is the backend.
* **multi-host (DCN)**: ``jax.distributed.initialize`` — wrapped here so a
  multi-host filter-array service starts with one call per host and the
  global device list feeds the same ``make_mesh``.
* **host<->client**: the gRPC server (:mod:`tpubloom.server`).

No NCCL/MPI/Gloo anywhere — on TPU the collective layer *is* XLA over
ICI/DCN.
"""

from __future__ import annotations

import logging
from typing import Optional

import jax

from tpubloom import faults

log = logging.getLogger("tpubloom.distributed")


def initialize_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    *,
    auto_detect: bool = False,
) -> dict:
    """Join (or bootstrap) a multi-host JAX runtime over DCN.

    Three modes:

    * explicit: pass coordinator/num_processes/process_id;
    * ``auto_detect=True`` with no arguments: ``jax.distributed.initialize()``
      reads the TPU pod metadata (the standard cloud-TPU path);
    * neither (default): single-host no-op returning the local topology —
      safe to call unconditionally in tests/CPU environments where pod
      auto-detection would fail.

    Call once per host before building meshes. Returns a topology summary
    dict (host count, device counts).
    """
    # chaos hook (ISSUE 4 satellite): a multi-host bring-up that dies at
    # the coordinator join is a distinct failure class from a shard fault
    faults.fire("dist.initialize")
    if num_processes is not None and num_processes > 1:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
        log.info(
            "joined multi-host pod: process %s/%s, coordinator %s",
            process_id, num_processes, coordinator_address,
        )
    elif auto_detect:
        jax.distributed.initialize()
        log.info("joined multi-host pod via metadata auto-detection")
    topo = {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_device_count": jax.local_device_count(),
        "global_device_count": jax.device_count(),
    }
    log.info("topology: %s", topo)
    return topo
