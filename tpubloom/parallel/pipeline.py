"""Streaming insert pipeline — BASELINE config 3 (1B-key streams).

Parity: the reference has no streaming story; its closest tool is Redis
pipelining of per-key commands (SURVEY.md §2.2 "Streaming/pipeline
parallel"). The TPU-native equivalent pinned there: a host->device input
pipeline for billion-key streams with periodic checkpoint overlap.

Mechanics:

* the host packs fixed-size key batches while the device crunches the
  previous ones — JAX's async dispatch IS the double buffer; the pipeline
  just avoids synchronizing, with a bounded in-flight window as
  backpressure so host-side buffers can't pile up;
* every ``checkpoint_every`` keys the AsyncCheckpointer snapshots the array
  (HBM copy + async D2H + background write) WITHOUT stalling inserts, and
  records the stream offset in the checkpoint header;
* **crash recovery contract** (SURVEY.md §5 failure row): on restart,
  ``resume_offset`` says where the newest checkpoint cut the stream.
  Replaying the source from any point <= that offset is safe — scatter-OR
  is idempotent, so at-least-once delivery converges to the same bits —
  and everything before the offset is guaranteed present. Tail loss is
  bounded by ``checkpoint_every`` + one in-flight batch window.
"""

from __future__ import annotations

from typing import Iterable, Iterator, Optional

import numpy as np

from tpubloom.checkpoint import AsyncCheckpointer
from tpubloom.utils.packing import pack_keys


class StreamInserter:
    """Feed an unbounded key stream into a filter at full device rate."""

    def __init__(
        self,
        filter_obj,
        *,
        batch_size: int = 1 << 16,
        sink=None,
        checkpoint_every: int = 0,
        max_in_flight: int = 8,
        start_offset: int = 0,
    ):
        self.filter = filter_obj
        self.batch_size = batch_size
        self.max_in_flight = max_in_flight
        self.consumed = start_offset  # keys consumed from the stream origin
        self._dispatched_since_sync = 0
        self.checkpointer: Optional[AsyncCheckpointer] = None
        if sink is not None and checkpoint_every:
            # meta_fn snapshots the offset at trigger time, under the same
            # control flow as inserts (run() is single-threaded), so the
            # recorded offset is consistent with the snapshotted bits.
            self.checkpointer = AsyncCheckpointer(
                filter_obj,
                sink,
                every_n_inserts=checkpoint_every,
                meta_fn=lambda: {"stream_offset": self._synced_offset()},
            )

    def _synced_offset(self) -> int:
        """Offset fully materialized on device at snapshot time.

        Everything dispatched is captured by the snapshot: the HBM copy in
        trigger() is enqueued AFTER all pending insert kernels on the same
        device stream, so `consumed` (all keys handed to the device) is the
        safe offset.
        """
        return self.consumed

    def run(self, keys: Iterable[bytes], *, limit: Optional[int] = None) -> dict:
        """Consume the stream (optionally at most ``limit`` keys). Returns
        run stats. Reentrant: call again to continue the same stream."""
        it: Iterator[bytes] = iter(keys)
        batch: list = []
        inserted = 0
        while True:
            batch.clear()
            budget = self.batch_size
            if limit is not None:
                budget = min(budget, limit - inserted)
                if budget <= 0:
                    break
            for key in it:
                batch.append(key)
                if len(batch) >= budget:
                    break
            if not batch:
                break
            keys_u8, lengths = pack_keys(
                batch, self.filter.config.key_len,
                key_policy=self.filter.config.key_policy,
            )
            if len(batch) < self.batch_size:  # static-shape padding
                pad = self.batch_size - len(batch)
                keys_u8 = np.pad(keys_u8, ((0, pad), (0, 0)))
                lengths = np.pad(lengths, (0, pad), constant_values=-1)
            self.filter.insert_arrays(keys_u8, lengths, n_valid=len(batch))
            inserted += len(batch)
            self.consumed += len(batch)
            self._dispatched_since_sync += 1
            if self._dispatched_since_sync >= self.max_in_flight:
                # backpressure: bound the async dispatch queue
                self.filter.block_until_ready()
                self._dispatched_since_sync = 0
            if self.checkpointer:
                self.checkpointer.notify_inserts(len(batch))
        self.filter.block_until_ready()
        return {
            "inserted": inserted,
            "stream_offset": self.consumed,
            "checkpoints_written": (
                self.checkpointer.checkpoints_written if self.checkpointer else 0
            ),
        }

    def close(self, *, final_checkpoint: bool = True) -> bool:
        """Flush and stop checkpointing. Returns False when the requested
        final checkpoint did NOT land — callers using close() as the
        durability point before discarding the source stream must check it
        (``checkpointer.last_error`` has the cause). No checkpointer
        configured -> trivially True."""
        if self.checkpointer:
            return self.checkpointer.close(final_checkpoint=final_checkpoint)
        return True


def resume_offset(restored_filter) -> int:
    """Stream offset recorded in the checkpoint a filter was restored from
    (0 if none): restart the source at or before this offset and re-run —
    idempotent inserts make the replay safe."""
    meta = getattr(restored_filter, "_restored_meta", None) or {}
    return int(meta.get("stream_offset", 0))
