"""Streaming insert pipeline — BASELINE config 3 (1B-key streams).

Parity: the reference has no streaming story; its closest tool is Redis
pipelining of per-key commands (SURVEY.md §2.2 "Streaming/pipeline
parallel"). The TPU-native equivalent pinned there: a host->device input
pipeline for billion-key streams with periodic checkpoint overlap.

Mechanics:

* the host packs fixed-size key batches while the device crunches the
  previous ones — JAX's async dispatch IS the double buffer; the pipeline
  just avoids synchronizing, with a bounded in-flight window as
  backpressure so host-side buffers can't pile up;
* every ``checkpoint_every`` keys the AsyncCheckpointer snapshots the array
  (HBM copy + async D2H + background write) WITHOUT stalling inserts, and
  records the stream offset in the checkpoint header;
* **crash recovery contract** (SURVEY.md §5 failure row): on restart,
  ``resume_offset`` says where the newest checkpoint cut the stream.
  Replaying the source from any point <= that offset is safe — scatter-OR
  is idempotent, so at-least-once delivery converges to the same bits —
  and everything before the offset is guaranteed present. Tail loss is
  bounded by ``checkpoint_every`` + one in-flight batch window.
"""

from __future__ import annotations

import queue
import threading
from typing import Iterable, Iterator, Optional

import numpy as np

from tpubloom.checkpoint import AsyncCheckpointer
from tpubloom.utils.packing import pack_keys


class StreamInserter:
    """Feed an unbounded key stream into a filter at full device rate.

    ``prefetch > 0`` overlaps host packing + H2D staging with device
    compute: a background thread packs the NEXT ``prefetch`` batches and
    starts their transfers while the device crunches the current one
    (the 1-core host's pack loop and the tunnel's H2D latency otherwise
    serialize with every insert dispatch)."""

    def __init__(
        self,
        filter_obj,
        *,
        batch_size: int = 1 << 16,
        sink=None,
        checkpoint_every: int = 0,
        max_in_flight: int = 8,
        start_offset: int = 0,
        prefetch: int = 0,
    ):
        self.filter = filter_obj
        self.batch_size = batch_size
        self.max_in_flight = max_in_flight
        self.prefetch = prefetch
        self.consumed = start_offset  # keys consumed from the stream origin
        self._dispatched_since_sync = 0
        self.checkpointer: Optional[AsyncCheckpointer] = None
        if sink is not None and checkpoint_every:
            # meta_fn snapshots the offset at trigger time, under the same
            # control flow as inserts (run() is single-threaded), so the
            # recorded offset is consistent with the snapshotted bits.
            self.checkpointer = AsyncCheckpointer(
                filter_obj,
                sink,
                every_n_inserts=checkpoint_every,
                meta_fn=lambda: {"stream_offset": self._synced_offset()},
            )

    def _synced_offset(self) -> int:
        """Offset fully materialized on device at snapshot time.

        Everything dispatched is captured by the snapshot: the HBM copy in
        trigger() is enqueued AFTER all pending insert kernels on the same
        device stream, so `consumed` (all keys handed to the device) is the
        safe offset.
        """
        return self.consumed

    def _packed_batches(self, it: Iterator[bytes], limit: Optional[int]):
        """Yield ``(keys_u8, lengths, n_valid)`` fixed-shape batches."""
        produced = 0
        while True:
            budget = self.batch_size
            if limit is not None:
                budget = min(budget, limit - produced)
                if budget <= 0:
                    return
            batch = []
            for key in it:
                batch.append(key)
                if len(batch) >= budget:
                    break
            if not batch:
                return
            keys_u8, lengths = pack_keys(
                batch, self.filter.config.key_len,
                key_policy=self.filter.config.key_policy,
            )
            if len(batch) < self.batch_size:  # static-shape padding
                pad = self.batch_size - len(batch)
                keys_u8 = np.pad(keys_u8, ((0, pad), (0, 0)))
                lengths = np.pad(lengths, (0, pad), constant_values=-1)
            produced += len(batch)
            yield keys_u8, lengths, len(batch)

    def _prefetched(self, batches):
        """Run the packer on a background thread; stage each batch onto
        the device (jax.device_put starts the H2D without blocking) so
        transfers overlap device compute. Exceptions re-raise in the
        consumer."""
        import jax

        q: queue.Queue = queue.Queue(maxsize=self.prefetch)
        cancel = threading.Event()
        _END, _ERR = object(), object()

        def put(item) -> bool:
            # bounded put that gives up when the consumer is gone — a
            # plain q.put could block forever on early consumer exit,
            # stalling the unwind and leaking the thread + its buffers
            while not cancel.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def worker():
            try:
                for keys_u8, lengths, n in batches:
                    if not put((jax.device_put(keys_u8), jax.device_put(lengths), n)):
                        return
            except BaseException as e:  # noqa: BLE001 — re-raised below
                put((_ERR, e, 0))
                return
            put((_END, None, 0))

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item[0] is _END:
                    return
                if item[0] is _ERR:
                    raise item[1]
                yield item
        finally:
            cancel.set()
            while not q.empty():  # unblock a put-in-progress
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
            t.join(timeout=30)

    def run(self, keys: Iterable[bytes], *, limit: Optional[int] = None) -> dict:
        """Consume the stream (optionally at most ``limit`` keys). Returns
        run stats. Reentrant: call again to continue the same stream."""
        it: Iterator[bytes] = iter(keys)
        inserted = 0
        batches = self._packed_batches(it, limit)
        if self.prefetch:
            batches = self._prefetched(batches)
        for keys_u8, lengths, n_valid in batches:
            self.filter.insert_arrays(keys_u8, lengths, n_valid=n_valid)
            inserted += n_valid
            self.consumed += n_valid
            self._dispatched_since_sync += 1
            if self._dispatched_since_sync >= self.max_in_flight:
                # backpressure: bound the async dispatch queue
                self.filter.block_until_ready()
                self._dispatched_since_sync = 0
            if self.checkpointer:
                self.checkpointer.notify_inserts(n_valid)
        self.filter.block_until_ready()
        return {
            "inserted": inserted,
            "stream_offset": self.consumed,
            "checkpoints_written": (
                self.checkpointer.checkpoints_written if self.checkpointer else 0
            ),
        }

    def close(self, *, final_checkpoint: bool = True) -> bool:
        """Flush and stop checkpointing. Returns False when the requested
        final checkpoint did NOT land — callers using close() as the
        durability point before discarding the source stream must check it
        (``checkpointer.last_error`` has the cause). No checkpointer
        configured -> trivially True."""
        if self.checkpointer:
            return self.checkpointer.close(final_checkpoint=final_checkpoint)
        return True


def resume_offset(restored_filter) -> int:
    """Stream offset recorded in the checkpoint a filter was restored from
    (0 if none): restart the source at or before this offset and re-run —
    idempotent inserts make the replay safe."""
    meta = getattr(restored_filter, "_restored_meta", None) or {}
    return int(meta.get("stream_offset", 0))
