"""Multi-chip parallelism: sharded filter arrays, distributed init, streaming."""
