"""ShardedBloomFilter — a filter array sharded across a TPU device mesh.

Parity: BASELINE config 5 — "64-shard filter array over v5e-8, m=2^36 total
— pmap hash + all-reduce-OR cross-chip membership". The reference gem has no
multi-node story (a single Redis instance is its whole world, SURVEY.md
§2.2); sharding across Redis instances is something its users bolt on
client-side. Here it is a first-class component.

Design (routed layout, SURVEY.md §3.5):

* The m-bit array is split into ``n_shards`` independent sub-filters of
  ``m_local = m / n_shards`` bits, laid out ``[n_shards, n_words_local]``
  and sharded over the mesh axis ``"shards"`` — shard s lives in chip s's
  HBM (1 GiB/chip at m=2^36 over 8 chips).
* Every chip hashes the **full** replicated batch (hashing is cheap VPU
  work; replicating it avoids an all-to-all of raw keys — the scaling-book
  move of trading redundant compute for collective traffic). A routing hash
  assigns each key to exactly one shard; a chip scatter-ORs only the keys it
  owns and drops the rest, so the whole k-position group of a key is local
  to one chip.
* Membership: each chip evaluates the gather-AND verdict for its owned keys;
  a single ``psum`` over the ``shards`` axis (all-reduce-OR of one-hot
  verdicts — rides the ICI) assembles the replicated ``bool[B]`` answer.
  One small collective per batch, O(B) bytes, no raw-key movement.
* Insert races are benign (scatter-OR commutes); routing is deterministic,
  so the same key always lands on the same chip.

The same code runs on a real v5e-8 and on the fake 8-device CPU backend
(``xla_force_host_platform_device_count``) used in tests and by the
driver's ``dryrun_multichip``.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

try:
    from jax import shard_map
except ImportError:  # jax < 0.6: experimental namespace + check_rep kwarg
    from jax.experimental.shard_map import shard_map as _shard_map_legacy

    def shard_map(f, **kwargs):
        if "check_vma" in kwargs:  # renamed from check_rep in newer jax
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _shard_map_legacy(f, **kwargs)


from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpubloom import faults
from tpubloom.config import FilterConfig
from tpubloom.filter import _FilterBase
from tpubloom.obs import context as obs
from tpubloom.ops import bitops, blocked, counting, hashing
from tpubloom.utils.packing import redis_bitmap_to_words, words_to_redis_bitmap

AXIS = "shards"


def make_mesh(n_shards: int, devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """1-D device mesh over the ``shards`` axis.

    ``n_shards`` may exceed the device count if it divides evenly — each
    device then hosts several logical shards (how 64 shards map onto 8
    chips in config 5: 8 shard-rows per chip).
    """
    if devices is None:
        devices = jax.devices()
    n_dev = len(devices)
    if n_shards % n_dev != 0 and n_dev % n_shards != 0:
        raise ValueError(f"n_shards={n_shards} incompatible with {n_dev} devices")
    use = devices[: min(n_shards, n_dev)]
    return Mesh(np.array(use), (AXIS,))


def _route_local(config: FilterConfig, shards_per_dev: int, keys_u8, lengths):
    """The one routing decision every sharded op shares: hash the
    replicated batch with the routing hash, map the owning shard to this
    device's local row space. Returns ``(local_row[B], owned[B],
    lens[B])`` — ``owned`` marks keys routed to one of this device's
    shard rows (False for batch padding); ``local_row`` is meaningful
    only where owned (callers clamp with ``jnp.where(owned, ...)``)."""
    dev = jax.lax.axis_index(AXIS)
    lens = jnp.maximum(lengths, 0)
    route = hashing.route_shards(
        keys_u8, lens, n_shards=config.shards, seed=config.seed
    ).astype(jnp.int32)
    local_row = route - dev * shards_per_dev
    owned = (local_row >= 0) & (local_row < shards_per_dev) & (lengths >= 0)
    return local_row, owned, lens


def _use_local_sweep(
    config: FilterConfig, local_rows: int, batch: int, *,
    presence: bool = False,
) -> bool:
    """Resolve config.insert_path for the per-device hot loop (the local
    row count, not the global filter, decides sweep applicability) —
    delegates to the single resolve_insert_path funnel. ``batch`` must be
    the EXPECTED OWNED count (~B / n_dev), matching the window-sizing
    call: resolving with the full replicated batch would overstate
    per-device occupancy by n_dev× and let a globally-dense but
    per-device-sparse batch stream the whole local block array for a
    handful of owned rows."""
    from tpubloom.ops import sweep

    return (
        sweep.resolve_insert_path(
            config, batch, presence=presence, n_blocks=local_rows
        )
        == "sweep"
    )


def _routed_positions(config: FilterConfig, shards_per_dev: int, keys_u8, lengths):
    """Shared insert/query preamble: hash the replicated batch, route each
    key, and translate to this device's local (word, bit) coordinates.

    Returns ``(word[B, k], bit[B, k], owned[B])`` where ``owned`` marks keys
    routed to one of this device's shard rows (False for padding) and
    ``word`` is clamped to row 0 for unowned keys (callers mask with
    ``owned`` — scatter drops them, gather verdicts are ignored).
    """
    m_local = config.m_per_shard
    local_row, owned, lens = _route_local(config, shards_per_dev, keys_u8, lengths)
    ph, pl = hashing.positions(
        keys_u8, lens, m=m_local, k=config.k, seed=config.seed
    )
    word, bit = hashing.split_word_bit(ph, pl)
    # Global->local row: shard r is row (r - dev*shards_per_dev) here.
    word = word + jnp.where(owned, local_row, 0)[:, None] * (m_local // 32)
    return word, bit, owned


def make_sharded_insert_fn(config: FilterConfig, mesh: Mesh):
    """``(words[S, W], keys[B, L], lengths[B]) -> words`` over the mesh.

    ``words`` is sharded over ``shards``; keys/lengths are replicated.
    """
    shards_per_dev = config.shards // mesh.devices.size

    def local_insert(words_block, keys_u8, lengths):
        # words_block: [shards_per_dev, n_words_local] — this device's rows.
        word, bit, owned = _routed_positions(
            config, shards_per_dev, keys_u8, lengths
        )
        flat = words_block.reshape(-1)
        valid_k = jnp.broadcast_to(owned[:, None], word.shape)
        flat = bitops.scatter_or(flat, word.ravel(), bit.ravel(), valid_k.ravel())
        return flat.reshape(words_block.shape)

    return shard_map(
        local_insert,
        mesh=mesh,
        in_specs=(P(AXIS, None), P(), P()),
        out_specs=P(AXIS, None),
    )


def make_sharded_query_fn(config: FilterConfig, mesh: Mesh):
    """``(words[S, W], keys[B, L], lengths[B]) -> bool[B]`` (replicated).

    Each chip answers for the keys it owns; ``psum`` over the shards axis
    (all-reduce-OR of disjoint one-hot verdicts) assembles the full answer.
    """
    shards_per_dev = config.shards // mesh.devices.size

    def local_query(words_block, keys_u8, lengths):
        word, bit, owned = _routed_positions(
            config, shards_per_dev, keys_u8, lengths
        )
        verdict = bitops.query_membership(words_block.reshape(-1), word, bit)
        one_hot = jnp.where(owned, verdict, False).astype(jnp.uint32)
        hit = jax.lax.psum(one_hot, AXIS)  # all-reduce-OR over ICI
        return hit > 0

    return shard_map(
        local_query,
        mesh=mesh,
        in_specs=(P(AXIS, None), P(), P()),
        out_specs=P(),
    )


def local_blocked_storage_fat(config: FilterConfig) -> bool:
    """Whether the sharded blocked storage keeps each shard's rows in the
    fat [NBL*W/128, 128] view (mirrors filter.blocked_storage_fat on the
    PER-SHARD geometry — mesh-size independent, because fat rows never
    straddle a shard boundary when NBL % J == 0). Applies to plain and
    counting blocked layouts; the per-device hot loop then runs the
    fat-row kernels at the 128-lane DMA tier (VERDICT r3 #3)."""
    if not config.block_bits:
        return False
    w = config.words_per_block
    return 128 % w == 0 and config.n_blocks_per_shard % (128 // w) == 0


def sharded_blocked_shape(config: FilterConfig) -> tuple[int, int, int]:
    """Global device-array shape for sharded blocked storage (plain or
    counting): per-shard fat rows when :func:`local_blocked_storage_fat`
    holds, else logical rows. The ONE place the sharded fat geometry is
    spelled out (ShardedBloomFilter and the driver dryrun both use it)."""
    if local_blocked_storage_fat(config):
        return (
            config.shards,
            config.n_blocks_per_shard * config.words_per_block // 128,
            128,
        )
    return (config.shards, config.n_blocks_per_shard, config.words_per_block)


def _routed_blocks(
    config: FilterConfig, shards_per_dev: int, keys_u8, lengths, *, want_bit=False
):
    """Blocked-layout preamble: route keys to shards, then to this device's
    local block rows. Returns ``(blk[B], masks[B, W], owned[B])`` (plus the
    raw in-block positions when ``want_bit`` — the sweep path re-sorts and
    rebuilds masks itself) with ``blk`` indexing the device-local
    ``[shards_per_dev * n_blocks_local]`` row space (clamped to 0 for
    unowned keys)."""
    nbl = config.n_blocks_per_shard
    local_row, owned, lens = _route_local(config, shards_per_dev, keys_u8, lengths)
    blk, bit = blocked.block_positions(
        keys_u8, lens,
        n_blocks=nbl, block_bits=config.block_bits, k=config.k,
        seed=config.seed, block_hash=config.block_hash,
    )
    masks = blocked.build_masks(bit, config.words_per_block)
    blk = blk + jnp.where(owned, local_row, 0) * nbl
    if want_bit:
        return blk, masks, owned, bit
    return blk, masks, owned


def make_sharded_blocked_insert_fn(config: FilterConfig, mesh: Mesh):
    """Blocked-layout sharded insert: ``(blocks[S, NBL, W], keys, lengths)``
    with ``blocks`` sharded over ``shards``; one row RMW per owned key.
    On TPU the per-device hot loop runs the Pallas partition sweep
    (pallas_call inside shard_map) when the local shape qualifies."""
    shards_per_dev = config.shards // mesh.devices.size
    local_rows = shards_per_dev * config.n_blocks_per_shard

    fat_store = local_blocked_storage_fat(config)
    n_dev = mesh.devices.size
    w = config.words_per_block

    def local_insert(blocks_block, keys_u8, lengths):
        from tpubloom.ops import sweep

        # blocks_block: [shards_per_dev, n_blocks_local, W] logical or
        # [shards_per_dev, NBL*W/128, 128] fat — this device's rows.
        B = keys_u8.shape[0]
        blk, masks, owned, bit = _routed_blocks(
            config, shards_per_dev, keys_u8, lengths, want_bit=True
        )
        use_sweep = _use_local_sweep(config, local_rows, max(1, B // n_dev))
        if fat_store:
            flat = blocks_block.reshape(-1, 128)  # [spd*NBLJ, 128]
            # window sizing uses the EXPECTED owned count (~B/n_dev):
            # sizing for the full replicated batch would inflate KJ by
            # n_dev x; per-window occupancy of owned keys is Poisson, so
            # lam+8sigma of B/n_dev covers it (overflow -> scatter
            # fallback inside apply_fat_updates keeps skew correct)
            fat_params = (
                sweep.choose_fat_params(local_rows, max(1, B // n_dev), w)
                if use_sweep
                else None
            )
            if fat_params is not None:
                out = sweep.apply_fat_updates(
                    flat, blk, bit, owned, block_bits=config.block_bits,
                    params=fat_params, storage_fat=True,
                )
                return out.reshape(blocks_block.shape)
            if use_sweep:
                # legacy kernel needs the logical view (reshape copy —
                # only shapes the fat chooser rejects land here)
                out = sweep.apply_blocked_updates(
                    flat.reshape(-1, w), blk, bit, owned,
                    block_bits=config.block_bits,
                )
                return out.reshape(blocks_block.shape)
            frow, m128 = blocked.fat_fold_masks(blk, masks, 128 // w)
            out = blocked.blocked_insert(flat, frow, m128, owned)
            return out.reshape(blocks_block.shape)
        flat = blocks_block.reshape(-1, w)
        if use_sweep:
            flat = sweep.apply_blocked_updates(
                flat, blk, bit, owned, block_bits=config.block_bits
            )
        else:
            flat = blocked.blocked_insert(flat, blk, masks, owned)
        return flat.reshape(blocks_block.shape)

    return shard_map(
        local_insert,
        mesh=mesh,
        in_specs=(P(AXIS, None, None), P(), P()),
        out_specs=P(AXIS, None, None),
        # pallas_call outputs cannot carry vma metadata; the local insert
        # has no collectives, so the varying-axes lint has nothing to check
        check_vma=False,
    )


def make_sharded_blocked_query_fn(config: FilterConfig, mesh: Mesh):
    """Blocked-layout sharded membership with the same psum-OR assembly as
    the flat path: owners answer, ICI all-reduce merges.

    On TPU the per-device verdicts ride the read-only query sweep kernel
    (ISSUE 12) when the LOCAL shape qualifies and ``shards_per_dev == 1``:
    every key (owned or not) then queries its natural in-shard block row
    on every device — the occupancy stays uniform over the local rows,
    the sweep's tail-suffix presence contract holds (``lengths >= 0`` is
    tail padding), and unowned keys' garbage verdicts are masked by
    ``owned`` before the psum, exactly as the gather path masks them.
    With several shards per device the unowned keys would pile onto
    shard-row 0's windows (n_dev× the sized occupancy → perpetual
    overflow fallback), so those geometries keep the gather."""
    shards_per_dev = config.shards // mesh.devices.size
    local_rows = shards_per_dev * config.n_blocks_per_shard

    fat_store = local_blocked_storage_fat(config)
    w = config.words_per_block

    def local_query(blocks_block, keys_u8, lengths):
        from tpubloom.ops import sweep

        B = keys_u8.shape[0]
        blk, masks, owned, bit = _routed_blocks(
            config, shards_per_dev, keys_u8, lengths, want_bit=True
        )
        if fat_store and shards_per_dev == 1 and (
            sweep.resolve_query_path(config, B, n_blocks=local_rows)
            == "sweep"
        ):
            # window sizing uses the FULL batch: with spd == 1 every key
            # lands at its in-shard row on every device (blk is already
            # local — `owned` adds 0), so per-window occupancy covers B,
            # same as the gather path's B-row gather per device
            params = sweep.choose_fat_query_params(local_rows, B, w)
            if params is not None:
                flat = blocks_block.reshape(-1, 128)
                verdict = sweep.apply_fat_query(
                    flat, blk, bit, lengths >= 0,
                    block_bits=config.block_bits, params=params,
                    storage_fat=True,
                )
                one_hot = jnp.where(owned, verdict, False).astype(jnp.uint32)
                return jax.lax.psum(one_hot, AXIS) > 0
        if fat_store:
            flat = blocks_block.reshape(-1, 128)
            verdict = blocked.fat_blocked_query(flat, blk, masks)
        else:
            flat = blocks_block.reshape(-1, w)
            verdict = blocked.blocked_query(flat, blk, masks)
        one_hot = jnp.where(owned, verdict, False).astype(jnp.uint32)
        hit = jax.lax.psum(one_hot, AXIS)
        return hit > 0

    return shard_map(
        local_query,
        mesh=mesh,
        in_specs=(P(AXIS, None, None), P(), P()),
        out_specs=P(),
        # pallas_call outputs carry no vma metadata (see blocked insert);
        # the psum still assembles the replicated verdict either way
        check_vma=False,
    )


# -- counting variant (configs 4 x 5: sharded counting filter array) ---------


def _routed_counter_positions(config: FilterConfig, shards_per_dev, keys_u8, lengths):
    """Flat-counting preamble: route keys, then device-local counter
    positions. ``m`` counts COUNTERS; shard s owns counters
    ``[s*m_local, (s+1)*m_local)``. Returns ``(pos[B, k], owned[B])`` with
    ``pos`` in this device's ``[0, shards_per_dev*m_local)`` local space
    (row 0 for unowned keys — callers mask)."""
    m_local = config.m_per_shard
    local_row, owned, lens = _route_local(config, shards_per_dev, keys_u8, lengths)
    _, pl = hashing.positions(
        keys_u8, lens, m=m_local, k=config.k, seed=config.seed
    )
    pos = pl.astype(jnp.int32) + jnp.where(owned, local_row, 0)[:, None] * m_local
    return pos, owned


def make_sharded_counter_fn(config: FilterConfig, mesh: Mesh, *, increment: bool):
    """Flat-counting sharded update: ``(words[S, Wc], keys, lengths) ->
    words`` with saturating +1 (insert) / flooring -1 (delete) on this
    device's packed 4-bit counters — same one-clamp-per-batch semantics
    as :func:`tpubloom.ops.counting.counter_update` (the ground truth)."""
    shards_per_dev = config.shards // mesh.devices.size

    def local_update(words_block, keys_u8, lengths):
        pos, owned = _routed_counter_positions(
            config, shards_per_dev, keys_u8, lengths
        )
        valid_k = jnp.broadcast_to(owned[:, None], pos.shape)
        flat = counting.counter_update(
            words_block.reshape(-1), pos.ravel(), valid_k.ravel(),
            increment=increment,
        )
        return flat.reshape(words_block.shape)

    return shard_map(
        local_update,
        mesh=mesh,
        in_specs=(P(AXIS, None), P(), P()),
        out_specs=P(AXIS, None),
    )


def make_sharded_counting_query_fn(config: FilterConfig, mesh: Mesh):
    """Flat-counting sharded membership: owners test all-k-counters
    nonzero, psum-OR over ICI assembles the replicated verdict."""
    shards_per_dev = config.shards // mesh.devices.size

    def local_query(words_block, keys_u8, lengths):
        pos, owned = _routed_counter_positions(
            config, shards_per_dev, keys_u8, lengths
        )
        verdict = counting.counting_membership(words_block.reshape(-1), pos)
        one_hot = jnp.where(owned, verdict, False).astype(jnp.uint32)
        hit = jax.lax.psum(one_hot, AXIS)
        return hit > 0

    return shard_map(
        local_query,
        mesh=mesh,
        in_specs=(P(AXIS, None), P(), P()),
        out_specs=P(),
    )


def _routed_counter_blocks(config: FilterConfig, shards_per_dev, keys_u8, lengths):
    """Blocked-counting preamble: route keys to shards, then to this
    device's local block rows. Returns ``(blk[B], cpos[B, k], owned[B])``
    with ``blk`` in the device-local ``[shards_per_dev * n_blocks_local]``
    row space and ``cpos`` the in-block counter positions."""
    nbl = config.n_blocks_per_shard
    local_row, owned, lens = _route_local(config, shards_per_dev, keys_u8, lengths)
    blk, cpos = blocked.block_positions(
        keys_u8, lens,
        n_blocks=nbl, block_bits=config.counters_per_block, k=config.k,
        seed=config.seed, block_hash=config.block_hash,
    )
    blk = blk + jnp.where(owned, local_row, 0) * nbl
    return blk, cpos, owned


def make_sharded_blocked_counter_fn(
    config: FilterConfig, mesh: Mesh, *, increment: bool
):
    """Blocked-counting sharded update; on TPU the per-device hot loop is
    the Pallas counting sweep (``sweep.apply_counter_updates`` inside
    shard_map), elsewhere the sorted-scan flat-counting kernel on the
    raveled local array — bit-identical results either way."""
    shards_per_dev = config.shards // mesh.devices.size
    local_rows = shards_per_dev * config.n_blocks_per_shard
    cpb = config.counters_per_block

    fat_store = local_blocked_storage_fat(config)
    n_dev = mesh.devices.size
    w = config.words_per_block

    def local_update(blocks_block, keys_u8, lengths):
        from tpubloom.ops import sweep

        B = keys_u8.shape[0]
        blk, cpos, owned = _routed_counter_blocks(
            config, shards_per_dev, keys_u8, lengths
        )
        use_sweep = _use_local_sweep(config, local_rows, max(1, B // n_dev))
        if use_sweep and config.k > 15:
            if config.insert_path == "sweep":
                # match the single-chip contract (filter.py): a forced
                # sweep must not silently run the scatter path
                raise ValueError(
                    "counting sweep supports k <= 15 — use "
                    "insert_path='scatter'"
                )
            use_sweep = False
        if fat_store:
            flat = blocks_block.reshape(-1, 128)
            fat_params = (
                sweep.choose_fat_params(
                    local_rows, max(1, B // n_dev), w, counting=True
                )
                if use_sweep
                else None
            )
            if fat_params is not None:
                out = sweep.apply_fat_counter_updates(
                    flat, blk, cpos, owned,
                    counters_per_block=cpb, k=config.k, increment=increment,
                    params=fat_params, storage_fat=True,
                )
                return out.reshape(blocks_block.shape)
            if use_sweep:
                out = sweep.apply_counter_updates(
                    flat.reshape(-1, w), blk, cpos, owned,
                    counters_per_block=cpb, k=config.k, increment=increment,
                )
                return out.reshape(blocks_block.shape)
            # flat scatter fallback: the raveled fat bytes ARE the
            # raveled logical bytes — no fold or reshape copy needed
            gpos = (blk[:, None] * cpb + cpos.astype(jnp.int32)).astype(
                jnp.int32
            )
            valid_k = jnp.broadcast_to(owned[:, None], gpos.shape)
            out = counting.counter_update(
                flat.reshape(-1), gpos.ravel(), valid_k.ravel(),
                increment=increment,
            )
            return out.reshape(blocks_block.shape)
        flat = blocks_block.reshape(-1, w)
        if use_sweep:
            flat = sweep.apply_counter_updates(
                flat, blk, cpos, owned,
                counters_per_block=cpb, k=config.k, increment=increment,
            )
            return flat.reshape(blocks_block.shape)
        gpos = (blk[:, None] * cpb + cpos.astype(jnp.int32)).astype(jnp.int32)
        valid_k = jnp.broadcast_to(owned[:, None], gpos.shape)
        out = counting.counter_update(
            flat.reshape(-1), gpos.ravel(), valid_k.ravel(),
            increment=increment,
        )
        return out.reshape(blocks_block.shape)

    return shard_map(
        local_update,
        mesh=mesh,
        in_specs=(P(AXIS, None, None), P(), P()),
        out_specs=P(AXIS, None, None),
        # pallas_call outputs carry no vma metadata (see blocked insert)
        check_vma=False,
    )


def make_sharded_blocked_counting_query_fn(config: FilterConfig, mesh: Mesh):
    """Blocked-counting sharded membership: one local row gather per owned
    key + all-counters-nonzero, psum-OR assembly."""
    shards_per_dev = config.shards // mesh.devices.size
    cpb = config.counters_per_block

    fat_store = local_blocked_storage_fat(config)
    w = config.words_per_block

    def local_query(blocks_block, keys_u8, lengths):
        blk, cpos, owned = _routed_counter_blocks(
            config, shards_per_dev, keys_u8, lengths
        )
        if fat_store:
            flat = blocks_block.reshape(-1, 128)
            verdict = counting.fat_blocked_counting_membership(
                flat, blk, cpos, w
            )
        else:
            flat = blocks_block.reshape(-1, w)
            verdict = counting.blocked_counting_membership(flat, blk, cpos)
        one_hot = jnp.where(owned, verdict, False).astype(jnp.uint32)
        hit = jax.lax.psum(one_hot, AXIS)
        return hit > 0

    return shard_map(
        local_query,
        mesh=mesh,
        in_specs=(P(AXIS, None, None), P(), P()),
        out_specs=P(),
    )


class ShardedBloomFilter(_FilterBase):
    """Filter array over a device mesh (config 5). API-compatible with
    :class:`tpubloom.filter.BloomFilter`."""

    def __init__(
        self,
        config: FilterConfig,
        mesh: Optional[Mesh] = None,
        devices: Optional[Sequence[jax.Device]] = None,
    ):
        if config.shards < 2:
            raise ValueError("ShardedBloomFilter needs config.shards >= 2")
        if config.counting and config.m >= (1 << 31):
            raise ValueError("counting filters support m < 2^31")
        self.mesh = mesh if mesh is not None else make_mesh(config.shards, devices)
        if config.shards % self.mesh.devices.size != 0:
            raise ValueError(
                f"shards={config.shards} must be a multiple of mesh size "
                f"{self.mesh.devices.size}"
            )
        super().__init__(config, 0)  # words set below with explicit sharding
        # per-shard fat [NBL*W/128, 128] storage where the shard geometry
        # allows (same row-major bytes per shard; 128-lane DMA tier for
        # the per-device hot loop — see filter.BlockedBloomFilter)
        self._fat = local_blocked_storage_fat(config)
        if config.counting and config.block_bits:
            self.sharding = NamedSharding(self.mesh, P(AXIS, None, None))
            self.words = jax.device_put(
                jnp.zeros(sharded_blocked_shape(config), jnp.uint32),
                self.sharding,
            )
            self._insert = jax.jit(
                make_sharded_blocked_counter_fn(config, self.mesh, increment=True),
                donate_argnums=0,
            )
            self._delete = jax.jit(
                make_sharded_blocked_counter_fn(config, self.mesh, increment=False),
                donate_argnums=0,
            )
            self._query = jax.jit(
                make_sharded_blocked_counting_query_fn(config, self.mesh)
            )
        elif config.counting:
            self.sharding = NamedSharding(self.mesh, P(AXIS, None))
            self.words = jax.device_put(
                jnp.zeros(
                    (config.shards, config.n_counter_words // config.shards),
                    jnp.uint32,
                ),
                self.sharding,
            )
            self._insert = jax.jit(
                make_sharded_counter_fn(config, self.mesh, increment=True),
                donate_argnums=0,
            )
            self._delete = jax.jit(
                make_sharded_counter_fn(config, self.mesh, increment=False),
                donate_argnums=0,
            )
            self._query = jax.jit(
                make_sharded_counting_query_fn(config, self.mesh)
            )
        elif config.block_bits:
            self.sharding = NamedSharding(self.mesh, P(AXIS, None, None))
            self.words = jax.device_put(
                jnp.zeros(sharded_blocked_shape(config), jnp.uint32),
                self.sharding,
            )
            self._insert = jax.jit(
                make_sharded_blocked_insert_fn(config, self.mesh), donate_argnums=0
            )
            self._query = jax.jit(make_sharded_blocked_query_fn(config, self.mesh))
        else:
            self.sharding = NamedSharding(self.mesh, P(AXIS, None))
            self.words = jax.device_put(
                jnp.zeros((config.shards, config.n_words_per_shard), jnp.uint32),
                self.sharding,
            )
            self._insert = jax.jit(
                make_sharded_insert_fn(config, self.mesh), donate_argnums=0
            )
            self._query = jax.jit(make_sharded_query_fn(config, self.mesh))

    def clear(self) -> None:
        self.words = jax.device_put(jnp.zeros_like(self.words), self.sharding)
        self.n_inserted = 0

    # -- per-shard fault points (ISSUE 4 satellite) --------------------------

    def _fire_shard_faults_packed(self, point: str, keys_u8, lengths) -> None:
        """Chaos hook over ALREADY-PACKED host arrays: fire ``point``
        once per shard this batch routes to, with ``shard=<index>``
        context — an armed ``shard=N`` predicate turns it into a
        PARTIAL failure (batches that touch shard N fail, everything
        else proceeds). Disarmed cost is one dict lookup; the host-side
        routing hash only runs while armed. This is the staged/packed
        paths' hook (ISSUE 11: lifting the coalescer exclusion required
        every sharded entry point, not just the list-path overrides, to
        keep the ``shard.*`` chaos surface)."""
        if not faults.is_armed(point):
            return
        lengths = np.asarray(lengths)
        routes = np.asarray(
            hashing.route_shards(
                jnp.asarray(keys_u8),
                jnp.asarray(np.maximum(lengths, 0)),
                n_shards=self.config.shards,
                seed=self.config.seed,
            )
        )
        touched = sorted(
            {int(s) for s, ln in zip(routes, lengths) if ln >= 0}
        )
        for shard in touched:
            faults.fire(point, shard=shard)

    def _fire_shard_faults(self, point: str, keys) -> None:
        """List-path chaos hook — packs, then routes (see
        :meth:`_fire_shard_faults_packed`)."""
        if not faults.is_armed(point):
            return
        keys_u8, lengths, _ = self._pack_padded(keys)
        self._fire_shard_faults_packed(point, keys_u8, lengths)

    def insert_batch(self, keys, **kwargs):
        self._fire_shard_faults("shard.insert", keys)
        return super().insert_batch(keys, **kwargs)

    def include_batch(self, keys):
        self._fire_shard_faults("shard.query", keys)
        return super().include_batch(keys)

    # -- per-device phase metrics (ISSUE 12 satellite, ROADMAP 1(c)) ---------

    def _kernel_fence(self, handle) -> None:
        """Break the single ``kernel``/``kernel_query`` span into
        per-shard device timings on the direct (per-request) path: fence
        each addressable shard in turn, recording a ``kernel_shard<i>``
        phase measured from the fence start — shard i's span is the
        time by which shards 0..i had all completed (the fences run
        sequentially over concurrently-executing devices), so the spans
        are monotone and the first big JUMP names the straggler device.
        Runs ONLY under an active request
        context (the library/bench paths keep the single fence;
        coalesced flushes fence on the dispatcher, which carries no
        request context — the per-flush span stays whole there, as
        before)."""
        import time

        ctx = obs.current()
        shards = getattr(handle, "addressable_shards", None)
        if ctx is None or not shards or len(shards) <= 1:
            handle.block_until_ready()
            return
        t0 = time.perf_counter()
        for i, sh in enumerate(shards):
            sh.data.block_until_ready()
            ctx.add_phase(f"kernel_shard{i}", time.perf_counter() - t0)
        handle.block_until_ready()

    # -- staged / packed surface (ISSUE 11) ----------------------------------
    #
    # The single-chip staged pipeline (filter._FilterBase.stage_batch /
    # launch_insert / launch_query) applies to the mesh unchanged — the
    # jitted shard_map kernels take the same (keys_u8, lengths) operands
    # — but the server excluded sharded filters from it (PR 10) because
    # the raw launches would bypass the per-shard ``shard.*`` fault
    # points above. These overrides restore that chaos surface: the
    # staged tuple carries the HOST arrays alongside the device handles,
    # and every launch fires the routed fault points before dispatch.
    # Staging also replicates the batch across the mesh explicitly
    # (device_put under the h2d phase), so the replication transfer
    # happens while the PREVIOUS flush's kernel is still in flight —
    # the coalescer's double buffering, mesh edition.

    #: tells the server's ``_staged_ok`` gate that the staged/packed
    #: fast paths preserve this filter's fault-point semantics
    staged_fault_points = True

    def _stage_batch(self, keys_u8, lengths):
        """Replicated H2D: place the batch on every mesh device now,
        split from the shard_map launch (the base class's single-device
        ``jnp.asarray`` would defer the broadcast into the launch)."""
        with obs.phase("h2d"):
            rep = NamedSharding(self.mesh, P())
            return (
                jax.device_put(np.ascontiguousarray(keys_u8), rep),
                jax.device_put(np.ascontiguousarray(lengths), rep),
            )

    def stage_batch(self, keys=None, *, rows=None):
        """Staged batch that ALSO carries the packed host arrays — the
        launch-side fault hooks route them without a second packing
        pass. Opaque to callers (launch_* unpack it)."""
        if rows is not None:
            keys_u8, lengths, B = self._prep_packed(np.asarray(rows, np.uint8))
        else:
            keys_u8, lengths, B = self._pack_padded(keys)
        d_keys, d_lengths = self._stage_batch(keys_u8, lengths)
        return d_keys, d_lengths, B, keys_u8, lengths

    def launch_insert(self, staged):
        d_keys, d_lengths, B, keys_u8, lengths = staged
        self._fire_shard_faults_packed("shard.insert", keys_u8, lengths)
        return super().launch_insert((d_keys, d_lengths, B))

    def launch_query(self, staged):
        d_keys, d_lengths, B, keys_u8, lengths = staged
        self._fire_shard_faults_packed("shard.query", keys_u8, lengths)
        return super().launch_query((d_keys, d_lengths, B))

    # delete (counting configs only — configs 4 x 5)

    def delete_batch(self, keys) -> None:
        if not self.config.counting:
            raise ValueError("delete requires a counting config")
        self._fire_shard_faults("shard.delete", keys)
        keys_u8, lengths, B = self._pack_padded(keys)
        self.words = self._delete(self.words, keys_u8, lengths)
        self.n_inserted = max(0, self.n_inserted - B)

    def delete(self, key) -> None:
        self.delete_batch([key])

    def shard_fill_ratios(self) -> Optional[list]:
        """Per-shard fraction of set bits (None for counting configs) —
        the /metrics ``tpubloom_shard_fill_ratio{filter,shard}`` gauge.
        Routing-skew triage: shards fill ~uniformly under the routing
        hash, so one shard running hot means a key-distribution problem
        (or a routing regression) that the GLOBAL fill ratio averages
        away. One device reduction, O(shards) bytes D2H."""
        if self.config.counting:
            return None
        per_word = jax.lax.population_count(
            self.words.reshape(self.config.shards, -1)
        )
        # float32 accumulator, same tradeoff as bitops.popcount_fill:
        # no uint32 overflow at m_per_shard > 2^32 bits, gauge-grade
        # precision
        counts = np.asarray(jnp.sum(per_word.astype(jnp.float32), axis=1))
        return [float(c) / self.config.m_per_shard for c in counts]

    def stats(self) -> dict:
        base = {
            "m": self.config.m,
            "k": self.config.k,
            "shards": self.config.shards,
            "devices": int(self.mesh.devices.size),
            "n_inserted": self.n_inserted,
            "n_queried": self.n_queried,
        }
        if self.config.counting:
            return base
        # one per-shard popcount serves every gauge: shards are equal
        # sized, so the global fill is exactly the mean of the per-shard
        # fills — no second O(m) reduction under the caller's op lock
        fills = self.shard_fill_ratios()
        fill = float(np.mean(fills))
        estimated = fill**self.config.k
        predicted = self.predicted_fpr()
        return {
            **base,
            "fill_ratio": fill,
            "bits_set": int(round(fill * self.config.m)),
            "estimated_fpr": estimated,
            "predicted_fpr": predicted,
            "fpr_drift": estimated - predicted,
            "fill_ratio_per_shard": fills,
        }

    @property
    def words_logical(self) -> np.ndarray:
        """Host copy in the logical per-shard layout: [shards, NBL, W]
        for blocked configs (undoing the fat per-shard view — same
        row-major bytes), else the device shape."""
        host = np.asarray(self.words)
        if self.config.block_bits:
            return host.reshape(
                self.config.shards,
                self.config.n_blocks_per_shard,
                self.config.words_per_block,
            )
        return host

    # Persistence: global layout = shard-major concatenation; bit
    # (s * m_local + p) of the export is bit p of shard s. Round-trips
    # through the same Redis-bitmap format as the single-device filter.

    def to_redis_bitmap(self) -> bytes:
        if self.config.block_bits or self.config.counting:
            raise ValueError(
                "blocked/counting layouts are not Redis-bitmap exportable "
                "(different position spec); use to_bytes"
            )
        host = np.asarray(self.words).reshape(-1)
        return words_to_redis_bitmap(host, self.config.m)

    @classmethod
    def from_redis_bitmap(
        cls, config: FilterConfig, data: bytes, **kwargs
    ) -> "ShardedBloomFilter":
        if config.block_bits or config.counting:
            raise ValueError("blocked/counting layouts restore via from_bytes")
        f = cls(config, **kwargs)
        words = redis_bitmap_to_words(data, config.m).reshape(
            config.shards, config.n_words_per_shard
        )
        f.words = jax.device_put(jnp.asarray(words), f.sharding)
        return f

    # blocked-layout persistence: raw LE words, shard-major then row-major

    def to_bytes(self) -> bytes:
        return np.asarray(self.words).reshape(-1).astype("<u4").tobytes()

    @classmethod
    def from_bytes(
        cls, config: FilterConfig, data: bytes, **kwargs
    ) -> "ShardedBloomFilter":
        f = cls(config, **kwargs)
        arr = np.frombuffer(data, dtype="<u4").astype(np.uint32)
        f.words = jax.device_put(
            jnp.asarray(arr.reshape(f.words.shape)), f.sharding
        )
        return f
