"""tpubloom — a TPU-native bloom-filter framework.

A ground-up JAX/XLA/Pallas re-design of the capabilities of
``kontera-technologies/redis-bloomfilter`` (see SURVEY.md; the reference mount
was empty at survey time, so parity targets come from BASELINE.json):

* the per-key hot path (k× MurmurHash3/FNV-1a, then set/test of bits in an
  m-bit array) runs as batched jit-compiled kernels on TPU,
* the bit array lives in HBM as a packed ``uint32`` array,
* inserts are fused scatter-OR, queries fused gather-AND reductions,
* ``shard_map`` + all-reduce-OR gives multi-chip filter arrays,
* a counting-filter variant supports delete via 4-bit packed counters,
* the device bit array checkpoints asynchronously in Redis-string-bitmap
  format, and
* a gRPC server exposes the batch API so the original Ruby
  ``Redis::Bloomfilter`` front-end can select a ``:jax`` driver alongside
  ``:ruby`` and ``:lua``.
"""

from tpubloom.version import __version__
from tpubloom.params import optimal_m_k, theoretical_fpr
from tpubloom.config import FilterConfig
from tpubloom.filter import (
    BlockedBloomFilter,
    BlockedCountingBloomFilter,
    BloomFilter,
    CountingBloomFilter,
)
from tpubloom.cpu_ref import CPUBlockedBloomFilter, CPUBloomFilter
from tpubloom.scalable import CPUScalableBloomFilter, ScalableBloomFilter

__all__ = [
    "__version__",
    "optimal_m_k",
    "theoretical_fpr",
    "FilterConfig",
    "BloomFilter",
    "BlockedBloomFilter",
    "CountingBloomFilter",
    "BlockedCountingBloomFilter",
    "CPUBloomFilter",
    "CPUBlockedBloomFilter",
    "ScalableBloomFilter",
    "CPUScalableBloomFilter",
]
