"""FilterConfig — the one config object for the whole framework.

Parity: the reference's config surface is the constructor options hash
``:size, :error_rate, :key_name, :driver, :redis`` (+ ``:hash_engine``)
(SURVEY.md §5 "Config/flag system" [PK]; BASELINE.json pins the driver
boundary). We mirror it as a single frozen dataclass — no global flags —
and derive (m, k) from (capacity, error_rate) with the reference-identical
math in :mod:`tpubloom.params` so configs are portable between the Ruby
front-end and this framework.
"""

from __future__ import annotations

import dataclasses

from tpubloom.params import optimal_m_k, round_up_pow2

#: Default seed for the hash family (any fixed u32; part of the filter's
#: identity — two filters interoperate only if (m, k, seed, hash spec) match).
DEFAULT_SEED = 0x9747B28C

#: Fields that define a filter's *semantic identity*: two configs agreeing on
#: these produce interchangeable bit arrays (positions are only portable
#: between identical hash configs; shards is identity-relevant because the
#: sharded payload is shard-major with per-shard-local positions).
IDENTITY_FIELDS = (
    "m", "k", "seed", "counting", "shards", "block_bits", "block_hash",
    "kind", "topk",
)

#: Filter kinds with their own storage layout + kernels (ISSUE 19).
#: "bloom" covers the whole pre-existing family (plain/counting/blocked/
#: sharded/scalable); the sketch kinds plug in via tpubloom.sketch.registry.
FILTER_KINDS = ("bloom", "cuckoo", "cms", "topk")


def identity_mismatch(a, b, fields=IDENTITY_FIELDS):
    """First identity field on which configs ``a`` and ``b`` disagree, or
    None if they match. ``a``/``b`` may be FilterConfig or plain dicts."""

    def get(c, f):
        if isinstance(c, dict):
            if f in c:
                return c[f]
            if f == "block_hash":
                # headers serialized before the field existed were written
                # by the AP in-block spec (the only one that existed then),
                # NOT the current default — see FilterConfig.from_dict
                return "ap" if c.get("block_bits", 0) else ""
            # configs serialized before a field existed (e.g. block_bits in
            # old checkpoint headers) compare as the field's default
            default = FilterConfig.__dataclass_fields__[f].default
            if default is dataclasses.MISSING:
                raise KeyError(f)
            return default
        return getattr(c, f)

    for field in fields:
        if get(a, field) != get(b, field):
            return field
    return None


@dataclasses.dataclass(frozen=True)
class FilterConfig:
    """Identity + layout of one bloom filter.

    Attributes:
      m: number of bits in the filter. Powers of two use the 64-bit position
        path (supports m up to 2^36); non-powers-of-two must be < 2^31 and
        use the 32-bit path. See ``tpubloom.ops.hashing`` for the exact spec.
      k: number of hash positions per key.
      seed: u32 seed for the hash family.
      key_len: maximum key length in bytes; keys are zero-padded to this
        length on device. Must be a multiple of 4.
      key_policy: what to do with keys longer than ``key_len``:
        ``"error"`` (default) or ``"digest"`` (replace by a 16-byte BLAKE2b
        digest on the host before packing).
      counting: counting-filter variant (4-bit counters, supports delete).
      shards: number of device shards for the sharded filter array
        (1 = single device). m must be divisible by shards*32.
      key_name: checkpoint namespace (mirrors the reference's Redis key name).
      checkpoint_every: insert count between automatic async checkpoints
        (0 = never).
      block_bits: 0 = flat layout (the reference-compatible position spec);
        a power of two in [128, 4096] selects the *blocked* layout, where all
        k bits of a key land in one block_bits-sized block (cache-line bloom
        filter, Putze et al. 2007). Blocked trades a slightly higher FPR at
        high fill for ~k× fewer random HBM accesses — the throughput layout.
        Positions follow the blocked spec in ``tpubloom.ops.blocked``;
        blocked filters are NOT bit-compatible with flat ones.
      insert_path: blocked-insert implementation: ``"auto"`` (default)
        picks the Pallas partition-sweep kernel on TPU when the shape
        qualifies and the sorted-scatter XLA path otherwise; ``"sweep"``
        / ``"scatter"`` force one. Not part of the filter's identity —
        both paths produce bit-identical arrays.
      query_path: blocked-membership implementation: ``"auto"`` (default)
        picks the read-only Pallas query sweep on TPU when the shape
        qualifies (``tpubloom.ops.sweep.choose_fat_query_params``) and
        the row-gather XLA path otherwise; ``"sweep"`` / ``"gather"``
        force one. Not part of the filter's identity — both paths
        answer bit-identical verdicts (reads never change the array).
      block_hash: in-block position derivation for the blocked layout
        (part of the filter's identity). ``"chunk"`` (the default when it
        fits) slices each position from disjoint bit ranges of the
        (h_b, g_a, g_b) 96-bit hash pool — positions are i.i.d. uniform.
        ``"ap"`` is the legacy arithmetic-progression walk
        ``(g_a + i*(g_b|1)) mod block_bits``, whose position sets form a
        tiny 2-parameter family: two same-block keys colliding in
        (g_a mod b, g_b mod b) share ALL positions, which puts a measured
        FPR floor of ~4*load/block_bits^2 under every blocked filter
        (see params.blocked_fpr). ``"auto"`` resolves to "chunk" when
        k*log2(in-block positions) <= 96, else "ap". Flat layouts carry
        ``""``. Checkpoint headers written before this field existed
        restore as "ap" (the spec they were built with).
    """

    m: int
    k: int
    seed: int = DEFAULT_SEED
    key_len: int = 16
    key_policy: str = "error"
    counting: bool = False
    shards: int = 1
    key_name: str = "tpubloom"
    checkpoint_every: int = 0
    block_bits: int = 0
    insert_path: str = "auto"
    query_path: str = "auto"
    block_hash: str = "auto"
    #: Filter kind (ISSUE 19): "bloom" (the whole pre-existing family),
    #: "cuckoo" (m = fingerprint slots, k = candidate buckets per key),
    #: "cms" (m = row width in counters, k = rows), or "topk" (a CMS that
    #: additionally maintains a host-side top-`topk` heavy-hitter heap).
    #: Part of the filter's identity — storage layouts are incompatible.
    kind: str = "bloom"
    #: Heavy-hitter heap size; required > 0 for kind="topk", 0 otherwise.
    topk: int = 0

    def __post_init__(self) -> None:
        if self.kind not in FILTER_KINDS:
            raise ValueError(f"kind must be one of {FILTER_KINDS}, got {self.kind!r}")
        if self.kind != "bloom":
            # sketch kinds own their storage layout; the bloom-family
            # layout options are meaningless (and unimplemented) for them
            if self.counting or self.block_bits or self.shards != 1:
                raise ValueError(
                    f"kind={self.kind!r} does not combine with counting/"
                    "block_bits/shards — those are bloom-family layouts"
                )
            if self.kind == "cuckoo" and not (self.m & (self.m - 1)) == 0:
                raise ValueError(
                    f"cuckoo filters need a power-of-two slot count m, got {self.m}"
                )
        if self.kind == "topk":
            if self.topk <= 0:
                raise ValueError("kind='topk' requires topk > 0")
        elif self.topk:
            raise ValueError(f"topk is only meaningful for kind='topk', got {self.topk}")
        if self.m <= 0:
            raise ValueError(f"m must be positive, got {self.m}")
        if not self.m_is_pow2 and self.m >= (1 << 31):
            raise ValueError(
                f"non-power-of-two m must be < 2^31 (32-bit position path), got {self.m}"
            )
        if self.m_is_pow2 and self.m > (1 << 36):
            # word indices are int32: pos >> 5 must stay < 2^31 (see
            # hashing.split_word_bit), so 2^36 bits is the single-array cap.
            raise ValueError(f"m must be <= 2^36, got {self.m}")
        if not (1 <= self.k <= 64):
            raise ValueError(f"k must be in [1, 64], got {self.k}")
        if self.key_len <= 0 or self.key_len % 4 != 0:
            raise ValueError(f"key_len must be a positive multiple of 4, got {self.key_len}")
        if self.key_policy not in ("error", "digest"):
            raise ValueError(f"key_policy must be 'error' or 'digest', got {self.key_policy}")
        if not (0 <= self.seed < (1 << 32)):
            raise ValueError(f"seed must be a u32, got {self.seed}")
        if self.shards < 1:
            raise ValueError(f"shards must be >= 1, got {self.shards}")
        if self.m % (self.shards * 32) != 0:
            raise ValueError(
                f"m ({self.m}) must be divisible by shards*32 ({self.shards * 32})"
            )
        if self.counting and self.m % 8 != 0:
            raise ValueError(f"counting filters need m divisible by 8, got {self.m}")
        if self.insert_path not in ("auto", "sweep", "scatter"):
            raise ValueError(
                f"insert_path must be auto/sweep/scatter, got {self.insert_path}"
            )
        if self.query_path not in ("auto", "sweep", "gather"):
            raise ValueError(
                f"query_path must be auto/sweep/gather, got {self.query_path}"
            )
        if self.block_bits:
            bb = self.block_bits
            if bb & (bb - 1) or not (128 <= bb <= 4096):
                raise ValueError(
                    f"block_bits must be a power of two in [128, 4096], got {bb}"
                )
            if not self.m_is_pow2:
                raise ValueError("blocked layout requires power-of-two m")
            if self.counting:
                # blocked counting: a block_bits-bit block holds
                # block_bits/4 counters; m counts COUNTERS (as in the
                # flat counting layout) and must be < 2^31 (positions
                # flatten to blk * counters_per_block + c for the flat
                # counting kernels / oracle)
                if self.m < bb // 4:
                    raise ValueError(
                        f"m ({self.m}) must be >= counters per block ({bb // 4})"
                    )
                if self.m % (self.shards * (bb // 4)) != 0:
                    raise ValueError(
                        f"m ({self.m}) must be divisible by "
                        f"shards*counters_per_block ({self.shards * (bb // 4)})"
                    )
            else:
                if self.m < bb:
                    raise ValueError(
                        f"m ({self.m}) must be >= block_bits ({bb})"
                    )
                if self.m % (self.shards * bb) != 0:
                    raise ValueError(
                        f"m ({self.m}) must be divisible by shards*block_bits "
                        f"({self.shards * bb})"
                    )
        # resolve/validate the in-block hash spec (identity field)
        if self.block_bits:
            domain = self.block_bits // 4 if self.counting else self.block_bits
            nb = (domain - 1).bit_length()
            fits = self.k * nb <= 96  # the (h_b, g_a, g_b) pool
            bh = self.block_hash
            if bh == "auto":
                bh = "chunk" if fits else "ap"
                object.__setattr__(self, "block_hash", bh)
            if self.block_hash not in ("chunk", "ap"):
                raise ValueError(
                    f"block_hash must be auto/chunk/ap, got {self.block_hash!r}"
                )
            if self.block_hash == "chunk" and not fits:
                raise ValueError(
                    f"block_hash='chunk' needs k*log2(in-block positions) <= 96 "
                    f"(k={self.k}, {nb} bits/position) — use 'ap'"
                )
        else:
            if self.block_hash not in ("", "auto"):
                raise ValueError(
                    "block_hash is only meaningful for blocked layouts "
                    f"(block_bits=0), got {self.block_hash!r}"
                )
            object.__setattr__(self, "block_hash", "")

    # -- derived layout ----------------------------------------------------

    @property
    def m_is_pow2(self) -> bool:
        return (self.m & (self.m - 1)) == 0

    @property
    def log2_m(self) -> int:
        if not self.m_is_pow2:
            raise ValueError("log2_m only defined for power-of-two m")
        return self.m.bit_length() - 1

    @property
    def n_words(self) -> int:
        """uint32 words in the packed bit array (plain filter)."""
        return (self.m + 31) // 32

    @property
    def n_counter_words(self) -> int:
        """uint32 words in the packed 4-bit counter array (counting filter)."""
        return (self.m + 7) // 8

    @property
    def counters_per_block(self) -> int:
        """4-bit counters per block (blocked counting layout)."""
        if not self.block_bits or not self.counting:
            raise ValueError(
                "counters_per_block is only defined for blocked counting layouts"
            )
        return self.block_bits // 4

    @property
    def n_blocks(self) -> int:
        """Number of blocks (blocked layout only). For blocked counting
        filters m counts counters, so a block covers block_bits/4 of them."""
        if not self.block_bits:
            raise ValueError("n_blocks is only defined for blocked layouts")
        if self.counting:
            return self.m // self.counters_per_block
        return self.m // self.block_bits

    @property
    def n_blocks_per_shard(self) -> int:
        return self.n_blocks // self.shards

    @property
    def words_per_block(self) -> int:
        if not self.block_bits:
            raise ValueError("words_per_block is only defined for blocked layouts")
        return self.block_bits // 32

    @property
    def n_words_per_shard(self) -> int:
        return self.n_words // self.shards

    @property
    def m_per_shard(self) -> int:
        return self.m // self.shards

    # -- constructors -------------------------------------------------------

    @classmethod
    def from_capacity(
        cls,
        capacity: int,
        error_rate: float,
        *,
        pow2_m: bool = True,
        **kwargs,
    ) -> "FilterConfig":
        """Reference-style sizing: give capacity + error rate, get a filter.

        ``pow2_m=True`` (default) rounds m up to a power of two — strictly
        more bits, so the configured error rate stays an upper bound — which
        enables the fast device path (mask instead of mod) and arbitrary m.
        """
        m, k = optimal_m_k(capacity, error_rate)
        if pow2_m:
            m = round_up_pow2(m)
        else:
            m = ((m + 31) // 32) * 32  # keep the packed array whole-word
        return cls(m=m, k=k, **kwargs)

    def replace(self, **kwargs) -> "FilterConfig":
        if "block_bits" in kwargs and "block_hash" not in kwargs:
            # crossing the flat<->blocked boundary invalidates the resolved
            # in-block spec ("" <-> chunk/ap); re-resolve from "auto"
            if bool(kwargs["block_bits"]) != bool(self.block_bits):
                kwargs["block_hash"] = "auto"
        return dataclasses.replace(self, **kwargs)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "FilterConfig":
        if d.get("block_bits") and "block_hash" not in d:
            # serialized before the field existed == built with the AP spec
            d = dict(d, block_hash="ap")
        return cls(**d)
