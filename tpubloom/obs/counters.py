"""Process-global event counters for layers below the server.

Kernel-selection and other library-level code has no handle on a
``BloomService`` (it may run in a bare-library process), so events that
must be visible in ``/metrics`` — e.g. a Pallas geometry probe demoting
the process to the scatter path — land here. The exposition layer merges
these with the server's per-RPC counters; ``Stats`` RPC snapshots include
them under ``process_counters``.
"""

from __future__ import annotations

import threading
from collections import defaultdict

_lock = threading.Lock()
_counters: dict[str, int] = defaultdict(int)


def incr(name: str, n: int = 1) -> None:
    with _lock:
        _counters[name] += n


def get(name: str) -> int:
    with _lock:
        return _counters.get(name, 0)


def global_counters() -> dict[str, int]:
    """Snapshot copy of all process-global counters."""
    with _lock:
        return dict(_counters)


def reset_for_tests() -> None:
    """Zero everything — test isolation only."""
    with _lock:
        _counters.clear()
