"""Process-global event counters for layers below the server.

Kernel-selection and other library-level code has no handle on a
``BloomService`` (it may run in a bare-library process), so events that
must be visible in ``/metrics`` — e.g. a Pallas geometry probe demoting
the process to the scatter path — land here. The exposition layer merges
these with the server's per-RPC counters; ``Stats`` RPC snapshots include
them under ``process_counters``.
"""

from __future__ import annotations

from collections import defaultdict

from tpubloom.utils import locks

_lock = locks.named_lock("obs.counters")
_counters: dict[str, int] = defaultdict(int)
_gauges: dict[str, float] = {}


def incr(name: str, n: int = 1) -> None:
    with _lock:
        _counters[name] += n


def get(name: str) -> int:
    with _lock:
        return _counters.get(name, 0)


def global_counters() -> dict[str, int]:
    """Snapshot copy of all process-global counters."""
    with _lock:
        return dict(_counters)


def set_gauge(name: str, value: float) -> None:
    """Process-global gauge (last-write-wins): library-level state that is
    a level, not an event — e.g. the client circuit-breaker state."""
    with _lock:
        _gauges[name] = value


def get_gauge(name: str, default: float = 0.0) -> float:
    with _lock:
        return _gauges.get(name, default)


def global_gauges() -> dict[str, float]:
    """Snapshot copy of all process-global gauges."""
    with _lock:
        return dict(_gauges)


def reset_for_tests() -> None:
    """Zero everything — test isolation only."""
    with _lock:
        _counters.clear()
        _gauges.clear()
