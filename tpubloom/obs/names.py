"""Central catalog of every counter/gauge name the process emits.

Before ISSUE 6 the metric vocabulary lived wherever the ``incr``/
``set_gauge``/``metrics.count`` call sites happened to be — a typo'd
name minted a brand-new series nobody's dashboards watched, and a
renamed one silently orphaned the old series. This catalog is the
single declaration point: ``python -m tpubloom.analysis.lint`` verifies
that every literal metric name used anywhere in ``tpubloom/`` is
declared here EXACTLY ONCE (and in the right kind), and that every
declared name is actually emitted somewhere — so the catalog can't rot
into wishful documentation.

Names built at runtime (per-fault, per-method, per-replica series)
can't be checked literal-by-literal; their shapes are declared in
:data:`DYNAMIC_PREFIXES` so the exposition layer and dashboards still
have one place to look.

Declaration rules the lint enforces:

* a name appears in exactly one of :data:`COUNTERS` / :data:`GAUGES`;
* every literal first argument to ``counters.incr``, ``metrics.count``
  (counter kind) or ``counters.set_gauge`` (gauge kind) in
  ``tpubloom/`` is declared under that kind;
* every declared name has at least one emit site in ``tpubloom/``.
"""

from __future__ import annotations

#: Monotone event counts (rendered as Prometheus ``counter``).
COUNTERS = (
    "blackbox_records_dropped",
    "blackbox_records_written",
    "breaker_closed",
    "breaker_opened",
    "ckpt_corrupt_detected",
    "ckpt_quarantine_evicted",
    "ckpt_restore_read_errors",
    "client_ask_redirects",
    "client_moved_redirects",
    "client_primary_redirects",
    "client_replica_fallbacks",
    "client_slot_refreshes",
    "client_topology_pushes",
    "client_topology_refreshes",
    "cluster_ask_redirects",
    "cluster_filters_migrated",
    "cluster_forward_dups",
    "cluster_forward_entries_expired",
    "cluster_forward_failures",
    "cluster_forwards",
    "cluster_migrate_installs",
    "cluster_migrate_snapshots_sent",
    "cluster_migrate_tail_records",
    "cluster_migrations_completed",
    "cluster_moved_redirects",
    "cms_keys_incremented",
    "cuckoo_full_rejections",
    "cuckoo_kicks_total",
    "delete_dedup_hits",
    "faults_injected",
    "filters_created",
    "flight_dumps_written",
    "flight_events_recorded",
    "geometry_probe_compiles",
    "geometry_probe_demotions",
    "ha_demotions",
    "ha_promotions",
    "ha_role_transitions",
    "ingest_clear_flushes",
    "ingest_delete_flushes",
    "ingest_fallback_direct",
    "ingest_flushes",
    "ingest_fused_flushes",
    "ingest_keys_coalesced",
    "ingest_plain_flushes",
    "ingest_query_flushes",
    "ingest_requests_coalesced",
    "ingest_split_flushes",
    "insert_dedup_hits",
    "keys_deleted",
    "keys_inserted",
    "keys_queried",
    "log_failstop_rejected",
    "monitor_events_dropped",
    "query_gather_launches",
    "query_sweep_launches",
    "quorum_stale_acks",
    "quorum_write_failures",
    "quorum_writes_acked",
    "readonly_rejected",
    "repl_ack_decode_errors",
    "repl_ack_stream_reopened",
    "repl_acks_dropped",
    "repl_acks_received",
    "repl_acks_sent",
    "repl_batched_frames_received",
    "repl_bootstrap_partial_resyncs",
    "repl_full_resyncs",
    "repl_log_append_errors",
    "repl_log_corrupt_dropped",
    "repl_log_torn_tail_truncated",
    "repl_log_truncations",
    "repl_partial_resyncs",
    "repl_reconnects",
    "repl_records_applied",
    "repl_records_reappended",
    "repl_records_skipped",
    "repl_records_streamed",
    "repl_replay_applied",
    "repl_snapshots_installed",
    "repl_stream_batched_bytes_raw",
    "repl_stream_batched_bytes_wire",
    "repl_stream_batched_frames",
    "repl_stream_cut_identity_rotated",
    "requests_shed",
    "restores_with_corrupt_generations",
    "sentinel_failovers",
    "sentinel_failovers_adopted",
    "sentinel_fenced",
    "sentinel_odown_agreed",
    "sentinel_sdown_entered",
    "sentinel_topology_pushes",
    "sentinel_votes_granted",
    "stale_epoch_rejected",
    "storage_evictions_total",
    "storage_hydrations_shed",
    "storage_hydrations_total",
    "storage_warm_demotions",
    "stream_acks_total",
    "stream_credit_shrinks",
    "stream_credit_throttles",
    "stream_frame_dedup_hits",
    "stream_frames_total",
    "topk_heap_updates",
    "trace_requests_sampled",
    "trace_spans_recorded",
)

#: Last-write-wins levels (rendered as Prometheus ``gauge``).
GAUGES = (
    "client_breaker_state",
    "cluster_config_epoch",
    "cluster_slots_importing",
    "cluster_slots_migrating",
    "cluster_slots_owned",
    "ha_epoch",
    "ha_role",
    "ingest_parked_current",
    "monitor_subscribers",
    "repl_connected_replicas",
    "repl_lag_seconds",
    "repl_lag_seq",
    "repl_log_bytes",
    "repl_log_segments",
    "repl_log_seq",
    "repl_max_replica_lag_seq",
    "retry_after_ms_current",
    "sentinel_known_replicas",
    "sentinel_last_election_votes",
    "sentinel_sdown",
    "storage_cold_filters",
    "storage_resident_bytes",
    "storage_resident_filters",
    "storage_warm_bytes",
    "storage_warm_filters",
    "stream_connected_current",
    "trace_buffer_spans",
    "wait_blocked_current",
)

#: Per-request phase spans (ISSUE 13 — the counter-registry pattern
#: extended to the phase vocabulary). Every literal name passed to
#: ``obs.phase(...)`` / ``ctx.add_phase(...)`` must be declared here;
#: the lint's ``phase-registry`` check closes both directions so the
#: slowlog, ``bench.py``'s ``e2e_phases`` tail and the per-phase
#: latency histograms keep naming the same stages. Semantics are
#: documented where the spans are minted: :mod:`tpubloom.obs.context`.
PHASES = (
    "decode",
    "host_prep",
    "h2d",
    "kernel",
    "kernel_query",
    "d2h",
    "encode",
)

#: Phase names minted at runtime, prefix-declared like the metric
#: DYNAMIC_PREFIXES below: the pattern and where it comes from.
PHASE_DYNAMIC_PREFIXES = (
    ("kernel_shard", "per-device mesh-launch completion phases "
     "(tpubloom.parallel.sharded, ROADMAP 1(c)) — kernel_shard<i> is "
     "the time from fence start to device i's completion; the first "
     "jump names the straggler"),
)

#: Distributed-tracing span vocabulary (ISSUE 15 — the phase-registry
#: pattern extended to spans). Every literal name passed to
#: ``trace.span(...)`` / ``trace.record_span(...)`` must be declared
#: here; the lint's ``trace-registry`` check closes both directions so
#: ``TraceGet`` trees, the ``/trace`` view and dashboards keep naming
#: the same stages. Semantics:
#:
#: * ``client.hop``      — one client-side RPC attempt window (Python
#:   ``BloomClient._rpc`` incl. every cluster MOVED/ASK hop and
#:   migration re-drive; attrs name the method + dialed address)
#: * ``ingest.park``     — a request waiting in the coalescer's queue
#:   for its flush to complete (child of the request's root span)
#: * ``ingest.flush``    — ONE coalesced flush (its own trace id;
#:   ``links`` name every parked request's root span, so N-to-1
#:   batching stays explainable; kernel phases + the barrier are its
#:   children)
#: * ``ingest.stream_recv`` — one streamed data frame's receive-and-
#:   park window on the bidi ingest plane (ISSUE 18): decode through
#:   park (or inline direct-path completion), under the FRAME's rid so
#:   the flush's links still resolve; attrs carry method/seq/parked
#: * ``barrier.wait``    — the synchronous-replication commit barrier
#:   (direct path: child of the request; coalesced: child of the flush)
#: * ``cluster.forward`` — a migration dual-write forward to the slot's
#:   import target
#: * ``repl.apply``      — a replica applying one op-log record, stamped
#:   with the ORIGIN rid (attrs carry seq/method/filter)
#: * ``storage.hydrate`` / ``storage.evict`` — tenant paging transitions
#:   on the faulting request's path (ISSUE 14)
#: * ``sentinel.vote_down`` / ``sentinel.promote`` /
#:   ``sentinel.topology`` — one failover election's RPCs (ISSUE 16
#:   satellite): the leading sentinel records a span per peer vote
#:   request, per Promote attempt and per AnnounceTopology push, all
#:   under one election trace id (``Sentinel.last_election_rid``), so
#:   an election is traceable span-by-span, not just as one flight
#:   event. Spilled to the black box — elections are crash forensics
#:   by definition.
#:
#: ``client.call`` is deliberately ABSENT from this registry: it is the
#: synthetic shared root ``trace.assemble`` fabricates client-side so a
#: multi-hop MOVED/ASK/re-drive call renders as one tree — it is never
#: emitted into any ring, so it has no emit site to close over.
SPANS = (
    "client.hop",
    "ingest.park",
    "ingest.flush",
    "ingest.stream_recv",
    "barrier.wait",
    "cluster.forward",
    "repl.apply",
    "storage.hydrate",
    "storage.evict",
    "sentinel.vote_down",
    "sentinel.promote",
    "sentinel.topology",
)

#: Span names minted at runtime, prefix-declared like the phase/metric
#: dynamic prefixes: the pattern and where it comes from.
SPAN_DYNAMIC_PREFIXES = (
    ("rpc.", "per-RPC server root spans — rpc.<Method> is the whole "
     "handler window (tpubloom.obs.trace.finish_request; attrs carry "
     "filter/slot/batch/seq/verdict code)"),
    ("phase.", "the obs.context phase timers promoted to child spans "
     "— phase.<name> for every name in PHASES/PHASE_DYNAMIC_PREFIXES "
     "(tpubloom.obs.trace.commit_children)"),
)

#: Flight-recorder event vocabulary (ISSUE 15): the lifecycle events
#: ``tpubloom.obs.flight.note`` records — rare, structured, dumped to
#: JSON on SIGTERM / fatal / DEGRADED-flip / on demand. Same
#: trace-registry closure as SPANS.
#:
#: * ``shed``           — an admission or hydration-quota shed
#: * ``breaker``        — a client circuit-breaker state flip
#: * ``role_change``    — promotion / demotion (attrs: role, epoch)
#: * ``election``       — a sentinel failover election completed
#: * ``migration``      — a slot migration started / finalized
#: * ``eviction``       — the storage tier paged a tenant out
#: * ``health``         — the Health status flipped (attrs: status,
#:   reasons) — the DEGRADED flip also triggers a dump
#: * ``oplog_failstop`` — an op-log append error fail-stopped writes
#:   (also triggers a dump: this is the "fatal" case)
#: * ``drain``          — SIGTERM/SIGINT drain began (dump follows)
#: * ``boot``           — the process came up (attrs: role, epoch,
#:   addr) — an aircraft recorder logs power-on; with the black box
#:   (ISSUE 16) every state dir's ring carries at least this, so a
#:   post-mortem can anchor "which process wrote these final events"
#: * ``stream``         — a bidi ingest stream's lifecycle (ISSUE 19
#:   satellite): ``phase=connect`` on open, ``phase=kill`` when the
#:   transport/fault path breaks the stream mid-flight, and
#:   ``phase=replay`` when a reconnected client's re-sent frame is
#:   answered from the rid-dedup cache — the three beats a post-mortem
#:   needs to see exactly-once replay actually happen
EVENTS = (
    "shed",
    "breaker",
    "role_change",
    "election",
    "migration",
    "eviction",
    "health",
    "oplog_failstop",
    "drain",
    "boot",
    "stream",
)

#: Shapes of names minted at runtime (not literal-checkable): the
#: pattern, its kind, and where it comes from.
DYNAMIC_PREFIXES = (
    ("fault_", "counter", "per-point injection counts (tpubloom.faults)"),
    ("stream_", "counter", "per-streaming-RPC open counts (service wrapper)"),
    ("cluster_slot_keys_total_", "counter",
     "per-slot key traffic on keyed RPCs (service wrapper, cluster "
     "mode) — the load signal slot rebalancing should follow"),
)

COUNTER_SET = frozenset(COUNTERS)
GAUGE_SET = frozenset(GAUGES)
