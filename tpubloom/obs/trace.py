"""Distributed request tracing (ISSUE 15) — follow one rid everywhere.

The stack spans cluster hops, coalesced flushes, commit barriers,
replica appliers and storage hydrations, but until this module the
observability story was per-node and per-phase: a slow write was a rid
in one node's slowlog plus disconnected histograms. This is the
Dapper-style span model adapted to the rid machinery the repo already
has:

* **trace_id = the client rid.** Every hop of one logical call already
  shares a rid (retries, MOVED/ASK follow-ups, migration re-drives,
  op-log records, the dedup cache) — so the rid IS the trace id, and no
  new correlation token crosses the wire.
* **spans** are plain dicts ``{rid, span, parent, name, start,
  duration_s, attrs, links}`` — msgpack-ready for the ``TraceGet`` RPC
  and JSON-ready for the ``/trace?rid=`` HTTP view. Names come from the
  declared vocabulary in :data:`tpubloom.obs.names.SPANS` /
  :data:`tpubloom.obs.names.SPAN_DYNAMIC_PREFIXES` (the lint's
  ``trace-registry`` check closes both directions, exactly like
  ``phase-registry``).
* **links** make N-to-1 batching explainable: the ingest coalescer's
  flush span carries ``links=[{rid, span}, ...]`` naming every parked
  request it merged, and the ring indexes the reverse direction — so
  ``TraceGet(rid)`` returns the request's own spans PLUS any flush span
  that linked it PLUS that flush trace's children (kernel phases,
  barrier) and, assembled cross-node, the replica applies of the merged
  record.

Sampling (the ``--trace-sample`` knob):

* ``configure(sample=None)`` (the default) is **fully off**: request
  contexts carry no event buffer, clients stamp no wire field, every
  helper is a truthy-check no-op — the hot path pays nothing.
* ``configure(sample=R)`` arms the ring. The per-rid decision is
  **deterministic** (``crc32(rid)/2^32 < R``), so every node that sees
  the same rid — server, replicas, migration targets — makes the SAME
  decision with no coordination and no extra wire bytes.
* a request may force capture via the wire field ``trace = {"forced":
  true, "span": <parent span id>}`` (what a sampled client stamps, and
  what the coalescer stamps into merged op-log records so replicas
  capture the apply regardless of their own rate), and
  **slowlog-worthy requests are always captured** when the ring is
  armed — the tail you would chase in SLOWLOG always has its tree.

Per-request child spans ride the existing :mod:`tpubloom.obs.context`
machinery for free: when the ring is armed, phase timers also append
``(name, start, duration)`` events to the thread-local context, and
:func:`finish_request` commits them as ``phase.<name>`` children of the
request's root ``rpc.<Method>`` span. :func:`span` is the explicit
context-manager twin for non-phase children (``storage.hydrate``,
``barrier.wait``, ``cluster.forward``...). Both are lock-free appends —
the ring's own lock (``obs.trace``) is only taken at commit time, on
paths that hold no other lock, so tracing adds no lock-order edges.
"""

from __future__ import annotations

import contextlib
import random
import time
import zlib
from collections import OrderedDict
from typing import Iterator, Optional

from tpubloom.obs import blackbox as obs_blackbox
from tpubloom.obs import context as obs_context
from tpubloom.obs import counters as obs_counters
from tpubloom.utils import locks

#: None = tracing fully off (the default); a float in [0, 1] arms the
#: ring at that deterministic per-rid sample rate (0.0 = capture only
#: forced and slowlog-worthy requests).
_sample: Optional[float] = None

#: Bounded per-node span buffer (total spans across traces).
DEFAULT_CAPACITY_SPANS = 4096


def new_span_id() -> str:
    """8-hex span id; collision-safe within one trace."""
    return "%08x" % random.getrandbits(32)


class TraceRing:
    """Bounded per-node ring of spans, indexed by trace id and by the
    rids a span LINKS (the flush-span reverse index). Oldest trace
    evicted first once the total span budget is exceeded."""

    def __init__(self, max_spans: int = DEFAULT_CAPACITY_SPANS):
        self.max_spans = int(max_spans)
        self._lock = locks.named_lock("obs.trace")
        #: trace id -> [span dicts], insertion-ordered for eviction
        self._traces: "OrderedDict[str, list]" = OrderedDict()
        #: linked rid -> {trace ids whose spans link it}
        self._links: dict = {}
        self._nspans = 0

    def record(self, span: dict) -> None:
        with self._lock:
            tid = span["rid"]
            lst = self._traces.get(tid)
            if lst is None:
                lst = self._traces[tid] = []
            lst.append(span)
            self._nspans += 1
            for link in span.get("links") or ():
                lr = link.get("rid")
                if lr:
                    self._links.setdefault(lr, set()).add(tid)
            while self._nspans > self.max_spans:
                if len(self._traces) > 1:
                    _, evicted = self._traces.popitem(last=False)
                else:
                    # a single trace id over the whole budget (a caller
                    # reusing one rid across many forced calls) must
                    # still be bounded: trim its oldest spans. The link
                    # index drops the trimmed spans' entries — a
                    # surviving same-trace span linking the same rid
                    # loses its reverse index, acceptable for this
                    # pathological shape
                    only = next(iter(self._traces.values()))
                    excess = self._nspans - self.max_spans
                    evicted = only[:excess]
                    del only[:excess]
                self._nspans -= len(evicted)
                for s in evicted:
                    for link in s.get("links") or ():
                        tids = self._links.get(link.get("rid"))
                        if tids is not None:
                            tids.discard(s["rid"])
                            if not tids:
                                self._links.pop(link.get("rid"), None)
            nspans = self._nspans
        # counters OUTSIDE the ring lock: obs.trace stays edge-free
        obs_counters.incr("trace_spans_recorded")
        obs_counters.set_gauge("trace_buffer_spans", float(nspans))

    def get(self, rid: str, follow_links: bool = True) -> list:
        """Spans of ``rid``'s trace, plus (one link hop) every trace
        holding a span that LINKS ``rid`` — the coalescer's flush trace
        with its kernel-phase/barrier children rides along."""
        with self._lock:
            out = [dict(s) for s in self._traces.get(rid, ())]
            if follow_links:
                for tid in sorted(self._links.get(rid, ())):
                    if tid != rid:
                        out.extend(dict(s) for s in self._traces.get(tid, ()))
        return out

    def stats(self) -> dict:
        with self._lock:
            return {"spans": self._nspans, "traces": len(self._traces)}

    def clear(self) -> None:
        with self._lock:
            self._traces.clear()
            self._links.clear()
            self._nspans = 0


_ring = TraceRing()


def configure(
    sample: Optional[float], capacity: Optional[int] = None
) -> None:
    """Arm (or disarm, ``sample=None``) process-wide tracing. Arming
    also turns on per-request child-event capture in
    :mod:`tpubloom.obs.context` (disarmed contexts carry no buffer)."""
    global _sample
    _sample = None if sample is None else max(0.0, min(1.0, float(sample)))
    if capacity is not None:
        _ring.max_spans = int(capacity)
    obs_context.set_trace_capture(_sample is not None)


def ensure_enabled() -> None:
    """Arm the ring at sample 0.0 iff currently off — what a traced
    CLIENT needs (it forces capture per call by its own rate and must
    never lower a rate the server half of the process configured)."""
    if _sample is None:
        configure(0.0)


def enabled() -> bool:
    return _sample is not None


def sample_rate() -> Optional[float]:
    return _sample


def hit(rid: str, rate: Optional[float] = None) -> bool:
    """Deterministic per-rid sampling decision — the same everywhere a
    rid travels, with no coordination (crc32 is stable across processes
    and platforms)."""
    r = _sample if rate is None else rate
    if not r:
        return False
    if r >= 1.0:
        return True
    h = zlib.crc32(rid.encode("utf-8", "replace")) & 0xFFFFFFFF
    return h / 2**32 < r


def record_span(
    name: str,
    *,
    rid: str,
    start: float,
    duration_s: float,
    span: Optional[str] = None,
    parent: Optional[str] = None,
    attrs: Optional[dict] = None,
    links: Optional[list] = None,
    spill: bool = False,
) -> str:
    """Record one finished span into the ring (no-op when tracing is
    off); returns the span id. ``attrs`` values must be msgpack-safe
    scalars (the caller casts). ``spill=True`` (forced or slowlog-
    worthy spans — ISSUE 16) additionally writes the span through to
    the crash-forensics black box's mapped trace ring, so the spans
    explaining a crash survive the crash; the spill is lock-free and a
    no-op when the black box is disarmed."""
    sid = span or new_span_id()
    if _sample is None:
        return sid
    s: dict = {
        "rid": rid,
        "span": sid,
        "parent": parent,
        "name": name,
        "start": float(start),
        "duration_s": float(duration_s),
    }
    if attrs:
        s["attrs"] = attrs
    if links:
        s["links"] = links
    _ring.record(s)
    if spill:
        obs_blackbox.spill_span(s)
    return sid


def get_trace(rid: str) -> list:
    if _sample is None or not rid:
        return []
    return _ring.get(rid)


def buffer_stats() -> dict:
    return _ring.stats()


# -- request plumbing (the obs.context integration) ---------------------------


def arm_request(rctx, *, forced: bool = False, parent=None) -> bool:
    """Decide capture for one request context (wrapper, post-decode):
    forced (the wire ``trace`` field) or the deterministic rid sample.
    Slowlog-worthy requests are additionally captured at finish even
    when this says no — see :func:`finish_request`."""
    if _sample is None:
        return False
    rctx.trace_parent = parent if isinstance(parent, str) else None
    rctx.trace_forced = bool(forced)
    if forced or hit(rctx.rid):
        rctx.trace_armed = True
        rctx.trace_span = new_span_id()
    return rctx.trace_armed


def request_armed() -> bool:
    """True when the ACTIVE request context is being captured — what
    ``_log_op`` checks to stamp ``trace={"forced": true}`` into the
    record so replicas capture the apply too."""
    ctx = obs_context.current()
    return ctx is not None and getattr(ctx, "trace_armed", False)


def request_ref() -> Optional[tuple]:
    """``(rid, root span id)`` of the active captured request, else
    None — what a parked coalescer entry remembers so the flush span
    can LINK it."""
    ctx = obs_context.current()
    if ctx is None or not getattr(ctx, "trace_armed", False):
        return None
    return (ctx.rid, ctx.trace_span)


@contextlib.contextmanager
def span(name: str, **attrs) -> Iterator[None]:
    """Explicit child span of the active request (no-op without an
    armed context): lock-free append, committed under the request's
    root span at finish."""
    ctx = obs_context.current()
    if ctx is None or ctx.trace_events is None:
        yield
        return
    w0 = time.time()
    t0 = time.perf_counter()
    try:
        yield
    finally:
        ctx.trace_events.append(
            (name, w0, time.perf_counter() - t0, attrs or None, False)
        )


def commit_children(rctx, root: str, *, spill: bool = False) -> None:
    """Commit the context's buffered child events under ``root`` —
    phase timers become ``phase.<name>`` spans, explicit spans keep
    their own names. ``spill`` rides through to :func:`record_span`
    (ISSUE 16: a forced/slowlog-worthy request's WHOLE tree goes to the
    black box, not just its root)."""
    for name, w0, dt, attrs, is_phase in rctx.trace_events or ():
        if is_phase:
            record_span(
                f"phase.{name}",
                rid=rctx.rid, parent=root, start=w0,
                duration_s=dt, attrs=attrs, spill=spill,
            )
        else:
            # explicit trace.span() children: the name was validated at
            # its own call site by the trace-registry check
            record_span(
                name,
                rid=rctx.rid, parent=root, start=w0,
                duration_s=dt, attrs=attrs, spill=spill,
            )


def finish_request(
    rctx, duration_s: float, *, attrs: Optional[dict] = None,
    slow: bool = False,
) -> Optional[str]:
    """Commit one finished request: the root ``rpc.<Method>`` span plus
    every buffered child. Captured when the request was armed OR when
    it is slowlog-worthy (``slow``) — the slow tail always traces."""
    if _sample is None:
        return None
    if not (rctx.trace_armed or slow):
        return None
    if rctx.trace_armed:
        obs_counters.incr("trace_requests_sampled")
    # black-box spill (ISSUE 16): the forced and slowlog-worthy trees
    # are exactly the ones a crash post-mortem wants on disk
    spill = slow or getattr(rctx, "trace_forced", False)
    root = rctx.trace_span or new_span_id()
    record_span(
        f"rpc.{rctx.method}",
        rid=rctx.rid,
        span=root,
        parent=rctx.trace_parent,
        start=rctx.started_at,
        duration_s=duration_s,
        attrs=attrs,
        spill=spill,
    )
    commit_children(rctx, root, spill=spill)
    return root


def assemble(spans: list, rid: Optional[str] = None) -> dict:
    """Client-side tree assembly over a merged span set: ``{span id ->
    [child span ids]}`` via parent edges AND link edges (a flush span
    adopts the requests it links as tree neighbors), plus the connected
    components — ONE component is the acceptance shape for a healthy
    single-call trace.

    With ``rid`` given (ISSUE 16 satellite, the PR-15 seam): a
    multi-hop redirect chain — MOVED/ASK follow-ups, migration
    re-drives — leaves one PARENTLESS ``client.hop`` root per hop, so
    one logical call used to assemble as a forest. When more than one
    root belongs to ``rid``'s own trace, a shared synthetic root
    (``client.call``, marked ``attrs.synthesized``) adopts them, their
    components merge, and the logical call renders as ONE tree. The
    synthetic span is returned under ``"synthetic"`` (never recorded
    into any ring — it exists only in assembled views, which is why it
    is not part of the emitted-span registry)."""
    by_id = {s["span"]: s for s in spans}
    parent: dict = {}
    neighbors: dict = {s["span"]: set() for s in spans}
    for s in spans:
        p = s.get("parent")
        if p in by_id:
            parent[s["span"]] = p
            neighbors[s["span"]].add(p)
            neighbors[p].add(s["span"])
        for link in s.get("links") or ():
            target = link.get("span")
            if target in by_id:
                neighbors[s["span"]].add(target)
                neighbors[target].add(s["span"])
    components = []
    seen: set = set()
    for sid in by_id:
        if sid in seen:
            continue
        comp, stack = set(), [sid]
        while stack:
            cur = stack.pop()
            if cur in comp:
                continue
            comp.add(cur)
            stack.extend(neighbors[cur] - comp)
        seen |= comp
        components.append(sorted(comp))
    roots = [sid for sid in by_id if sid not in parent]
    out = {"roots": roots, "components": components, "parent": parent}
    if rid is not None:
        orphans = [s for s in roots if by_id[s].get("rid") == rid]
        if len(orphans) > 1:
            synth_id = new_span_id()
            starts = [float(by_id[s].get("start") or 0.0) for s in orphans]
            ends = [
                float(by_id[s].get("start") or 0.0)
                + float(by_id[s].get("duration_s") or 0.0)
                for s in orphans
            ]
            synthetic = {
                "rid": rid,
                "span": synth_id,
                "parent": None,
                "name": "client.call",
                "start": min(starts),
                "duration_s": max(ends) - min(starts),
                "attrs": {"synthesized": True, "hops": len(orphans)},
            }
            adopted = set(orphans)
            for s in orphans:
                parent[s] = synth_id
            merged, rest = {synth_id}, []
            for comp in components:
                if adopted & set(comp):
                    merged.update(comp)
                else:
                    rest.append(comp)
            out["components"] = rest + [sorted(merged)]
            out["roots"] = [synth_id] + [s for s in roots if s not in adopted]
            out["synthetic"] = synthetic
    return out


def reset_for_tests() -> None:
    """Disarm + clear + restore the default capacity — test isolation
    only."""
    configure(None, capacity=DEFAULT_CAPACITY_SPANS)
    _ring.clear()
