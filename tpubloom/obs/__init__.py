"""Cross-layer observability subsystem.

Parity: the reference gem ships no metrics of its own — operators lean on
Redis ``INFO`` / ``SLOWLOG`` / ``MONITOR`` (SURVEY.md §5). This package is
the TPU-native replacement for that operator surface, pinned by BASELINE's
observability row: keys inserted/queried, batch sizes, kernel/request
latency, checkpoint lag, fill ratio & predicted FPR — all scrapeable,
without attaching a profiler or running bench archaeology.

Pieces (each importable on its own, stdlib-only except where noted):

* :mod:`tpubloom.obs.context` — thread-local request context + named
  phase timers (decode / host_prep / h2d / kernel / d2h / encode). The
  filter layer records phases into whatever request is active; with no
  active request every span is a no-op, so library users pay ~nothing.
* :mod:`tpubloom.obs.counters` — process-global counters for events that
  happen below the server layer (e.g. ``geometry_probe_demotions`` when a
  Pallas geometry probe demotes to scatter), merged into ``/metrics``.
* :mod:`tpubloom.obs.slowlog` — Redis-SLOWLOG-parity ring of the N
  slowest requests (method, args summary, batch, duration, request id,
  phase breakdown), served by the ``SlowlogGet``/``SlowlogReset`` RPCs.
* :mod:`tpubloom.obs.exposition` — Prometheus text-format rendering of
  the server's counters, latency/phase histograms, per-filter and
  checkpoint gauges, and the global counters.
* :mod:`tpubloom.obs.httpd` — the background HTTP thread serving
  ``GET /metrics`` (plus ``/healthz``, ``/trace?rid=`` and
  ``/flight``), enabled by the server's ``--metrics-port`` flag.
* :mod:`tpubloom.obs.trace` — distributed request tracing (ISSUE 15):
  a Dapper-style span ring keyed on the client rid, behind the
  server's ``--trace-sample`` knob, served by the ``TraceGet`` RPC.
* :mod:`tpubloom.obs.flight` — the flight recorder (ISSUE 15): a
  bounded lock-free ring of lifecycle events dumped to JSON on
  SIGTERM / fatal / Health-DEGRADED flips and on demand.

Request correlation: the gRPC client stamps every request with a ``rid``
(``BloomClient.last_rid``); the server threads it into
``tracing.annotate`` spans AND the slowlog entry, so a slow request found
in SLOWLOG can be looked up by id in a Perfetto trace of the same window.
"""

from tpubloom.obs.context import (  # noqa: F401
    RequestContext,
    current,
    current_rid,
    new_rid,
    phase,
    request,
)
from tpubloom.obs.counters import global_counters, incr  # noqa: F401
from tpubloom.obs.slowlog import Slowlog, summarize_request  # noqa: F401
