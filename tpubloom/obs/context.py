"""Thread-local request context + phase timers.

The server opens a :func:`request` around every RPC; lower layers
(``filter.py`` packing/dispatch, protocol decode/encode) wrap their work
in :func:`phase` spans. Phases accumulate on the innermost active
context; with no context active a span is a no-op ``yield``, so the
library hot path outside the server pays one truthy check per span.

Phase vocabulary (DECLARED in :data:`tpubloom.obs.names.PHASES` /
:data:`tpubloom.obs.names.PHASE_DYNAMIC_PREFIXES` — the lint's
``phase-registry`` check closes both directions, so a name used here
but not declared there, or declared but never emitted, fails CI; the
semantics stay documented in this module):

* ``decode``    — wire bytes -> request dict (msgpack)
* ``host_prep`` — key packing + batch padding on the host
* ``h2d``       — staging packed arrays onto the device
* ``kernel``    — jitted MUTATING device work (dispatch + completion
  fence): inserts, deletes, fused test-and-insert
* ``kernel_query`` — jitted READ-ONLY device work (membership queries)
  — split from ``kernel`` since ISSUE 12 so the read path's device time
  is trackable on its own (the query sweep kernel is the direct lever
  on it)
* ``d2h``       — device results -> host arrays
* ``encode``    — response dict -> wire bytes

Sharded filters additionally emit ``kernel_shard<i>`` spans on the
direct (per-request) path: per-device time-to-completion of one mesh
launch, measured from the fence start (ROADMAP 1(c) — the straggler
shard is the widest span).

Under JAX async dispatch the h2d/kernel boundary is approximate (the
transfer may still be in flight when dispatch starts); the completion
fence inside ``kernel`` makes the SUM honest, which is what the
transport-bound vs code-bound triage needs.
"""

from __future__ import annotations

import contextlib
import random
import threading
import time
from typing import Iterator, Optional

_tls = threading.local()

#: Set by :func:`tpubloom.obs.trace.configure` (ISSUE 15): when the
#: trace ring is armed, fresh request contexts carry an event buffer so
#: phase timers double as child spans; disarmed (the default) they
#: carry None and the hot path pays one falsy check per phase.
_trace_capture = False


def set_trace_capture(on: bool) -> None:
    global _trace_capture
    _trace_capture = bool(on)


def new_rid() -> str:
    """16-hex-char request id; cheap, collision-safe at slowlog scale."""
    return "%016x" % random.getrandbits(64)


class RequestContext:
    """Per-request accumulator: id, batch size, phase durations — plus,
    with tracing armed, the buffered child-span events and the capture
    decision :mod:`tpubloom.obs.trace` commits at finish."""

    __slots__ = (
        "method", "rid", "batch", "summary", "phases", "started_at",
        "trace_events", "trace_armed", "trace_span", "trace_parent",
        "trace_forced",
    )

    def __init__(self, method: str, rid: Optional[str] = None):
        self.method = method
        self.rid = rid or new_rid()
        self.batch = 0
        self.summary = ""
        self.phases: dict[str, float] = {}
        self.started_at = time.time()
        #: (name, wall start, duration, attrs, is_phase) child events,
        #: or None when tracing is off (zero per-phase overhead)
        self.trace_events: Optional[list] = [] if _trace_capture else None
        self.trace_armed = False
        self.trace_span: Optional[str] = None
        self.trace_parent: Optional[str] = None
        #: the wire trace field forced capture (ISSUE 16: forced
        #: requests spill their tree to the crash-forensics black box)
        self.trace_forced = False

    def add_phase(self, name: str, seconds: float) -> None:
        # += : a phase may run more than once per request (e.g. kernel
        # twice for the query-then-insert presence fallback)
        self.phases[name] = self.phases.get(name, 0.0) + seconds


def current() -> Optional[RequestContext]:
    return getattr(_tls, "ctx", None)


def current_rid() -> Optional[str]:
    ctx = current()
    return ctx.rid if ctx is not None else None


@contextlib.contextmanager
def request(method: str, rid: Optional[str] = None) -> Iterator[RequestContext]:
    """Install a fresh RequestContext for this thread (re-entrant: the
    previous context is restored on exit, so nested server calls don't
    cross-contaminate phases)."""
    ctx = RequestContext(method, rid)
    prev = current()
    _tls.ctx = ctx
    try:
        yield ctx
    finally:
        _tls.ctx = prev


@contextlib.contextmanager
def phase(name: str) -> Iterator[None]:
    """Time a named phase into the active request context (no-op without
    one)."""
    ctx = current()
    if ctx is None:
        yield
        return
    events = ctx.trace_events
    w0 = time.time() if events is not None else 0.0
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        ctx.add_phase(name, dt)
        if events is not None:
            # ISSUE 15: the phase timer doubles as a child span —
            # committed as phase.<name> under the request's root span
            # when the request is captured (trace.commit_children)
            events.append((name, w0, dt, None, True))
