"""Thread-local request context + phase timers.

The server opens a :func:`request` around every RPC; lower layers
(``filter.py`` packing/dispatch, protocol decode/encode) wrap their work
in :func:`phase` spans. Phases accumulate on the innermost active
context; with no context active a span is a no-op ``yield``, so the
library hot path outside the server pays one truthy check per span.

Phase vocabulary (DECLARED in :data:`tpubloom.obs.names.PHASES` /
:data:`tpubloom.obs.names.PHASE_DYNAMIC_PREFIXES` — the lint's
``phase-registry`` check closes both directions, so a name used here
but not declared there, or declared but never emitted, fails CI; the
semantics stay documented in this module):

* ``decode``    — wire bytes -> request dict (msgpack)
* ``host_prep`` — key packing + batch padding on the host
* ``h2d``       — staging packed arrays onto the device
* ``kernel``    — jitted MUTATING device work (dispatch + completion
  fence): inserts, deletes, fused test-and-insert
* ``kernel_query`` — jitted READ-ONLY device work (membership queries)
  — split from ``kernel`` since ISSUE 12 so the read path's device time
  is trackable on its own (the query sweep kernel is the direct lever
  on it)
* ``d2h``       — device results -> host arrays
* ``encode``    — response dict -> wire bytes

Sharded filters additionally emit ``kernel_shard<i>`` spans on the
direct (per-request) path: per-device time-to-completion of one mesh
launch, measured from the fence start (ROADMAP 1(c) — the straggler
shard is the widest span).

Under JAX async dispatch the h2d/kernel boundary is approximate (the
transfer may still be in flight when dispatch starts); the completion
fence inside ``kernel`` makes the SUM honest, which is what the
transport-bound vs code-bound triage needs.
"""

from __future__ import annotations

import contextlib
import random
import threading
import time
from typing import Iterator, Optional

_tls = threading.local()


def new_rid() -> str:
    """16-hex-char request id; cheap, collision-safe at slowlog scale."""
    return "%016x" % random.getrandbits(64)


class RequestContext:
    """Per-request accumulator: id, batch size, phase durations."""

    __slots__ = ("method", "rid", "batch", "summary", "phases", "started_at")

    def __init__(self, method: str, rid: Optional[str] = None):
        self.method = method
        self.rid = rid or new_rid()
        self.batch = 0
        self.summary = ""
        self.phases: dict[str, float] = {}
        self.started_at = time.time()

    def add_phase(self, name: str, seconds: float) -> None:
        # += : a phase may run more than once per request (e.g. kernel
        # twice for the query-then-insert presence fallback)
        self.phases[name] = self.phases.get(name, 0.0) + seconds


def current() -> Optional[RequestContext]:
    return getattr(_tls, "ctx", None)


def current_rid() -> Optional[str]:
    ctx = current()
    return ctx.rid if ctx is not None else None


@contextlib.contextmanager
def request(method: str, rid: Optional[str] = None) -> Iterator[RequestContext]:
    """Install a fresh RequestContext for this thread (re-entrant: the
    previous context is restored on exit, so nested server calls don't
    cross-contaminate phases)."""
    ctx = RequestContext(method, rid)
    prev = current()
    _tls.ctx = ctx
    try:
        yield ctx
    finally:
        _tls.ctx = prev


@contextlib.contextmanager
def phase(name: str) -> Iterator[None]:
    """Time a named phase into the active request context (no-op without
    one)."""
    ctx = current()
    if ctx is None:
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        ctx.add_phase(name, time.perf_counter() - t0)
