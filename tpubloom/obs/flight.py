"""Flight recorder (ISSUE 15) — a bounded ring of structured lifecycle
events that survives to a JSON dump when the process is about to stop
being observable.

Post-mortem debugging of chaos failures used to depend on scraping a
LIVE ``/metrics`` endpoint: once the process died (SIGKILL mid-chaos, a
drain, an OOM) the sequence of sheds, breaker flips, role changes,
elections, migrations and evictions that led there was gone. This ring
keeps the last N lifecycle events (they are RARE — this is not a
request log) and dumps them:

* on **SIGTERM** (the server's drain handler),
* on a **fatal** write-path fail-stop (op-log append error),
* on a **Health DEGRADED flip** (SERVING -> DEGRADED),
* **on demand** — the metrics HTTP thread serves ``GET /flight`` and
  :func:`dump` is callable from anywhere.

Event kinds are DECLARED in :data:`tpubloom.obs.names.EVENTS` — the
lint's ``trace-registry`` check closes both directions, so a typo'd
kind can't silently mint an unknown series and a declared kind nobody
emits rots loudly.

The ring itself is lock-free: events append to a ``collections.deque``
(maxlen-bounded; CPython appends are atomic), and snapshots via
``list(deque)`` are consistent enough for a post-mortem artifact. The
ONE lock :func:`note` touches is the ``obs.counters`` leaf (the
``flight_events_recorded`` counter) — so a call site holding some lock
``X`` needs the ``X -> obs.counters`` edge declared in the lock-order
manifest. Every current site either holds no lock or holds one whose
counters edge is already declared (filter.op, service.promote,
client.breaker, sentinel.state); a NEW note() under a lock that never
touched counters must declare its edge or move the note outside.

Dump directory resolution: :func:`configure` (the server points it at
its state dir), else the ``TPUBLOOM_FLIGHT_DIR`` environment variable —
which is how the CI chaos shards collect every subprocess server's
dumps as one artifact without touching each test harness.

Since ISSUE 16 the ring is also DURABLE: when
:func:`tpubloom.obs.blackbox.configure` armed the crash-forensics black
box (servers do it for their state dir), every :func:`note` writes
through to an mmap'd, CRC-framed ring file that survives SIGKILL — the
deque stays as the live view (``GET /flight``, dumps), the mapped ring
is what a post-mortem reads out of a dead node. The write-through is
lock-free like the deque append, so the locking contract above is
unchanged.
"""

from __future__ import annotations

import itertools
import json
import logging
import os
import time
from collections import deque
from typing import Optional

from tpubloom.obs import blackbox as obs_blackbox
from tpubloom.obs import counters as obs_counters

log = logging.getLogger("tpubloom.obs")

#: env var naming the dump directory when no explicit configure() ran
#: (mirrors TPUBLOOM_LOCK_CHECK_DIR: CI pins it inside the workspace so
#: every subprocess server's dumps survive as artifacts)
DUMP_DIR_ENV = "TPUBLOOM_FLIGHT_DIR"

DEFAULT_CAPACITY = 512

_events: deque = deque(maxlen=DEFAULT_CAPACITY)
_dump_dir: Optional[str] = None
#: atomic dump sequence (itertools.count.__next__ is atomic in
#: CPython) — concurrent dumps (two threads hitting the fatal path at
#: once) must get distinct file AND tmp names, never interleave into
#: one
_dump_seq = itertools.count(1)


def configure(
    dump_dir: Optional[str] = None, capacity: Optional[int] = None
) -> None:
    global _events, _dump_dir
    if dump_dir is not None:
        _dump_dir = dump_dir
    if capacity is not None and capacity != _events.maxlen:
        _events = deque(_events, maxlen=int(capacity))


def note(kind: str, **attrs) -> None:
    """Record one lifecycle event. ``kind`` must be declared in
    :data:`tpubloom.obs.names.EVENTS`; ``attrs`` are JSON-safe scalars
    (the caller casts). Cheap: a lock-free deque append plus one
    ``obs.counters`` incr — see the module docstring before calling
    this under a lock the manifest has no counters edge for."""
    ev: dict = {"ts": time.time(), "kind": kind}
    if attrs:
        ev["attrs"] = attrs
    _events.append(ev)
    # crash-forensics write-through (ISSUE 16): when the black box is
    # armed, the event also lands in the mmap'd ring — still lock-free
    # (atomic seq reservation + one slice assignment), so this path
    # stays safe under every lock the docstring above names. A SIGKILL
    # now loses at most the record being copied, not the whole ring.
    obs_blackbox.note_event(ev)
    obs_counters.incr("flight_events_recorded")


def snapshot() -> list:
    """Copy of the ring, oldest first."""
    return [dict(e) for e in list(_events)]


def dump(reason: str, extra: Optional[dict] = None) -> Optional[str]:
    """Write the ring to ``flight-<pid>-<reason>-<n>.json`` in the
    configured dump dir (or ``$TPUBLOOM_FLIGHT_DIR``); returns the path
    or None when no directory is known / the write failed. Best-effort
    by design — a dump must never turn a drain into a crash."""
    directory = _dump_dir or os.environ.get(DUMP_DIR_ENV)
    if not directory:
        return None
    n = next(_dump_seq)
    path = os.path.join(
        directory, f"flight-{os.getpid()}-{reason}-{n}.json"
    )
    payload = {
        "pid": os.getpid(),
        "ts": time.time(),
        "reason": reason,
        "events": snapshot(),
    }
    if extra:
        payload["extra"] = extra
    try:
        os.makedirs(directory, exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}.{n}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(payload, f, indent=1, default=str)
        os.replace(tmp, path)
    except OSError:
        log.exception("flight-recorder dump to %s failed", path)
        return None
    obs_counters.incr("flight_dumps_written")
    return path


def reset_for_tests() -> None:
    global _dump_dir, _dump_seq
    _events.clear()
    _dump_dir = None
    _dump_seq = itertools.count(1)
