"""Cross-node metrics aggregation (ISSUE 9 satellite, open since PR 1).

``python -m tpubloom.obs.aggregate --nodes host:port,host:port,...``
fetches ``/metrics`` from every listed node's exposition endpoint
(:mod:`tpubloom.obs.httpd`) and merges them into ONE scrape target:

* every sample line gains a ``node="host:port"`` label (prepended, so
  existing labels are preserved verbatim — histogram ``le`` included);
* ``# HELP`` / ``# TYPE`` headers are kept once per metric family
  (first node wins; the fleet shares one vocabulary via
  :mod:`tpubloom.obs.names`, so headers agree);
* a synthetic ``tpubloom_aggregate_node_up{node=...} 0|1`` gauge makes
  scrape failures visible instead of silently shrinking the fleet.

Modes: ``--port N`` serves the merged view at ``/metrics`` (one scrape
target for a whole cluster — each fan-out happens per scrape, so the
view is always live); ``--once`` prints a single merged scrape to
stdout and exits (debugging, cron snapshots).

Stdlib only (urllib + the PR-1 ``MetricsServer``) — the image must not
grow dependencies.
"""

from __future__ import annotations

import argparse
import sys
import urllib.error
import urllib.request
from typing import Optional

DEFAULT_TIMEOUT_S = 5.0


def fetch_metrics(node: str, timeout: float = DEFAULT_TIMEOUT_S) -> str:
    """One node's raw exposition text (``node`` is host:port of its
    ``--metrics-port`` endpoint). Raises on any fetch problem."""
    with urllib.request.urlopen(
        f"http://{node}/metrics", timeout=timeout
    ) as resp:
        return resp.read().decode("utf-8", errors="replace")


def _label_escape(value: str) -> str:
    return value.replace("\\", "\\\\").replace("\n", "\\n").replace('"', '\\"')


def relabel(text: str, node: str) -> list:
    """Sample lines of one scrape with ``node=...`` prepended to each
    label set; comment/blank lines are returned unchanged (the caller
    dedups headers)."""
    out = []
    label = f'node="{_label_escape(node)}"'
    for line in text.splitlines():
        if not line or line.startswith("#"):
            out.append(line)
            continue
        name_part, sep, value_part = line.rpartition(" ")
        if not sep:
            out.append(line)
            continue
        if "{" in name_part:
            name, _, rest = name_part.partition("{")
            out.append(f"{name}{{{label},{rest} {value_part}")
        else:
            out.append(f"{name_part}{{{label}}} {value_part}")
    return out


def merge_scrapes(scrapes: dict) -> str:
    """``{node: exposition text | None}`` → one merged exposition body.
    ``None`` marks an unreachable node (up=0, no samples)."""
    out: list = []
    seen_headers: set = set()
    out.append(
        "# HELP tpubloom_aggregate_node_up 1 when the node's /metrics "
        "answered this scrape"
    )
    out.append("# TYPE tpubloom_aggregate_node_up gauge")
    for node in sorted(scrapes):
        up = scrapes[node] is not None
        out.append(
            f'tpubloom_aggregate_node_up{{node="{_label_escape(node)}"}} '
            f"{1 if up else 0}"
        )
    for node in sorted(scrapes):
        text = scrapes[node]
        if text is None:
            continue
        for line in relabel(text, node):
            if line.startswith("#"):
                # "# HELP <name> ..." / "# TYPE <name> ..." — keep the
                # first node's copy of each
                parts = line.split(None, 3)
                key = tuple(parts[:3])
                if key in seen_headers:
                    continue
                seen_headers.add(key)
            elif not line:
                continue
            out.append(line)
    return "\n".join(out) + "\n"


def aggregate(nodes: list, timeout: float = DEFAULT_TIMEOUT_S) -> str:
    scrapes: dict = {}
    for node in nodes:
        try:
            scrapes[node] = fetch_metrics(node, timeout=timeout)
        except (urllib.error.URLError, OSError, ValueError):
            scrapes[node] = None
    return merge_scrapes(scrapes)


def main(argv: Optional[list] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tpubloom.obs.aggregate",
        description="merge /metrics from many tpubloom nodes into one "
        "scrape target with per-node labels",
    )
    parser.add_argument(
        "--nodes", required=True,
        type=lambda s: [a for a in s.split(",") if a],
        help="comma-separated host:port of each node's --metrics-port",
    )
    parser.add_argument(
        "--port", type=int, default=9464,
        help="serve the merged view at http://0.0.0.0:PORT/metrics "
        "(default 9464; 0 = ephemeral)",
    )
    parser.add_argument(
        "--timeout", type=float, default=DEFAULT_TIMEOUT_S,
        help="per-node fetch timeout in seconds (default 5)",
    )
    parser.add_argument(
        "--once", action="store_true",
        help="print one merged scrape to stdout and exit",
    )
    args = parser.parse_args(argv)
    if args.once:
        sys.stdout.write(aggregate(args.nodes, timeout=args.timeout))
        return 0
    from tpubloom.obs.httpd import MetricsServer

    server = MetricsServer(
        lambda: aggregate(args.nodes, timeout=args.timeout), port=args.port
    )
    print(
        f"aggregating {len(args.nodes)} node(s) at "
        f"http://0.0.0.0:{server.port}/metrics",
        flush=True,
    )
    import threading

    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    finally:
        server.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
