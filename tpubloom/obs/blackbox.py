"""Crash-forensics black box (ISSUE 16) — mmap'd flight/trace rings
that survive SIGKILL, plus the fleet post-mortem CLI.

PR 15's flight recorder and trace ring die with the process: only the
dump-on-signal path (SIGTERM, fatal, DEGRADED flip) persists anything,
and the failures the chaos suite cares about most — SIGKILL
mid-migration, mid-eviction, mid-quorum — are exactly the ones that
never run a signal handler. This module is the durable layer: two
small file-backed rings in the node's state dir, written through an
``mmap`` so the KERNEL owns the dirty pages. A SIGKILL (or any process
death) loses nothing the slice assignment completed; only a machine
crash can lose unsynced pages, and the drain/fatal paths ``msync`` for
that case too.

Layout (one file per ring, fixed size, created once and reattached on
restart — the spec the README runbook documents):

* **header, 64 bytes**: ``MAGIC(8)=b"TPBBOX1\\n" | version u32le |
  slot_size u32le | nslots u32le | zeros``. Geometry is read back on
  reattach — the FILE's geometry wins over the caller's, so a restart
  with different defaults never misparses old slots.
* **slots**: ``nslots`` fixed slots of ``slot_size`` bytes; slot ``i``
  starts at ``64 + i * slot_size``. Record ``seq`` lives in slot
  ``seq % nslots`` — the ring overwrites oldest-first with no shared
  head pointer to corrupt.
* **frame** (op-log framing discipline, :mod:`tpubloom.repl.record`):
  ``FMAGIC(4)=b"TBBR" | seq u64le | body_len u32le | crc32c u32le |
  msgpack body``. The CRC covers ``seq || body_len || body`` — every
  byte of the frame is checksummed, so a record torn by a kill mid-copy
  (or a flipped byte anywhere in it) is *whole or skipped*, never
  misread. ``body`` is a msgpack map ``{"k": "meta"|"ev"|"span", "ts",
  "ep", ...}`` — ``ep`` is the writer's topology epoch at write time,
  which is what lets the CLI merge rings from different nodes into one
  epoch-then-wall-clock fleet timeline.

Writes are **lock-free**, by construction rather than by luck — the
:func:`tpubloom.obs.flight.note` path this rides is called under
``filter.op`` / ``service.promote`` / ``client.breaker`` /
``sentinel.state`` locks and is documented lock-free, and the runtime
lock-order analyzer would flag any new lock here:

* slot reservation is ``next(itertools.count())`` (GIL-atomic in
  CPython — the same trick the flight dump sequencer uses), so two
  threads never frame into the same slot;
* the write itself is ONE mmap slice assignment (a single bytecode, a
  C-level memcpy) — atomic against in-process readers, and torn-at-
  any-byte against a kill, which the CRC framing absorbs.

The reader side never needs the writer alive: :func:`read_ring` /
:func:`read_node` parse a plain ``bytes`` copy of the file, skip torn
slots, and order records by their embedded ``seq``. On top sits the
post-mortem CLI::

    python -m tpubloom.obs.blackbox <state-dir>... [--json] [--rid R]

which decodes every given node's rings (dead or live), correlates
flight events with trace spans AND op-log seqs by rid, and renders one
fleet timeline ordered by topology epoch + wall clock.
"""

from __future__ import annotations

import argparse
import itertools
import json
import logging
import mmap
import os
import shutil
import sys
import time
from typing import Optional

import msgpack

from tpubloom.obs import counters as obs_counters
from tpubloom.utils.crc32c import crc32c

log = logging.getLogger("tpubloom.obs")

MAGIC = b"TPBBOX1\n"
VERSION = 1
HEADER_LEN = 64
FMAGIC = b"TBBR"
FRAME_HEADER = len(FMAGIC) + 8 + 4 + 4  # magic | seq | body_len | crc

#: ring file names inside ``<state-dir>/blackbox/``
SUBDIR = "blackbox"
FLIGHT_RING = "flight.ring"
TRACE_RING = "trace.ring"

#: defaults sized so both rings together stay under ~1.3 MiB per node:
#: flight events are rare and small, spans carry attrs and links
DEFAULT_FLIGHT_SLOTS = 1024
DEFAULT_FLIGHT_SLOT_SIZE = 256
DEFAULT_TRACE_SLOTS = 2048
DEFAULT_TRACE_SLOT_SIZE = 512


def _frame(seq: int, body: bytes) -> bytes:
    head = seq.to_bytes(8, "little") + len(body).to_bytes(4, "little")
    return (
        FMAGIC + head + crc32c(head + body).to_bytes(4, "little") + body
    )


class MappedRing:
    """One mmap'd slot ring. Create with :meth:`open` (never raises into
    the caller's write path — a broken disk disables the ring, it does
    not crash a drain or a promote)."""

    def __init__(self, path: str, slot_size: int, nslots: int):
        self.path = path
        size = HEADER_LEN + slot_size * nslots
        exists = os.path.exists(path) and os.path.getsize(path) >= HEADER_LEN
        fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o644)
        try:
            if exists:
                header = os.pread(fd, HEADER_LEN, 0)
                if (
                    header[:8] == MAGIC
                    and int.from_bytes(header[8:12], "little") == VERSION
                ):
                    # reattach: the FILE's geometry wins — old slots
                    # must keep parsing under the sizes they were
                    # written with
                    slot_size = int.from_bytes(header[12:16], "little")
                    nslots = int.from_bytes(header[16:20], "little")
                    size = HEADER_LEN + slot_size * nslots
                else:
                    exists = False  # foreign/corrupt header: recreate
            if not exists:
                header = (
                    MAGIC
                    + VERSION.to_bytes(4, "little")
                    + slot_size.to_bytes(4, "little")
                    + nslots.to_bytes(4, "little")
                )
                os.pwrite(fd, header.ljust(HEADER_LEN, b"\0"), 0)
            if os.path.getsize(path) != size:
                os.ftruncate(fd, size)
            self.slot_size = slot_size
            self.nslots = nslots
            self._mm = mmap.mmap(fd, size)
        finally:
            os.close(fd)
        # resume the seq space past whatever survived in the file, so a
        # restarted node appends AFTER its pre-crash history instead of
        # overwriting it from slot 0
        decoded = decode_ring(bytes(self._mm))
        last = decoded["records"][-1]["seq"] if decoded["records"] else -1
        self._seq = itertools.count(last + 1)

    def append(self, body: bytes) -> bool:
        """Frame ``body`` into the next slot; False iff it cannot fit.
        Lock-free: atomic seq reservation + one slice assignment."""
        if FRAME_HEADER + len(body) > self.slot_size:
            return False
        seq = next(self._seq)
        frame = _frame(seq, body)
        off = HEADER_LEN + (seq % self.nslots) * self.slot_size
        self._mm[off : off + len(frame)] = frame
        return True

    def sync(self) -> None:
        """msync for the machine-crash case (SIGKILL needs nothing —
        the kernel owns the dirty pages already)."""
        try:
            self._mm.flush()
        except (OSError, ValueError):
            pass

    def close(self) -> None:
        try:
            self._mm.flush()
            self._mm.close()
        except (OSError, ValueError):
            pass


# -- writer state (module-level, like flight/trace) ---------------------------

_flight_ring: Optional[MappedRing] = None
_trace_ring: Optional[MappedRing] = None
_dir: Optional[str] = None
#: topology epoch stamped into every record at write time — the fleet
#: merge's primary sort key (service.adopt_epoch / sentinel adoption
#: keep it current)
_epoch: int = 0
_node: dict = {}


def configure(
    state_dir: str,
    *,
    node: Optional[dict] = None,
    flight_slots: int = DEFAULT_FLIGHT_SLOTS,
    flight_slot_size: int = DEFAULT_FLIGHT_SLOT_SIZE,
    trace_slots: int = DEFAULT_TRACE_SLOTS,
    trace_slot_size: int = DEFAULT_TRACE_SLOT_SIZE,
) -> bool:
    """Arm the black box under ``<state_dir>/blackbox/``. Best-effort:
    returns False (and stays disabled) on any IO error — forensics must
    never stop a server from booting."""
    global _flight_ring, _trace_ring, _dir
    directory = os.path.join(state_dir, SUBDIR)
    try:
        os.makedirs(directory, exist_ok=True)
        _flight_ring = MappedRing(
            os.path.join(directory, FLIGHT_RING),
            flight_slot_size, flight_slots,
        )
        _trace_ring = MappedRing(
            os.path.join(directory, TRACE_RING),
            trace_slot_size, trace_slots,
        )
    except OSError:
        log.exception("black box disabled: cannot map rings in %s", directory)
        _flight_ring = _trace_ring = None
        return False
    _dir = directory
    if node:
        _node.update(node)
    set_node_meta(pid=os.getpid())
    return True


def enabled() -> bool:
    return _flight_ring is not None


def directory() -> Optional[str]:
    return _dir


def set_node_meta(**meta) -> None:
    """Update the node identity (``role``/``epoch``/``addr``/...) and,
    when armed, persist a ``meta`` record so a post-mortem knows who
    this ring belonged to and which epochs it lived through."""
    global _epoch
    ep = meta.get("epoch")
    if ep is not None:
        _epoch = max(_epoch, int(ep))
    _node.update({k: v for k, v in meta.items() if v is not None})
    ring = _flight_ring
    if ring is None:
        return
    _write(ring, {"k": "meta", "ts": time.time(), "ep": _epoch, **_node})


def _write(ring: MappedRing, body: dict) -> None:
    """Pack + append, degrading oversized records instead of losing
    them silently: attrs/links are dropped first, and a record that
    still cannot fit counts as dropped."""
    try:
        packed = msgpack.packb(body, use_bin_type=True, default=str)
        if not ring.append(packed):
            slim = {
                k: v for k, v in body.items() if k not in ("attrs", "links")
            }
            slim["truncated"] = True
            if not ring.append(
                msgpack.packb(slim, use_bin_type=True, default=str)
            ):
                obs_counters.incr("blackbox_records_dropped")
                return
    except (ValueError, OSError, TypeError):
        obs_counters.incr("blackbox_records_dropped")
        return
    obs_counters.incr("blackbox_records_written")


def note_event(ev: dict) -> None:
    """Write-through for :func:`tpubloom.obs.flight.note` — one truthy
    check when disarmed, a lock-free mapped append when armed."""
    ring = _flight_ring
    if ring is None:
        return
    _write(ring, {"k": "ev", "ep": _epoch, **ev})


def spill_span(span: dict) -> None:
    """Persist one forced/slowlog-worthy span (the spans explaining a
    crash must survive the crash) into the companion trace ring."""
    ring = _trace_ring
    if ring is None:
        return
    _write(ring, {"k": "span", "ts": span.get("start"), "ep": _epoch, **span})


def sync() -> None:
    for ring in (_flight_ring, _trace_ring):
        if ring is not None:
            ring.sync()


#: snapshot directory prefix under ``blackbox/`` — the post-mortem CLI
#: and pruning both key on it
SNAP_PREFIX = "snap-"


def snapshot_rings(reason: str, max_snapshots: int = 8) -> Optional[str]:
    """Freeze both rings into ``blackbox/snap-<ts>-<reason>/`` (ISSUE 18
    satellite). The rings are oldest-first OVERWRITE buffers — by the
    time someone reads a DEGRADED incident, minutes of healthy traffic
    may have lapped the records that explain it. Health's
    SERVING→DEGRADED flip calls this so the lead-up survives. Bounded:
    the oldest snapshots beyond ``max_snapshots`` are pruned (a
    flapping health check must not fill the disk). Best-effort like
    every writer here — returns the snapshot dir, or None (disarmed or
    IO error), and never raises."""
    directory = _dir
    if directory is None:
        return None
    sync()  # the copies must include everything written so far
    tag = "".join(
        c if c.isalnum() or c in "-_" else "-" for c in reason
    ) or "unknown"
    snap = os.path.join(
        directory, f"{SNAP_PREFIX}{int(time.time() * 1000):013d}-{tag}"
    )
    try:
        os.makedirs(snap, exist_ok=True)
        for fname in (FLIGHT_RING, TRACE_RING):
            src = os.path.join(directory, fname)
            if os.path.exists(src):
                shutil.copyfile(src, os.path.join(snap, fname))
    except OSError:
        log.exception("black box: ring snapshot failed in %s", directory)
        return None
    try:
        snaps = sorted(
            d for d in os.listdir(directory)
            if d.startswith(SNAP_PREFIX)
            and os.path.isdir(os.path.join(directory, d))
        )
        for stale in snaps[:-max_snapshots] if max_snapshots > 0 else snaps:
            shutil.rmtree(os.path.join(directory, stale), ignore_errors=True)
    except OSError:
        pass  # pruning is advisory; the snapshot itself landed
    return snap


def reset_for_tests() -> None:
    global _flight_ring, _trace_ring, _dir, _epoch
    for ring in (_flight_ring, _trace_ring):
        if ring is not None:
            ring.close()
    _flight_ring = _trace_ring = None
    _dir = None
    _epoch = 0
    _node.clear()


# -- decoding (works on dead processes: plain bytes, no mmap) -----------------


def decode_ring(buf: bytes) -> dict:
    """Parse one ring image: ``{"geometry", "records", "skipped"}``.
    ``records`` are seq-ordered bodies (each with its ``seq`` folded
    in); a slot whose frame is torn — short, bad magic, bad length, CRC
    mismatch, unparseable body — is *skipped*, exactly one record lost,
    never a misread."""
    if len(buf) < HEADER_LEN or buf[:8] != MAGIC:
        return {"geometry": None, "records": [], "skipped": 0}
    version = int.from_bytes(buf[8:12], "little")
    slot_size = int.from_bytes(buf[12:16], "little")
    nslots = int.from_bytes(buf[16:20], "little")
    geometry = {
        "version": version, "slot_size": slot_size, "nslots": nslots,
    }
    if version != VERSION or slot_size <= FRAME_HEADER or nslots <= 0:
        return {"geometry": geometry, "records": [], "skipped": 0}
    records, skipped = [], 0
    for i in range(nslots):
        off = HEADER_LEN + i * slot_size
        slot = buf[off : off + slot_size]
        if len(slot) < FRAME_HEADER:
            if slot.strip(b"\0"):
                skipped += 1  # truncated mid-slot: a torn tail
            continue
        if slot[:4] != FMAGIC:
            if slot.strip(b"\0"):
                skipped += 1
            continue
        seq = int.from_bytes(slot[4:12], "little")
        body_len = int.from_bytes(slot[12:16], "little")
        crc = int.from_bytes(slot[16:20], "little")
        body = slot[FRAME_HEADER : FRAME_HEADER + body_len]
        if (
            len(body) != body_len
            or crc32c(slot[4:16] + body) != crc
        ):
            skipped += 1
            continue
        try:
            rec = msgpack.unpackb(body, raw=False)
        except Exception:  # torn in a way the CRC cannot see (never
            skipped += 1  # observed; belt and braces for a post-mortem)
            continue
        if not isinstance(rec, dict):
            skipped += 1
            continue
        rec["seq"] = seq
        records.append(rec)
    records.sort(key=lambda r: r["seq"])
    return {"geometry": geometry, "records": records, "skipped": skipped}


def read_ring(path: str) -> dict:
    """Decode one ring file from disk (tolerates short/truncated
    files — missing slots read as torn)."""
    try:
        with open(path, "rb") as f:
            return decode_ring(f.read())
    except OSError:
        return {"geometry": None, "records": [], "skipped": 0}


def _blackbox_dir_of(path: str) -> Optional[str]:
    """Accept a state dir, the blackbox dir itself, or a ring file."""
    if os.path.isfile(path):
        return os.path.dirname(path) or "."
    if os.path.isdir(os.path.join(path, SUBDIR)):
        return os.path.join(path, SUBDIR)
    if os.path.isdir(path) and (
        os.path.exists(os.path.join(path, FLIGHT_RING))
        or os.path.exists(os.path.join(path, TRACE_RING))
    ):
        return path
    return None


def read_node(path: str) -> Optional[dict]:
    """Decode one node's black box: ``{"dir", "label", "meta",
    "events", "spans", "skipped"}``. ``meta`` is the newest meta
    record; ``label`` prefers the node's announced address."""
    directory = _blackbox_dir_of(path)
    if directory is None:
        return None
    flight = read_ring(os.path.join(directory, FLIGHT_RING))
    trace = read_ring(os.path.join(directory, TRACE_RING))
    meta: dict = {}
    events = []
    for rec in flight["records"]:
        if rec.get("k") == "meta":
            meta = {
                k: v for k, v in rec.items() if k not in ("k", "seq")
            }
        elif rec.get("k") == "ev":
            events.append(rec)
    spans = [r for r in trace["records"] if r.get("k") == "span"]
    state_dir = os.path.dirname(os.path.abspath(directory))
    label = meta.get("addr") or os.path.basename(state_dir)
    return {
        "dir": directory,
        "state_dir": state_dir,
        "label": str(label),
        "meta": meta,
        "events": events,
        "spans": spans,
        "skipped": flight["skipped"] + trace["skipped"],
    }


def scan_oplog(state_dir: str, rids: set) -> list:
    """Correlate by rid against the node's op log: scan every
    ``oplog.*.seg`` beside the blackbox dir with the op-log framing and
    keep the records whose rid the rings mentioned — the post-mortem's
    bridge from 'the span says it committed' to 'seq N in the log'."""
    from tpubloom.repl import record as repl_record

    out = []
    if not rids:
        return out
    try:
        names = sorted(
            fn for fn in os.listdir(state_dir)
            if fn.startswith("oplog.") and fn.endswith(".seg")
        )
    except OSError:
        return out
    for fn in names:
        try:
            with open(os.path.join(state_dir, fn), "rb") as f:
                buf = f.read()
        except OSError:
            continue
        records, _valid, _clean = repl_record.scan_buffer(buf)
        for rec in records:
            if rec.get("rid") in rids:
                out.append(
                    {
                        "seq": rec.get("seq"),
                        "method": rec.get("method"),
                        "rid": rec.get("rid"),
                        "ts": rec.get("ts"),
                        "filter": (rec.get("req") or {}).get("name"),
                    }
                )
    return out


def merge_timeline(
    nodes: list, *, rid: Optional[str] = None, with_oplog: bool = True
) -> list:
    """Merge decoded nodes into one fleet timeline: entries ``{"ts",
    "ep", "node", "type", ...}`` ordered by (topology epoch, wall
    clock) — epoch first because wall clocks across a fleet skew, and
    an epoch boundary is the one ordering every node agrees on."""
    entries = []
    rids: set = set()
    for node in nodes:
        for ev in node["events"]:
            attrs = ev.get("attrs") or {}
            if attrs.get("rid"):
                rids.add(attrs["rid"])
            entries.append(
                {
                    "ts": float(ev.get("ts") or 0.0),
                    "ep": int(ev.get("ep") or 0),
                    "node": node["label"],
                    "type": "event",
                    "kind": ev.get("kind"),
                    "attrs": attrs,
                    "seq": ev.get("seq"),
                }
            )
        for s in node["spans"]:
            if s.get("rid"):
                rids.add(s["rid"])
            entries.append(
                {
                    "ts": float(s.get("start") or s.get("ts") or 0.0),
                    "ep": int(s.get("ep") or 0),
                    "node": node["label"],
                    "type": "span",
                    "name": s.get("name"),
                    "rid": s.get("rid"),
                    "span": s.get("span"),
                    "parent": s.get("parent"),
                    "duration_s": s.get("duration_s"),
                    "attrs": s.get("attrs") or {},
                    "seq": s.get("seq"),
                }
            )
    if with_oplog:
        for node in nodes:
            want = {rid} if rid else rids
            for rec in scan_oplog(node["state_dir"], want):
                entries.append(
                    {
                        "ts": float(rec.get("ts") or 0.0),
                        "ep": 0,
                        "node": node["label"],
                        "type": "oplog",
                        "rid": rec.get("rid"),
                        "oplog_seq": rec.get("seq"),
                        "method": rec.get("method"),
                        "filter": rec.get("filter"),
                    }
                )
    if rid:
        entries = [
            e for e in entries
            if e.get("rid") == rid or (e.get("attrs") or {}).get("rid") == rid
            or e["type"] == "event"  # lifecycle context stays visible
        ]
    entries.sort(key=lambda e: (e["ep"], e["ts"], e.get("seq") or 0))
    return entries


def _fmt_ts(ts: float) -> str:
    if not ts:
        return "?" * 15
    lt = time.localtime(ts)
    return time.strftime("%H:%M:%S", lt) + f".{int((ts % 1) * 1e6):06d}"


def _render(nodes: list, timeline: list) -> str:
    lines = []
    for node in nodes:
        meta = node["meta"]
        lines.append(
            f"node {node['label']}  dir={node['state_dir']}  "
            f"pid={meta.get('pid', '?')}  role={meta.get('role', '?')}  "
            f"ep={meta.get('ep', 0)}  events={len(node['events'])}  "
            f"spans={len(node['spans'])}  torn={node['skipped']}"
        )
    lines.append("-" * 72)
    for e in timeline:
        head = f"{_fmt_ts(e['ts'])} ep={e['ep']:<3d} [{e['node']}]"
        if e["type"] == "event":
            attrs = " ".join(
                f"{k}={v}" for k, v in sorted((e["attrs"] or {}).items())
            )
            lines.append(f"{head} EVENT {e['kind']} {attrs}".rstrip())
        elif e["type"] == "span":
            dur = (e.get("duration_s") or 0.0) * 1e3
            attrs = " ".join(
                f"{k}={v}" for k, v in sorted((e["attrs"] or {}).items())
            )
            lines.append(
                f"{head} SPAN  {e['name']} rid={e.get('rid')} "
                f"{dur:.1f}ms {attrs}".rstrip()
            )
        else:
            lines.append(
                f"{head} OPLOG seq={e.get('oplog_seq')} {e.get('method')} "
                f"rid={e.get('rid')} filter={e.get('filter')}"
            )
    return "\n".join(lines)


def main(argv: Optional[list] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tpubloom.obs.blackbox",
        description="decode crash-forensics rings from any number of "
        "(dead or live) tpubloom state dirs and merge them into one "
        "fleet timeline ordered by topology epoch + wall clock",
    )
    parser.add_argument(
        "paths", nargs="+", metavar="STATE-DIR",
        help="state dirs (op-log/checkpoint dirs), blackbox/ dirs, or "
        "ring files",
    )
    parser.add_argument(
        "--json", action="store_true", dest="as_json",
        help="machine-readable output instead of the human timeline",
    )
    parser.add_argument(
        "--rid", default=None,
        help="focus the timeline on one request id (lifecycle events "
        "stay for context)",
    )
    parser.add_argument(
        "--no-oplog", action="store_true",
        help="skip the op-log seq correlation scan",
    )
    parser.add_argument(
        "--limit", type=int, default=0, metavar="N",
        help="keep only the N newest timeline entries",
    )
    args = parser.parse_args(argv)
    nodes = []
    for path in args.paths:
        node = read_node(path)
        if node is None:
            print(f"no black box under {path!r}", file=sys.stderr)
            continue
        nodes.append(node)
    if not nodes:
        print("nothing to decode", file=sys.stderr)
        return 2
    timeline = merge_timeline(
        nodes, rid=args.rid, with_oplog=not args.no_oplog
    )
    if args.limit > 0:
        timeline = timeline[-args.limit :]
    if args.as_json:
        print(json.dumps({"nodes": nodes, "timeline": timeline}, default=str))
    else:
        print(_render(nodes, timeline))
    return 0


if __name__ == "__main__":
    sys.exit(main())
