"""Background HTTP thread serving ``GET /metrics`` (Prometheus scrape).

A ``ThreadingHTTPServer`` on its own daemon thread — the gRPC data path
never blocks on a scrape; a scrape only contends for the per-filter op
locks while reading gauges (microseconds per filter). ``/healthz``
answers 200 for liveness probes without touching any filter.
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

log = logging.getLogger("tpubloom.obs")

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class MetricsServer:
    """Own the listener + thread; ``port`` holds the bound port (pass
    port 0 for an ephemeral one — tests and the smoke benchmark do)."""

    def __init__(self, render_fn, port: int = 0, host: str = "0.0.0.0"):
        # probe ONCE whether render_fn takes the exemplars knob — a
        # try/except TypeError at request time would also swallow real
        # TypeErrors raised inside the render and silently serve the
        # un-annotated view
        import inspect

        try:
            has_exemplars_knob = "exemplars" in inspect.signature(
                render_fn
            ).parameters
        except (TypeError, ValueError):  # builtins/partials w/o signature
            has_exemplars_knob = False

        class Handler(BaseHTTPRequestHandler):
            def _send_body(self, body: bytes, content_type: str) -> None:
                self.send_response(200)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _send_json(self, obj) -> None:
                self._send_body(
                    json.dumps(obj, indent=1, default=str).encode(),
                    "application/json",
                )

            def do_GET(self):  # noqa: N802 — http.server API
                path, _, query = self.path.partition("?")
                if path == "/metrics":
                    # ?exemplars=1 opts into the OpenMetrics-style
                    # exemplar annotations (ISSUE 9 satellite); stock
                    # 0.0.4 scrapers keep the unannotated default
                    want_exemplars = (
                        has_exemplars_knob
                        and "exemplars=1" in query.split("&")
                    )
                    try:
                        if want_exemplars:
                            body = render_fn(exemplars=True).encode()
                        else:
                            body = render_fn().encode()
                    except Exception:  # a broken gauge must not 500 forever silently
                        log.exception("metrics render failed")
                        self.send_error(500, "metrics render failed")
                        return
                    self._send_body(body, CONTENT_TYPE)
                elif path == "/healthz":
                    self._send_json({"ok": True})
                elif path == "/trace":
                    # ISSUE 15: the per-node trace view — the spans this
                    # process recorded for one rid (plus flush spans
                    # that LINK it), same data as the TraceGet RPC
                    from urllib.parse import parse_qs

                    from tpubloom.obs import trace as trace_mod

                    rid = (parse_qs(query).get("rid") or [""])[0]
                    if not rid:
                        self.send_error(400, "try /trace?rid=<request id>")
                        return
                    self._send_json(
                        {
                            "rid": rid,
                            "enabled": trace_mod.enabled(),
                            "spans": trace_mod.get_trace(rid),
                        }
                    )
                elif path == "/flight":
                    # ISSUE 15: the on-demand flight-recorder view —
                    # the same ring a SIGTERM/fatal/DEGRADED-flip dump
                    # writes to the state dir
                    from tpubloom.obs import flight as flight_mod

                    self._send_json({"events": flight_mod.snapshot()})
                else:
                    self.send_error(
                        404, "try /metrics, /healthz, /trace or /flight"
                    )

            def log_message(self, fmt, *args):  # scrapes are chatty; route to logging
                log.debug("metrics http: " + fmt, *args)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="tpubloom-metrics", daemon=True
        )
        self._thread.start()

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=10)


def start_metrics_server(service, port: int = 0, host: str = "0.0.0.0") -> MetricsServer:
    """Serve ``render_service(service)`` at ``http://host:port/metrics``
    (``?exemplars=1`` adds the rid exemplars on latency buckets)."""
    from tpubloom.obs.exposition import render_service

    return MetricsServer(
        lambda exemplars=False: render_service(service, exemplars=exemplars),
        port=port,
        host=host,
    )
