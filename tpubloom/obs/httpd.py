"""Background HTTP thread serving ``GET /metrics`` (Prometheus scrape).

A ``ThreadingHTTPServer`` on its own daemon thread — the gRPC data path
never blocks on a scrape; a scrape only contends for the per-filter op
locks while reading gauges (microseconds per filter). ``/healthz``
answers 200 for liveness probes without touching any filter.
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

log = logging.getLogger("tpubloom.obs")

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class MetricsServer:
    """Own the listener + thread; ``port`` holds the bound port (pass
    port 0 for an ephemeral one — tests and the smoke benchmark do)."""

    def __init__(self, render_fn, port: int = 0, host: str = "0.0.0.0"):
        # probe ONCE whether render_fn takes the exemplars knob — a
        # try/except TypeError at request time would also swallow real
        # TypeErrors raised inside the render and silently serve the
        # un-annotated view
        import inspect

        try:
            has_exemplars_knob = "exemplars" in inspect.signature(
                render_fn
            ).parameters
        except (TypeError, ValueError):  # builtins/partials w/o signature
            has_exemplars_knob = False

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — http.server API
                path, _, query = self.path.partition("?")
                if path == "/metrics":
                    # ?exemplars=1 opts into the OpenMetrics-style
                    # exemplar annotations (ISSUE 9 satellite); stock
                    # 0.0.4 scrapers keep the unannotated default
                    want_exemplars = (
                        has_exemplars_knob
                        and "exemplars=1" in query.split("&")
                    )
                    try:
                        if want_exemplars:
                            body = render_fn(exemplars=True).encode()
                        else:
                            body = render_fn().encode()
                    except Exception:  # a broken gauge must not 500 forever silently
                        log.exception("metrics render failed")
                        self.send_error(500, "metrics render failed")
                        return
                    self.send_response(200)
                    self.send_header("Content-Type", CONTENT_TYPE)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif path == "/healthz":
                    body = json.dumps({"ok": True}).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self.send_error(404, "try /metrics or /healthz")

            def log_message(self, fmt, *args):  # scrapes are chatty; route to logging
                log.debug("metrics http: " + fmt, *args)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="tpubloom-metrics", daemon=True
        )
        self._thread.start()

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=10)


def start_metrics_server(service, port: int = 0, host: str = "0.0.0.0") -> MetricsServer:
    """Serve ``render_service(service)`` at ``http://host:port/metrics``
    (``?exemplars=1`` adds the rid exemplars on latency buckets)."""
    from tpubloom.obs.exposition import render_service

    return MetricsServer(
        lambda exemplars=False: render_service(service, exemplars=exemplars),
        port=port,
        host=host,
    )
