"""Prometheus text-format (exposition format 0.0.4) rendering.

One render = one consistent scrape: counters and histogram buckets come
from a locked snapshot of the server's :class:`Metrics`, per-filter
gauges are read under each filter's op lock (so a gauge never reads a
donated mid-update device buffer), and the process-global counters are
merged in. No client library — the format is 30 lines of text, and the
environment must not grow dependencies.

Metric catalog (all prefixed ``tpubloom_``):

* ``keys_inserted_total`` / ``keys_queried_total`` / ... — every server
  counter, rendered as ``tpubloom_<name>_total``.
* ``rpc_duration_seconds`` — per-RPC latency histogram (log2 buckets,
  1us..~67s), labels ``{method}``.
* ``rpc_phase_seconds`` — the phase breakdown histogram, labels
  ``{method, phase}`` for decode/host_prep/h2d/kernel/d2h/encode.
* ``filter_fill_ratio`` / ``filter_bits_set`` / ``filter_estimated_fpr``
  / ``filter_predicted_fpr`` / ``filter_fpr_drift`` /
  ``filter_keys_inserted`` / ``filter_keys_queried`` /
  ``filter_layers`` — per-filter gauges, label ``{filter}``.
* ``shard_fill_ratio`` — per-shard fill, labels ``{filter, shard}``.
* ``checkpoint_lag_inserts`` / ``checkpoint_age_seconds`` /
  ``checkpoint_last_duration_seconds`` / ``checkpoint_seq`` /
  ``checkpoints_written_total`` — checkpoint gauges, label ``{filter}``.
* ``slowlog_entries`` / ``slowlog_recorded_total`` — slowlog state.
* ``uptime_seconds``, plus every process-global counter (e.g.
  ``geometry_probe_demotions_total``, ``faults_injected_total``,
  ``ckpt_corrupt_detected_total``) and every process-global gauge
  (e.g. ``client_breaker_state``: 0 closed / 1 half-open / 2 open).
* robustness counters (ISSUE 2): ``requests_shed_total``,
  ``delete_dedup_hits_total``, ``restores_with_corrupt_generations_total``.
* replication (ISSUE 3, process-global): gauges ``repl_log_seq`` /
  ``repl_log_bytes`` / ``repl_log_segments`` /
  ``repl_connected_replicas`` / ``repl_max_replica_lag_seq`` (primary),
  ``repl_lag_seq`` / ``repl_lag_seconds`` (replica),
  ``retry_after_ms_current`` / ``monitor_subscribers``; counters
  ``repl_full_resyncs_total`` / ``repl_partial_resyncs_total`` /
  ``repl_records_streamed_total`` / ``repl_records_applied_total`` /
  ``repl_records_skipped_total`` / ``repl_reconnects_total`` /
  ``repl_log_torn_tail_truncated_total`` / ``monitor_events_dropped_total``.
* synchronous replication (ISSUE 5): per-replica gauges
  ``repl_acked_seq{replica}`` / ``repl_replica_cursor{replica}`` (from
  the primary's connected sessions), the ``wait_blocked_current``
  process gauge (commit-barrier + Wait waiters currently blocked), the
  ``wait_barrier_seconds`` histogram (time spent blocked on replica
  acks), and counters ``repl_acks_received_total`` /
  ``repl_acks_sent_total`` / ``repl_acks_dropped_total`` /
  ``quorum_writes_acked_total`` / ``quorum_write_failures_total``.
"""

from __future__ import annotations

import math
from typing import Iterable

from tpubloom.obs import counters as _global

PREFIX = "tpubloom"

#: filter ``stats()`` field -> (gauge suffix, help text). Fields a filter
#: variant doesn't report are simply skipped.
_FILTER_GAUGES = {
    "fill_ratio": ("filter_fill_ratio", "Fraction of bits set"),
    "bits_set": ("filter_bits_set", "Number of bits set"),
    "estimated_fpr": (
        "filter_estimated_fpr",
        "FPR estimated from the observed fill ratio (fill^k)",
    ),
    "predicted_fpr": (
        "filter_predicted_fpr",
        "Analytic FPR predicted from n_inserted ((1-e^{-kn/m})^k)",
    ),
    "fpr_drift": (
        "filter_fpr_drift",
        "estimated_fpr - predicted_fpr (observed-vs-model drift)",
    ),
    "n_inserted": ("filter_keys_inserted", "Keys inserted into the filter"),
    "n_queried": ("filter_keys_queried", "Keys queried against the filter"),
    "n_layers": ("filter_layers", "Layer count (scalable filters)"),
}

_CKPT_GAUGES = {
    "lag_inserts": (
        "checkpoint_lag_inserts",
        "Inserts since the last checkpoint trigger",
    ),
    "age_seconds": (
        "checkpoint_age_seconds",
        "Seconds since the last checkpoint landed in the sink",
    ),
    "last_duration_seconds": (
        "checkpoint_last_duration_seconds",
        "Wall time of the last checkpoint serialize+write",
    ),
    "seq": ("checkpoint_seq", "Sequence number of the newest checkpoint"),
    "checkpoints_written": (
        "checkpoints_written_total",
        "Checkpoints successfully written",
    ),
}


def _escape(value: str) -> str:
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace("\n", "\\n")
        .replace('"', '\\"')
    )


def _fmt(value: float) -> str:
    if value != value:  # NaN
        return "NaN"
    if value == math.inf:
        return "+Inf"
    if value == -math.inf:
        return "-Inf"
    if isinstance(value, bool):
        return "1" if value else "0"
    if isinstance(value, int) or float(value).is_integer():
        return str(int(value))
    return repr(float(value))


def _line(name: str, value: float, labels: dict | None = None) -> str:
    if labels:
        body = ",".join(f'{k}="{_escape(v)}"' for k, v in labels.items())
        return f"{PREFIX}_{name}{{{body}}} {_fmt(value)}"
    return f"{PREFIX}_{name} {_fmt(value)}"


def _header(out: list, name: str, kind: str, help_text: str) -> None:
    out.append(f"# HELP {PREFIX}_{name} {help_text}")
    out.append(f"# TYPE {PREFIX}_{name} {kind}")


def _render_histogram(
    out: list,
    name: str,
    series: Iterable[tuple[dict, dict]],
    bucket_bounds_us: list,
    help_text: str,
    *,
    exemplars: bool = False,
) -> None:
    """``series`` = iterable of (labels, {counts, total_us, n,
    exemplars?}). With ``exemplars=True`` each bucket line carries its
    OpenMetrics exemplar (``# {rid="..."} value ts``) when one was
    recorded — the rid links the bucket to the matching slowlog entry /
    trace span (ISSUE 9 satellite; request the view with
    ``/metrics?exemplars=1``, stock 0.0.4 scrapes stay untouched)."""
    wrote_header = False
    for labels, hist in series:
        if not wrote_header:
            _header(out, name, "histogram", help_text)
            wrote_header = True
        bucket_exemplars = hist.get("exemplars") or {}
        cum = 0
        for i, count in enumerate(hist["counts"]):
            cum += count
            le = (
                _fmt(bucket_bounds_us[i] / 1e6)
                if i < len(bucket_bounds_us)
                else "+Inf"
            )
            line = _line(f"{name}_bucket", cum, {**labels, "le": le})
            ex = bucket_exemplars.get(i) if exemplars else None
            if ex is None and exemplars:
                ex = bucket_exemplars.get(str(i))  # msgpack/json round trips
            if ex is not None:
                line += (
                    f' # {{rid="{_escape(ex["rid"])}"}} '
                    f'{_fmt(ex["value_s"])} {_fmt(ex["ts"])}'
                )
            out.append(line)
        out.append(_line(f"{name}_sum", hist["total_us"] / 1e6, labels))
        out.append(_line(f"{name}_count", hist["n"], labels))


def render_service(service, *, exemplars: bool = False) -> str:
    """Render a full scrape for a live ``BloomService``.

    Duck-typed on: ``service.metrics.export()``, ``service.slowlog``, and
    ``service.gauge_snapshot()`` (see ``server/service.py``).
    ``exemplars=True`` annotates the RPC latency buckets with their
    newest request id (OpenMetrics exemplar syntax) — the same rid the
    slowlog keeps, so a latency spike walks straight to its request.
    """
    met = service.metrics.export()
    out: list[str] = []

    _header(out, "uptime_seconds", "gauge", "Server process uptime")
    out.append(_line("uptime_seconds", met["uptime_s"]))

    for name in sorted(met["counters"]):
        _header(out, f"{name}_total", "counter", f"Server counter {name}")
        out.append(_line(f"{name}_total", met["counters"][name]))

    process_counters = _global.global_counters()
    for name in sorted(process_counters):
        _header(out, f"{name}_total", "counter", f"Process counter {name}")
        out.append(_line(f"{name}_total", process_counters[name]))

    process_gauges = _global.global_gauges()
    for name in sorted(process_gauges):
        _header(out, name, "gauge", f"Process gauge {name}")
        out.append(_line(name, process_gauges[name]))

    bounds = met["bucket_bounds_us"]
    _render_histogram(
        out,
        "rpc_duration_seconds",
        (
            ({"method": m}, h)
            for m, h in sorted(met["latency"].items())
        ),
        bounds,
        "End-to-end RPC latency by method",
        exemplars=exemplars,
    )
    _render_histogram(
        out,
        "rpc_phase_seconds",
        (
            ({"method": key.split("/", 1)[0], "phase": key.split("/", 1)[1]}, h)
            for key, h in sorted(met["phases"].items())
        ),
        bounds,
        "Per-RPC phase breakdown (decode/host_prep/h2d/kernel/d2h/encode)",
        exemplars=exemplars,
    )
    waits = met.get("waits")
    if waits and waits.get("n"):
        _render_histogram(
            out,
            "wait_barrier_seconds",
            [({}, waits)],
            bounds,
            "Time spent blocked on replica acks (commit barrier + Wait)",
        )
    hydrations = met.get("hydrations")
    if hydrations and hydrations.get("n"):
        _render_histogram(
            out,
            "storage_hydration_seconds",
            [({}, hydrations)],
            bounds,
            "Tenant hydration latency (storage paging fault, ISSUE 14)",
        )

    gauge_headers_done: set[str] = set()

    def gauge(suffix: str, help_text: str, value, labels: dict) -> None:
        if value is None:
            return
        if suffix not in gauge_headers_done:
            kind = "counter" if suffix.endswith("_total") else "gauge"
            _header(out, suffix, kind, help_text)
            gauge_headers_done.add(suffix)
        out.append(_line(suffix, value, labels))

    for snap in service.gauge_snapshot():
        labels = {"filter": snap["filter"]}
        for field, (suffix, help_text) in _FILTER_GAUGES.items():
            value = snap["stats"].get(field)
            if isinstance(value, (int, float)):
                gauge(suffix, help_text, value, labels)
        for shard, fill in enumerate(snap.get("shard_fill") or []):
            gauge(
                "shard_fill_ratio",
                "Per-shard fraction of bits set",
                fill,
                {**labels, "shard": str(shard)},
            )
        for field, (suffix, help_text) in _CKPT_GAUGES.items():
            value = (snap.get("checkpoint") or {}).get(field)
            if isinstance(value, (int, float)):
                gauge(suffix, help_text, value, labels)

    # per-replica replication gauges (ISSUE 5): the primary's connected
    # sessions, labeled by the replica's announced address. Deduped by
    # label keeping the NEWEST session — a replica that reconnected
    # before its old stream was reaped would otherwise emit the same
    # series twice, and Prometheus rejects a scrape with duplicate
    # samples wholesale
    sessions = getattr(service, "repl_sessions", None)
    if sessions is not None:
        by_label: dict = {}
        for sess in sessions.describe():
            label = sess.get("listen") or sess.get("peer") or "?"
            prev = by_label.get(label)
            if prev is None or sess.get("connected_at", 0) >= prev.get(
                "connected_at", 0
            ):
                by_label[label] = sess
        for label, sess in sorted(by_label.items()):
            labels = {"replica": label}
            gauge(
                "repl_acked_seq",
                "Newest op seq this replica has acknowledged as applied",
                sess.get("acked"),
                labels,
            )
            gauge(
                "repl_replica_cursor",
                "Newest op seq streamed to this replica",
                sess.get("cursor"),
                labels,
            )

    _header(out, "slowlog_entries", "gauge", "Entries currently in the slowlog")
    out.append(_line("slowlog_entries", len(service.slowlog)))
    _header(
        out,
        "slowlog_recorded_total",
        "counter",
        "Requests ever considered by the slowlog",
    )
    out.append(_line("slowlog_recorded_total", service.slowlog.total_recorded))

    return "\n".join(out) + "\n"


def parse_families(text: str) -> dict[str, dict[tuple, float]]:
    """Tiny exposition-format parser for tests and the smoke benchmark:
    ``{metric_name: {(sorted label items): value}}``. Not a validating
    parser — just enough structure to assert on a scrape."""
    families: dict[str, dict[tuple, float]] = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        name_part, _, value_part = line.rpartition(" ")
        if "{" in name_part:
            name, _, label_body = name_part.partition("{")
            label_body = label_body.rstrip("}")
            labels = []
            for item in _split_labels(label_body):
                k, _, v = item.partition("=")
                labels.append((k, v.strip('"')))
            key = tuple(sorted(labels))
        else:
            name, key = name_part, ()
        value = float(value_part)
        families.setdefault(name, {})[key] = value
    return families


def _split_labels(body: str) -> list[str]:
    """Split ``a="x",b="y"`` on commas outside quotes."""
    items, depth_quote, start = [], False, 0
    for i, ch in enumerate(body):
        if ch == '"' and (i == 0 or body[i - 1] != "\\"):
            depth_quote = not depth_quote
        elif ch == "," and not depth_quote:
            items.append(body[start:i])
            start = i + 1
    if body[start:]:
        items.append(body[start:])
    return items
