"""SLOWLOG parity: a bounded log of the slowest requests.

The reference's operators triage latency with Redis ``SLOWLOG GET`` /
``SLOWLOG RESET`` (SURVEY.md §5); this is the same workflow over the
tpubloom wire protocol. Differences from Redis, on purpose:

* the buffer keeps the N **slowest** requests seen since the last reset
  (a min-heap on duration), not the N most recent over a threshold — on
  a batch server the interesting tail is the slow one, and a burst of
  mildly-slow requests must not evict the genuinely pathological entry;
* every entry carries the client-generated request id and the per-phase
  breakdown, so a slowlog hit correlates directly with profiler spans
  (``tracing.annotate`` folds the same rid into the span name) and
  distinguishes transport-bound from kernel-bound latency on its own.

Entries are plain dicts (msgpack-ready for the ``SlowlogGet`` RPC).
"""

from __future__ import annotations

import heapq
import time
from typing import Optional

from tpubloom.utils import locks


def summarize_request(method: str, req: dict) -> str:
    """Slowlog-safe one-line argument summary: key payloads become a
    count (raw keys may be sensitive and are bulky), everything else is
    shown by name."""
    parts = []
    for field, value in req.items():
        if field == "keys":
            parts.append(f"keys[{len(value)}]")
        elif field == "keys_fixed" and isinstance(value, dict):
            parts.append(
                f"keys_fixed[{value.get('n')}x{value.get('width')}B]"
            )
        elif field in ("rid",):
            continue
        elif isinstance(value, (bytes, bytearray)):
            parts.append(f"{field}=<{len(value)}B>")
        else:
            parts.append(f"{field}={value!r}")
    return f"{method} " + " ".join(parts) if parts else method


class Slowlog:
    """Thread-safe ring of the ``capacity`` slowest requests.

    ``threshold_s`` drops fast requests before they ever touch the heap
    (0.0 records everything, like Redis' slowlog-log-slower-than 0).
    """

    def __init__(self, capacity: int = 128, threshold_s: float = 0.0):
        self.capacity = capacity
        self.threshold_s = threshold_s
        self._lock = locks.named_lock("obs.slowlog")
        self._heap: list[tuple[float, int, dict]] = []
        self._next_id = 0
        self.total_recorded = 0

    def record(
        self,
        *,
        method: str,
        duration_s: float,
        rid: Optional[str] = None,
        batch: int = 0,
        args: str = "",
        phases: Optional[dict] = None,
        ts: Optional[float] = None,
    ) -> None:
        if duration_s < self.threshold_s or self.capacity <= 0:
            return
        entry = {
            "id": 0,  # assigned under the lock
            "time": ts if ts is not None else time.time(),
            "method": method,
            "rid": rid or "",
            "duration_s": duration_s,
            "batch": batch,
            "args": args,
            "phases": dict(phases or {}),
        }
        with self._lock:
            entry["id"] = self._next_id
            self._next_id += 1
            self.total_recorded += 1
            if len(self._heap) >= self.capacity:
                if duration_s <= self._heap[0][0]:
                    return  # faster than the fastest kept entry
                heapq.heapreplace(self._heap, (duration_s, entry["id"], entry))
            else:
                heapq.heappush(self._heap, (duration_s, entry["id"], entry))

    def would_record(self, duration_s: float) -> bool:
        """Whether a request of this duration would enter the ring —
        the tracing layer's "slowlog-worthy" predicate (ISSUE 15: slow
        requests are ALWAYS captured, sampled or not). Asked BEFORE
        :meth:`record` so the answer is not perturbed by the entry
        itself."""
        if duration_s < self.threshold_s or self.capacity <= 0:
            return False
        with self._lock:
            return (
                len(self._heap) < self.capacity
                or duration_s > self._heap[0][0]
            )

    def entries(self, n: Optional[int] = None) -> list[dict]:
        """Slowest first; at most ``n`` entries (all by default)."""
        with self._lock:
            ordered = sorted(self._heap, key=lambda t: (-t[0], -t[1]))
        out = [dict(e) for _, _, e in ordered]
        return out[:n] if n is not None else out

    def __len__(self) -> int:
        with self._lock:
            return len(self._heap)

    def reset(self) -> int:
        """Drop all entries; returns how many were dropped (ids keep
        counting up so post-reset entries are distinguishable)."""
        with self._lock:
            n = len(self._heap)
            self._heap.clear()
            return n
