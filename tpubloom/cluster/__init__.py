"""Cluster mode (ISSUE 9) — hash-slot sharding, MOVED/ASK redirects,
live slot migration. Redis Cluster parity for tpubloom:

* :mod:`tpubloom.cluster.slots` — CRC16-mod-16384 slot hashing (hash
  tags included), the persisted CRC-checked :class:`SlotMap` with
  config epochs;
* :mod:`tpubloom.cluster.node` — per-node :class:`ClusterState`: the
  ownership check behind every keyed RPC (``MOVED``/``ASK``/
  ``CLUSTERDOWN``), migration bookkeeping (dual-write forwards +
  exactly-once import gates), node→node RPC links;
* :mod:`tpubloom.cluster.migrate` — live slot migration
  (``MigrateSlot``): snapshot blobs + op-log tail node→node, the
  PR-3/5 resync machinery reused, with a dual-write window so no acked
  write is lost and counting filters never double-apply;
* :mod:`tpubloom.cluster.client` — the cluster-aware Python client:
  slot→shard cache refreshed on ``MOVED``, one-shot ``ASK`` follow-ups,
  per-shard sentinel/topology awareness layered on the PR-4 client;
* :mod:`tpubloom.cluster.rebalance` — ``python -m tpubloom.cluster``:
  ``init`` (seed assignments), ``info``, ``migrate``, ``rebalance``
  (plan + drive slot moves toward an even spread).

Server wiring: ``python -m tpubloom.server --cluster`` attaches a
:class:`ClusterState`; see ``tpubloom/server/service.py``.
"""

from tpubloom.cluster.client import ClusterClient
from tpubloom.cluster.node import ClusterState, KEYED_METHODS
from tpubloom.cluster.slots import NUM_SLOTS, SlotMap, SlotStore, crc16, key_slot

__all__ = [
    "ClusterClient",
    "ClusterState",
    "KEYED_METHODS",
    "NUM_SLOTS",
    "SlotMap",
    "SlotStore",
    "crc16",
    "key_slot",
]
