"""``python -m tpubloom.cluster`` — cluster admin CLI (see rebalance.py)."""

import sys

from tpubloom.cluster.rebalance import main

sys.exit(main())
