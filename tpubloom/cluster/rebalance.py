"""Cluster administration CLI (ISSUE 9): ``python -m tpubloom.cluster``.

Subcommands (all take ``--nodes a:port,b:port,...``):

* ``init`` — seed a fresh cluster: split the 16384 slots into
  contiguous even ranges over the nodes and push the full assignment to
  EVERY node (``ClusterSetSlot assign``) at ``--epoch`` (default 1).
* ``info`` — print each node's ``ClusterSlots`` view (epoch, ranges,
  in-flight migrations) as JSON.
* ``migrate --slot S --to ADDR`` — move one slot: the owner (resolved
  from the freshest map) drives ``MigrateSlot``.
* ``rebalance [--plan-only]`` — plan the minimal slot moves toward an
  even spread over ``--nodes`` and drive them sequentially (each move
  is one synchronous ``MigrateSlot``); ``--plan-only`` prints the plan
  without moving anything. New (empty) nodes are first pushed the
  current map so their ownership checks answer ``MOVED`` instead of
  ``CLUSTERDOWN``.

Every move is the crash-safe migration of
:mod:`tpubloom.cluster.migrate`: re-running an interrupted ``rebalance``
resumes via snapshot probes + op-log tails, never double-applies.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Optional

import grpc

from tpubloom.cluster import slots as slots_mod
from tpubloom.server import protocol

_CHANNEL_OPTIONS = list(protocol.CHANNEL_OPTIONS)


def node_call(addr: str, method: str, req: dict, timeout: float = 600.0) -> dict:
    channel = grpc.insecure_channel(addr, options=_CHANNEL_OPTIONS)
    try:
        raw = channel.unary_unary(
            protocol.method_path(method),
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b,
        )(protocol.encode(req), timeout=timeout)
        return protocol.check(protocol.decode(raw))
    finally:
        channel.close()


def even_ranges(nodes: list) -> list:
    """Contiguous even split of the keyspace: ``[[start, end, addr],
    ...]`` (the same shape Redis Cluster's create does)."""
    n = len(nodes)
    per = slots_mod.NUM_SLOTS // n
    out = []
    start = 0
    for i, addr in enumerate(nodes):
        end = slots_mod.NUM_SLOTS - 1 if i == n - 1 else start + per - 1
        out.append([start, end, addr])
        start = end + 1
    return out


def freshest_map(nodes: list) -> Optional[dict]:
    """The highest-epoch ``ClusterSlots`` answer across the nodes."""
    best = None
    for addr in nodes:
        try:
            resp = node_call(addr, "ClusterSlots", {}, timeout=5.0)
        except (grpc.RpcError, protocol.BloomServiceError):
            continue
        if not resp.get("enabled"):
            continue
        if best is None or int(resp.get("epoch") or 0) > int(best["epoch"]):
            best = resp
    return best


def push_assignment(nodes: list, ranges: list, epoch: int) -> list:
    """``ClusterSetSlot assign`` to every node; returns the nodes that
    could not be reached (the caller decides whether that is fatal)."""
    unreachable = []
    for addr in nodes:
        try:
            node_call(
                addr, "ClusterSetSlot",
                {"assign": ranges, "epoch": epoch}, timeout=10.0,
            )
        except (grpc.RpcError, protocol.BloomServiceError):
            unreachable.append(addr)
    return unreachable


def plan_moves(owners: dict, nodes: list) -> list:
    """Minimal-ish move plan toward an even spread of the ASSIGNED
    slots: ``[(slot, from, to), ...]``. Slots owned by nodes OUTSIDE
    the target set all move; then excess slots flow from over- to
    under-target nodes."""
    total = len(owners)
    target_floor = total // len(nodes)
    remainder = total - target_floor * len(nodes)
    targets = {
        addr: target_floor + (1 if i < remainder else 0)
        for i, addr in enumerate(nodes)
    }
    held: dict = {addr: [] for addr in nodes}
    stray: list = []
    for slot in sorted(owners):
        addr = owners[slot]
        if addr in held:
            held[addr].append(slot)
        else:
            stray.append((slot, addr))
    moves: list = []
    donors: list = []
    for addr in nodes:
        excess = len(held[addr]) - targets[addr]
        if excess > 0:
            donors.extend((held[addr].pop(), addr) for _ in range(excess))
    pool = stray + donors
    for addr in nodes:
        while len(held[addr]) < targets[addr] and pool:
            slot, src = pool.pop()
            moves.append((slot, src, addr))
            held[addr].append(slot)
    return moves


def _cmd_init(args) -> int:
    ranges = even_ranges(args.nodes)
    missed = push_assignment(args.nodes, ranges, args.epoch)
    print(json.dumps({"assigned": ranges, "epoch": args.epoch,
                      "unreachable": missed}))
    return 1 if missed else 0


def _cmd_info(args) -> int:
    views = {}
    for addr in args.nodes:
        try:
            views[addr] = node_call(addr, "ClusterSlots", {}, timeout=5.0)
        except (grpc.RpcError, protocol.BloomServiceError) as e:
            views[addr] = {"ok": False, "error": str(e)}
    print(json.dumps(views, indent=2))
    return 0


def _cmd_migrate(args) -> int:
    view = freshest_map(args.nodes)
    if view is None:
        print("no node answered ClusterSlots; is --cluster enabled?",
              file=sys.stderr)
        return 1
    owners = slots_mod.expand_ranges(view["ranges"])
    src = owners.get(args.slot)
    if src is None:
        print(f"slot {args.slot} is unassigned", file=sys.stderr)
        return 1
    if src == args.to:
        print(json.dumps({"ok": True, "noop": True, "slot": args.slot}))
        return 0
    resp = node_call(src, "MigrateSlot", {"slot": args.slot, "target": args.to})
    print(json.dumps(resp))
    return 0


def _cmd_rebalance(args) -> int:
    view = freshest_map(args.nodes)
    if view is None:
        print("no node answered ClusterSlots; run `init` first?",
              file=sys.stderr)
        return 1
    owners = slots_mod.expand_ranges(view["ranges"])
    epoch = int(view.get("epoch") or 0)
    if len(owners) < slots_mod.NUM_SLOTS:
        print(
            f"warning: only {len(owners)}/{slots_mod.NUM_SLOTS} slots "
            f"assigned; unassigned slots stay CLUSTERDOWN",
            file=sys.stderr,
        )
    # every node (incl. fresh ones) needs the current map before moves
    # start, or its ownership checks answer CLUSTERDOWN mid-rebalance
    push_assignment(args.nodes, slots_mod.ranges_of(owners), epoch)
    moves = plan_moves(owners, args.nodes)
    print(json.dumps({"planned_moves": len(moves),
                      "moves": [list(m) for m in moves[:32]]}))
    if args.plan_only:
        return 0
    done = failed = 0
    for slot, src, dst in moves:
        try:
            node_call(src, "MigrateSlot", {"slot": slot, "target": dst})
            done += 1
        except (grpc.RpcError, protocol.BloomServiceError) as e:
            failed += 1
            print(f"move slot {slot} {src} -> {dst} failed: {e}",
                  file=sys.stderr)
    print(json.dumps({"moved": done, "failed": failed}))
    return 1 if failed else 0


def main(argv: Optional[list] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tpubloom.cluster",
        description="tpubloom cluster admin (Redis Cluster parity)",
    )
    sub = parser.add_subparsers(dest="cmd", required=True)

    def add_nodes(p):
        p.add_argument(
            "--nodes", required=True,
            type=lambda s: [a for a in s.split(",") if a],
            help="comma-separated cluster node addresses (host:port)",
        )

    p = sub.add_parser("init", help="seed an even slot assignment")
    add_nodes(p)
    p.add_argument("--epoch", type=int, default=1)
    p.set_defaults(fn=_cmd_init)

    p = sub.add_parser("info", help="print every node's slot-map view")
    add_nodes(p)
    p.set_defaults(fn=_cmd_info)

    p = sub.add_parser("migrate", help="move one slot to a target node")
    add_nodes(p)
    p.add_argument("--slot", type=int, required=True)
    p.add_argument("--to", required=True, metavar="HOST:PORT")
    p.set_defaults(fn=_cmd_migrate)

    p = sub.add_parser("rebalance", help="plan + drive moves to an even spread")
    add_nodes(p)
    p.add_argument("--plan-only", action="store_true")
    p.set_defaults(fn=_cmd_rebalance)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
