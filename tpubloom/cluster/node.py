"""Per-node cluster state: slot ownership checks, redirects, forwarding.

One :class:`ClusterState` hangs off a cluster-enabled
:class:`tpubloom.server.service.BloomService` (``--cluster``). The RPC
wrapper consults it on every keyed data-plane request:

* slot owned here → serve;
* slot owned elsewhere → ``MOVED <slot> <addr>`` (Redis parity: the
  client updates its slot cache and re-routes);
* slot **migrating** away and the filter is already gone → ``ASK <slot>
  <target>`` (one-shot redirect, no cache update);
* slot **importing** here → served only when the request carries the
  ``asking`` flag (the client's ASK follow-up, or the source's
  dual-write forward).

Migration support (see :mod:`tpubloom.cluster.migrate`):

* ``forwarding`` — filter name → target address: the dual-write window.
  After a mutating RPC commits (and clears its durability barrier), the
  wrapper forwards it to the target with the ORIGINAL rid and its
  source-log ``src_seq``; the entry stays after the handoff so
  straggling in-flight writes still forward (bounded: one entry per
  migrated filter).
* ``import gates`` — target-side exactly-once bookkeeping: a gate is
  seeded at snapshot install with the source seq the blob covers
  (``base``), and every applied forward records its ``src_seq``. A
  forward at or below the base, or already seen, short-circuits to an
  OK response without re-applying — counting filters never
  double-apply even when the tail replay and the live dual-write
  deliver the same record twice. (Concurrent duplicate deliveries share
  the original rid, so the PR-2/3 rid-dedup cache covers the race the
  gate cannot.)

Node→node RPCs (installs, forwards, SETSLOT pushes) go through
:meth:`ClusterState.call` — a cached-channel msgpack/gRPC hop that
declares itself to the runtime lock tracker (``note_blocking``), so a
forward under a filter or registry lock is a lint/runtime finding.
"""

from __future__ import annotations

import logging
import time
from typing import Optional

import grpc

from tpubloom.cluster import slots as slots_mod
from tpubloom.obs import counters as _counters
from tpubloom.server import protocol
from tpubloom.utils import locks

log = logging.getLogger("tpubloom.cluster")

#: Keyed data-plane methods subject to the slot-ownership check (every
#: method whose request names one filter). Control-plane and
#: migration-internal verbs are exempt on purpose.
KEYED_METHODS = frozenset(
    {
        "CreateFilter",
        "DropFilter",
        "InsertBatch",
        "QueryBatch",
        "DeleteBatch",
        "Clear",
        "Stats",
        "Checkpoint",
        # sketch-plane verbs (ISSUE 19) are keyed like their bloom
        # counterparts — same slot routing, same MOVED/ASK machinery
        "CFReserve",
        "CFAdd",
        "CFDel",
        "CFExists",
        "CMSInitByDim",
        "CMSIncrBy",
        "CMSQuery",
        "TopKReserve",
        "TopKAdd",
        "TopKList",
    }
)

#: Per-import-gate bound on remembered src seqs. src seqs are GLOBAL
#: source-log seqs (interleaved with other filters' records), so there
#: is no contiguity to compact on; instead, once the set doubles past
#: this bound the OLDEST half folds into the base watermark. Safe in
#: practice because forwards are synchronous-with-the-ack and re-driven
#: within bounded budgets: by the time 65536 NEWER claims exist, a
#: delivery of an older record has long since succeeded or been
#: re-driven — and the whole gate drops at handoff finalize anyway.
GATE_SEEN_MAX = 65536

#: How long a dual-write forward entry outlives its slot's handoff
#: (ISSUE 10 satellite, ROADMAP 1(d)). Entries must linger PAST the
#: finalize — straggling in-flight writes that raced the ownership flip
#: still forward through them — but before this, they lingered forever
#: and grew without bound on slot churn. After the TTL a forward for a
#: finalized slot answers MOVED at this node anyway (ownership already
#: flipped), so expiry loses nothing.
FORWARD_TTL_S = 60.0

_CHANNEL_OPTIONS = list(protocol.CHANNEL_OPTIONS)


class ClusterState:
    """Slot map + migration bookkeeping for one cluster node."""

    def __init__(
        self,
        self_addr: str,
        state_dir: Optional[str] = None,
        *,
        forward_ttl_s: float = FORWARD_TTL_S,
    ):
        self.self_addr = self_addr
        self._lock = locks.named_lock("cluster.state")
        self._store = slots_mod.SlotStore(state_dir) if state_dir else None
        self.slots = (self._store.load() if self._store else None) or slots_mod.SlotMap()
        #: filter name -> target addr: dual-write forwards (source side)
        self._forwarding: dict = {}
        #: filter name -> monotonic time its slot's handoff finalized;
        #: entries older than ``forward_ttl_s`` past that moment expire
        self._forward_retired: dict = {}
        self.forward_ttl_s = float(forward_ttl_s)
        #: filter name -> {"base": int, "seen": set} (target side)
        self._gates: dict = {}
        self._channels: dict = {}
        self._update_gauges_locked()

    # -- persistence / gauges -------------------------------------------------

    def _persist_locked(self) -> None:
        if self._store is None:
            return
        try:
            self._store.store(self.slots)
        except OSError:
            log.exception("cluster slot map persist failed (non-fatal)")

    def _update_gauges_locked(self) -> None:
        owned = sum(1 for a in self.slots.owners.values() if a == self.self_addr)
        _counters.set_gauge("cluster_slots_owned", owned)
        _counters.set_gauge("cluster_slots_migrating", len(self.slots.migrating))
        _counters.set_gauge("cluster_slots_importing", len(self.slots.importing))
        _counters.set_gauge("cluster_config_epoch", self.slots.epoch)

    # -- views ----------------------------------------------------------------

    def describe(self) -> dict:
        with self._lock:
            return {"self": self.self_addr, **self.slots.to_dict()}

    def owner(self, slot: int) -> Optional[str]:
        with self._lock:
            return self.slots.owner(slot)

    def epoch(self) -> int:
        with self._lock:
            return self.slots.epoch

    def is_importing(self, slot: int) -> bool:
        with self._lock:
            return slot in self.slots.importing

    def summary(self) -> dict:
        """Small Health-embeddable view (full map via ClusterSlots)."""
        with self._lock:
            return {
                "epoch": self.slots.epoch,
                "slots_owned": sum(
                    1 for a in self.slots.owners.values()
                    if a == self.self_addr
                ),
                "migrating": len(self.slots.migrating),
                "importing": len(self.slots.importing),
            }

    # -- the ownership check --------------------------------------------------

    def check(
        self,
        name: str,
        *,
        asking: bool = False,
        exists: bool = False,
        primary_address: Optional[str] = None,
    ) -> None:
        """Raise the redirect for one keyed request, or return None to
        serve it. ``exists`` = the filter is present in the local
        registry (the ASK decision on a migrating slot).
        ``primary_address`` lets a shard REPLICA serve slots its primary
        owns (reads route to replicas through the PR-4 topology client;
        the slot map names the shard by its primary)."""
        slot = slots_mod.key_slot(name)
        with self._lock:
            owner = self.slots.owner(slot)
            migrating_to = self.slots.migrating.get(slot)
            importing = slot in self.slots.importing
        if owner is None:
            raise protocol.BloomServiceError(
                "CLUSTERDOWN",
                f"slot {slot} is unassigned — the cluster map is "
                f"incomplete on this node",
                details={"slot": slot},
            )
        if owner == self.self_addr or (
            primary_address is not None and owner == primary_address
        ):
            if migrating_to is not None and not exists:
                # mid-migration, a filter no longer (or never) here
                # belongs to the target — one-shot redirect, Redis ASK
                _counters.incr("cluster_ask_redirects")
                raise protocol.BloomServiceError(
                    "ASK",
                    f"ASK {slot} {migrating_to}",
                    details={"slot": slot, "addr": migrating_to},
                )
            return
        if importing and asking:
            return  # the client's ASK follow-up / a migration forward
        _counters.incr("cluster_moved_redirects")
        raise protocol.BloomServiceError(
            "MOVED",
            f"MOVED {slot} {owner}",
            details={"slot": slot, "addr": owner, "epoch": self.slots.epoch},
        )

    # -- admin verbs (ClusterSetSlot) ----------------------------------------

    def set_slot(self, req: dict) -> dict:
        """``ClusterSetSlot`` handler logic (Redis ``CLUSTER SETSLOT``
        parity, plus a bulk ``assign`` form the rebalancer uses to push
        whole maps):

        * ``{"assign": [[start, end, addr], ...], "epoch": E}`` — adopt
          a full assignment at config epoch E (rejected when older than
          the current map);
        * ``{"slot": S, "state": "migrating", "addr": target}`` — mark S
          as handing off (source side);
        * ``{"slot": S, "state": "importing", "addr": source}`` — mark S
          as arriving (target side);
        * ``{"slot": S, "state": "node", "addr": owner, "epoch": E}`` —
          finalize: S now belongs to ``owner`` at epoch E; clears the
          migration marks (import GATES deliberately survive — see the
          inline note: straggler forwards still need them);
        * ``{"slot": S, "state": "stable"}`` — clear migration marks
          without changing ownership (abort).
        """
        with self._lock:
            if "assign" in req:
                epoch = int(req.get("epoch") or 0)
                if not self.slots.adopt_assignments(req["assign"], epoch):
                    raise protocol.BloomServiceError(
                        "STALE_EPOCH",
                        f"assignment epoch {epoch} predates the current "
                        f"map epoch {self.slots.epoch}",
                        details={"epoch": self.slots.epoch},
                    )
                self._persist_locked()
                self._update_gauges_locked()
                return {"ok": True, "epoch": self.slots.epoch}
            slot = int(req["slot"])
            state = req.get("state")
            addr = req.get("addr")
            if state in ("migrating", "importing"):
                # a mark issued under an OLDER view than this node's is
                # a stale source trying to re-open a finished handoff —
                # honoring it would let its stale blob overwrite state
                # the rightful owner has since absorbed writes into
                req_epoch = req.get("epoch")
                if req_epoch is not None and int(req_epoch) < self.slots.epoch:
                    raise protocol.BloomServiceError(
                        "STALE_EPOCH",
                        f"{state} mark for slot {slot} was issued under "
                        f"epoch {req_epoch}, but this node's map is at "
                        f"{self.slots.epoch}",
                        details={"epoch": self.slots.epoch},
                    )
                if state == "migrating":
                    self.slots.migrating[slot] = addr
                else:
                    self.slots.importing[slot] = addr
            elif state == "stable":
                self.slots.migrating.pop(slot, None)
                self.slots.importing.pop(slot, None)
            elif state == "node":
                epoch = int(req.get("epoch") or (self.slots.epoch + 1))
                if epoch < self.slots.epoch:
                    raise protocol.BloomServiceError(
                        "STALE_EPOCH",
                        f"slot epoch {epoch} predates the current map "
                        f"epoch {self.slots.epoch}",
                        details={"epoch": self.slots.epoch},
                    )
                self.slots.owners[slot] = addr
                self.slots.epoch = epoch
                self.slots.migrating.pop(slot, None)
                self.slots.importing.pop(slot, None)
                # import gates deliberately SURVIVE the finalize:
                # straggler forwards and same-rid re-drives that raced
                # the handoff still need the "is this record already
                # contained?" answer (a record the snapshot covered
                # must dup out, not re-apply). A later re-import of the
                # slot reseeds per filter; the src tag keeps a stale
                # gate from judging a DIFFERENT source's seq space.
                if addr == self.self_addr:
                    # the slot came (back) to us: stale dual-write
                    # forwards for its filters would bounce off our own
                    # ownership — drop them
                    for n in [
                        name for name in self._forwarding
                        if slots_mod.key_slot(name) == slot
                    ]:
                        del self._forwarding[n]
                        self._forward_retired.pop(n, None)
                else:
                    # handoff finalized AWAY: start the forward entries'
                    # retirement clock (ROADMAP 1(d) — they used to be
                    # kept forever and grew on churn). Stragglers keep
                    # forwarding until the TTL; the sweep reaps after.
                    now = time.monotonic()
                    for n in self._forwarding:
                        if slots_mod.key_slot(n) == slot:
                            self._forward_retired.setdefault(n, now)
                self._sweep_forwards_locked()
            else:
                raise protocol.BloomServiceError(
                    "INVALID_ARGUMENT",
                    f"unknown ClusterSetSlot state {state!r} (want "
                    f"assign | migrating | importing | node | stable)",
                )
            self._persist_locked()
            self._update_gauges_locked()
            return {"ok": True, "epoch": self.slots.epoch, "slot": slot}

    # -- migration bookkeeping ------------------------------------------------

    def begin_forwarding(self, name: str, target: str) -> None:
        with self._lock:
            self._forwarding[name] = target
            # a re-armed migration resets any earlier retirement clock
            self._forward_retired.pop(name, None)

    def _sweep_forwards_locked(self) -> None:
        """Reap forward entries whose handoff finalized more than
        ``forward_ttl_s`` ago (ISSUE 10 satellite): straggler in-flight
        writes have long since landed or been re-driven, and on slot
        churn the entries otherwise accumulate forever."""
        if not self._forward_retired:
            return
        cutoff = time.monotonic() - self.forward_ttl_s
        expired = [
            n for n, at in self._forward_retired.items() if at <= cutoff
        ]
        for n in expired:
            self._forward_retired.pop(n, None)
            self._forwarding.pop(n, None)
        if expired:
            _counters.incr("cluster_forward_entries_expired", len(expired))

    def forward_target(self, name: str) -> Optional[str]:
        """Where a committed write on ``name`` must dual-write to, or
        None. Falls back to the PERSISTED ``migrating`` mark when the
        in-memory entry is gone (a restarted source must not ack writes
        it no longer forwards — the marks survive the crash, the dict
        does not; such forwards fail ``IMPORT_NOT_READY`` on the target
        until the re-driven migration reseeds the gate, which turns a
        silent stranded-write into a client-visible retry). Entries of a
        FINALIZED handoff age out after ``forward_ttl_s``."""
        with self._lock:
            self._sweep_forwards_locked()
            target = self._forwarding.get(name)
            if target is None:
                target = self.slots.migrating.get(slots_mod.key_slot(name))
            return target

    def seed_gate(self, name: str, base: int) -> None:
        """Target side: start (or reset) the exactly-once gate for one
        migrating filter — ``base`` is the source seq the just-installed
        snapshot covers. The gate remembers WHICH source it judges
        (src seqs are per-source-log): a later re-import of the slot
        from a different node must not be judged against it."""
        with self._lock:
            self._gates[name] = {
                "base": int(base),
                "seen": set(),
                "src": self.slots.importing.get(slots_mod.key_slot(name)),
            }

    def gate_base(self, name: str) -> Optional[int]:
        """The gate's snapshot-coverage seq — None when there is no
        gate, or when the slot is importing from a DIFFERENT source
        than the gate was seeded by (stale gate: the resume probe then
        answers "nothing here" and the source re-ships the blob)."""
        with self._lock:
            gate = self._gates.get(name)
            if gate is None:
                return None
            src = self.slots.importing.get(slots_mod.key_slot(name))
            if src is not None and gate.get("src") != src:
                return None
            return gate["base"]

    def gate_claim(self, name: str, src_seq: int) -> bool:
        """Atomically CLAIM one forwarded record for apply; False when
        the record is already contained here (snapshot coverage, an
        earlier delivery, or a concurrent claim) — the caller answers a
        dup ack without re-applying. Check-and-record must be one step:
        a migration's op-log-tail replay and the live dual-write can
        deliver the SAME record concurrently, and two non-atomic checks
        would both pass and double-apply a counting filter."""
        with self._lock:
            gate = self._gates.get(name)
            if gate is None:
                return True  # no gate: not an importing filter
            if src_seq <= gate["base"] or src_seq in gate["seen"]:
                return False
            gate["seen"].add(int(src_seq))
            if len(gate["seen"]) > 2 * GATE_SEEN_MAX:
                # fold the OLDEST half into the base watermark (see the
                # GATE_SEEN_MAX note for why this is safe) — the seqs
                # are global log seqs, so contiguity-based compaction
                # would never remove anything
                ordered = sorted(gate["seen"])
                cut = ordered[len(ordered) // 2 - 1]
                gate["seen"] = {s for s in gate["seen"] if s > cut}
                gate["base"] = max(gate["base"], cut)
            return True

    def gate_unclaim(self, name: str, src_seq: int) -> None:
        """Roll a claim back after the APPLY itself failed (the record
        is not contained after all, so a re-delivery must pass)."""
        with self._lock:
            gate = self._gates.get(name)
            if gate is not None:
                gate["seen"].discard(int(src_seq))

    # -- node→node RPC --------------------------------------------------------

    def call(
        self, addr: str, method: str, req: dict, timeout: float = 30.0
    ) -> dict:
        """One msgpack/gRPC unary call to a peer node; raises
        :class:`protocol.BloomServiceError` on an error answer."""
        locks.note_blocking("cluster.link")
        with self._lock:
            ch = self._channels.get(addr)
            if ch is None:
                ch = grpc.insecure_channel(addr, options=_CHANNEL_OPTIONS)  # lint: allow(blocking-under-lock): channel construction is lazy + non-connecting; the actual RPC below runs outside the lock
                self._channels[addr] = ch
        raw = ch.unary_unary(
            protocol.method_path(method),
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b,
        )(protocol.encode(req), timeout=timeout)
        return protocol.check(protocol.decode(raw))

    def close(self) -> None:
        with self._lock:
            channels = list(self._channels.values())
            self._channels.clear()
        for ch in channels:
            ch.close()
