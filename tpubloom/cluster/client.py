"""Cluster-aware Python client (ISSUE 9).

Routes every keyed call by ``key_slot(filter_name)`` through a cached
slot→shard map (fetched via ``ClusterSlots``; Redis cluster-client
parity) and heals the two redirect kinds the servers emit:

* ``MOVED <slot> <addr>`` — ownership changed (a finalized migration or
  a stale map): the cache entry is updated, the full map re-fetched
  best-effort, and the call retried at the new owner;
* ``ASK <slot> <addr>`` — slot mid-migration and the filter already
  lives at the target: ONE follow-up call flagged ``asking`` goes to
  the target, with no cache update (the source still owns the slot).

Each shard is a full PR-4 :class:`~tpubloom.server.client.BloomClient`
— pass ``shards=[{"sentinels": [...]}, ...]`` and every shard keeps its
own sentinel-managed primary/replica set: failovers inside a shard are
healed by that shard's client (sentinel refresh, rid-safe write
re-drive), while slot moves between shards are healed here. With
``topology_push=True`` each sentinel-backed shard also subscribes to
the sentinels' ``TopologyEvents`` stream (ISSUE 9 satellite) so a
failover re-points the shard client without waiting for an error.
"""

from __future__ import annotations

import time
from typing import Optional, Sequence

import grpc

from tpubloom.cluster import slots as slots_mod
from tpubloom.obs import counters as obs_counters
from tpubloom.obs import trace as trace_mod
from tpubloom.server import protocol
from tpubloom.server.client import BloomClient
from tpubloom.utils import locks

#: keyed-call retry budget across MOVED/CLUSTERDOWN re-routes.
MAX_REDIRECTS = 8


class ClusterClient:
    """Blocking cluster client; one per cluster, filters addressed by name."""

    def __init__(
        self,
        startup_nodes: Optional[Sequence[str]] = None,
        *,
        shards: Optional[Sequence[dict]] = None,
        topology_push: bool = False,
        **client_kwargs,
    ):
        """``startup_nodes`` — any cluster node addresses to bootstrap
        the slot map from. ``shards`` — richer per-shard config:
        ``{"primary": addr}`` and/or ``{"sentinels": [addr, ...]}``
        entries; sentinel-backed shards survive their own failovers via
        the PR-4 topology machinery. ``client_kwargs`` pass through to
        every underlying :class:`BloomClient` (timeouts, retries,
        breaker...)."""
        self._kwargs = dict(client_kwargs)
        self._kwargs.setdefault("breaker_threshold", 0)
        self._lock = locks.named_lock("cluster.client")
        #: rid of the newest logical keyed call (shared by its hops)
        self.last_rid: Optional[str] = None
        #: slot -> shard address (the server-side map's owner strings)
        self._slot_owner: dict = {}
        self.epoch = 0
        self._shard_clients: list = []
        self._direct: dict = {}
        self._startup = list(startup_nodes or ())
        for shard in shards or ():
            sentinels = list(shard.get("sentinels") or ())
            if sentinels:
                c = BloomClient(
                    shard.get("primary"), sentinels=sentinels, **self._kwargs
                )
                if topology_push:
                    c.enable_topology_push()
            else:
                c = BloomClient(shard["primary"], **self._kwargs)
            self._shard_clients.append(c)
        self.refresh_slots()

    # -- slot map / routing ---------------------------------------------------

    def _candidates(self) -> list:
        with self._lock:
            direct = list(self._direct.values())
        return self._shard_clients + direct

    def refresh_slots(self) -> bool:
        """Re-fetch the slot map from the first answering node; adopt it
        iff its config epoch is not older than the cached one."""
        probes = list(self._candidates())
        with self._lock:
            known = set(self._slot_owner.values())
        for addr in list(self._startup) + sorted(known):
            if all(c.address != addr for c in probes):
                probes.append(self._client_for(addr))
        for client in probes:
            try:
                resp = client._rpc("ClusterSlots", {})
            except (grpc.RpcError, protocol.BloomServiceError):
                continue
            if not resp.get("enabled") or not resp.get("ranges"):
                continue
            epoch = int(resp.get("epoch") or 0)
            with self._lock:
                if epoch < self.epoch:
                    continue
                self.epoch = epoch
                self._slot_owner = slots_mod.expand_ranges(resp["ranges"])
            obs_counters.incr("client_slot_refreshes")
            return True
        return False

    def _client_for(self, addr: str) -> BloomClient:
        """The shard client currently serving ``addr`` (shard clients
        re-point themselves across failovers), else a cached direct
        client."""
        for c in self._shard_clients:
            if c.address == addr:
                return c
        with self._lock:
            c = self._direct.get(addr)
        if c is not None:
            return c
        # maybe a shard failed over and addr is its NEW primary — let
        # sentinel-backed shards refresh before dialing directly
        for c in self._shard_clients:
            if c.sentinels:
                c.refresh_topology()
                if c.address == addr:
                    return c
        c = BloomClient(addr, **self._kwargs)
        with self._lock:
            self._direct[addr] = c
        return c

    def slot_of(self, name: str) -> int:
        return slots_mod.key_slot(name)

    def _owner_addr(self, slot: int) -> str:
        with self._lock:
            addr = self._slot_owner.get(slot)
        if addr is None:
            self.refresh_slots()
            with self._lock:
                addr = self._slot_owner.get(slot)
        if addr is None:
            raise protocol.BloomServiceError(
                "CLUSTERDOWN",
                f"slot {slot} has no known owner (no node answered "
                f"ClusterSlots with an assignment)",
                details={"slot": slot},
            )
        return addr

    @staticmethod
    def _hop_req(client: BloomClient, req: dict, keys, extra=None) -> dict:
        """One hop's request under the TARGET connection's negotiated
        encoding (ISSUE 14 satellite — the named PR-10 seam): key
        batches ride the per-shard ``BloomClient``'s zero-copy
        ``keys_fixed`` path when that shard's Health advertised it,
        falling back to the msgpack list per connection. Encoding per
        HOP matters: redirect targets negotiate independently."""
        r = dict(req)
        if keys is not None:
            r = client._encode_keys(r, keys)
        if extra:
            r.update(extra)
        return r

    def _keyed(
        self,
        method: str,
        req: dict,
        *,
        rid: Optional[str] = None,
        keys=None,
    ) -> dict:
        """Route one keyed request by its filter name, healing
        MOVED/ASK/CLUSTERDOWN along the way. One logical call = one rid
        across every redirect hop and re-drive (so a hop that applied
        before failing answers its replay from the dedup cache).
        ``keys`` (raw, unencoded) are folded into each hop's request
        under that hop's negotiated wire encoding."""
        from tpubloom.obs.context import new_rid

        rid = rid or new_rid()
        self.last_rid = rid
        slot = slots_mod.key_slot(req["name"])
        last: Optional[protocol.BloomServiceError] = None
        for attempt in range(MAX_REDIRECTS):
            try:
                # inside the try: a client-side CLUSTERDOWN (map gap
                # mid-rebalance) must burn a retry + backoff like the
                # server-sent one, not abort the whole budget
                addr = self._owner_addr(slot)
                client = self._client_for(addr)
                return client._rpc(
                    method, self._hop_req(client, req, keys), rid=rid
                )
            except protocol.BloomServiceError as e:
                last = e
                if e.code == "MOVED":
                    obs_counters.incr("client_moved_redirects")
                    new = e.details.get("addr")
                    with self._lock:
                        # the redirecting node's epoch is authoritative
                        # for this slot: adopting it keeps the refresh
                        # below from re-adopting an equal-epoch STALE
                        # map off a node the migration never touched
                        self.epoch = max(
                            self.epoch, int(e.details.get("epoch") or 0)
                        )
                        if new:
                            self._slot_owner[slot] = new
                    # the whole map probably changed (a finalized
                    # migration bumps the epoch) — refresh opportunistically,
                    # then RE-apply the hint: it is fresher than any map
                    # a lagging node could have answered with
                    self.refresh_slots()
                    if new:
                        with self._lock:
                            self._slot_owner[slot] = new
                    continue
                if e.code == "ASK":
                    obs_counters.incr("client_ask_redirects")
                    target = self._client_for(e.details["addr"])
                    return target._rpc(
                        method,
                        self._hop_req(target, req, keys, {"asking": True}),
                        rid=rid,
                    )
                if e.code == "CLUSTERDOWN":
                    self.refresh_slots()
                    time.sleep(0.05 * (attempt + 1))
                    continue
                if e.code == "MIGRATE_FORWARD_FAILED":
                    # the write APPLIED on the source but its dual-write
                    # forward didn't land (usually the snapshot-install
                    # window of a live migration): re-drive under the
                    # SAME rid — the source answers the replay from its
                    # dedup cache / idempotent apply and forwards again;
                    # the target's seq gate keeps it exactly-once
                    return self._redrive(
                        client, method, req, rid, e.details.get("src_seq"),
                        keys=keys,
                    )
                raise
        if last is None:  # pragma: no cover — every continue sets last
            last = protocol.BloomServiceError(
                "CLUSTERDOWN", f"no route to slot {slot} after "
                f"{MAX_REDIRECTS} attempts"
            )
        raise last

    def _redrive(
        self,
        client: BloomClient,
        method: str,
        req: dict,
        rid: str,
        src_seq=None,
        *,
        keys=None,
    ) -> dict:
        # the rid comes from the enclosing _keyed call, NOT from
        # client.last_rid — a concurrent call on the same shard client
        # would clobber that between the failure and the re-drive.
        # src_seq (the applied record's source-log seq, from the
        # failure's details) rides along so a post-finalize MOVED
        # follow-up is still judged by the new owner's import gate — a
        # record the migrated snapshot already contains must dup out,
        # not apply twice.
        last: Exception = protocol.BloomServiceError(
            "MIGRATE_FORWARD_FAILED", "re-drive never attempted"
        )
        w0, t0 = time.time(), time.perf_counter()
        # same deterministic decision the original hop made for this
        # rid; the re-drive bypasses _rpc (it must not re-mint a rid),
        # so it carries the forced trace field itself — a re-driven
        # write must stay capturable exactly in the migration windows
        # this path exists for — and records its own hop span, follow-
        # up hop included
        traced = client.trace_sample > 0 and trace_mod.hit(
            rid, client.trace_sample
        )
        hop = trace_mod.new_span_id() if traced else None
        extra: dict = {"rid": rid}
        if traced:
            extra["trace"] = {"forced": True, "span": hop}
        # ONE hop span covers the whole re-drive window, recorded in
        # the finally so a FAILED re-drive (the case a post-mortem
        # needs most) still shows up — _rpc's finally discipline
        hop_attrs = {"method": method, "addr": client.address,
                     "kind": "redrive", "code": "FAILED"}
        try:
            for i in range(30):
                time.sleep(min(1.0, 0.05 * (i + 1)))
                try:
                    resp = client._call_once(
                        method, self._hop_req(client, req, keys, extra)
                    )
                    hop_attrs["code"] = "OK"
                    return resp
                except protocol.BloomServiceError as e:
                    last = e
                    hop_attrs["code"] = e.code
                    if e.code == "MIGRATE_FORWARD_FAILED":
                        if e.details.get("src_seq") is not None:
                            src_seq = e.details["src_seq"]
                        continue  # install in flight — keep re-driving
                    if e.code in ("MOVED", "ASK"):
                        # the handoff finalized mid-re-drive: land the
                        # SAME rid + src_seq on the new owner (its
                        # gate/dedup absorbs a record that already made
                        # it across)
                        target = self._client_for(e.details["addr"])
                        follow = self._hop_req(
                            target, req, keys, {**extra, "asking": True}
                        )
                        if src_seq is not None:
                            follow["src_seq"] = int(src_seq)
                        resp = target._call_once(method, follow)
                        hop_attrs.update(
                            addr=target.address, kind="redrive-follow",
                            code="OK",
                        )
                        return resp
                    raise
                except grpc.RpcError as e:
                    last = e
                    hop_attrs["code"] = "UNAVAILABLE"
                    continue
            raise last
        finally:
            if traced:
                trace_mod.record_span(
                    "client.hop", rid=rid, span=hop, start=w0,
                    duration_s=time.perf_counter() - t0, attrs=hop_attrs,
                )

    # -- keyed operations (the BloomClient surface, routed) -------------------

    @staticmethod
    def _durability(req: dict, min_replicas, timeout_ms) -> dict:
        if min_replicas is not None:
            req["min_replicas"] = int(min_replicas)
        if timeout_ms is not None:
            req["min_replicas_timeout_ms"] = int(timeout_ms)
        return req

    def create_filter(
        self,
        name: str,
        *,
        capacity: Optional[int] = None,
        error_rate: Optional[float] = None,
        config: Optional[dict] = None,
        exist_ok: bool = False,
        restore: bool = True,
        **options,
    ) -> dict:
        req: dict = {"name": name, "exist_ok": exist_ok, "restore": restore}
        if config is not None:
            req["config"] = config
        else:
            req["capacity"] = capacity
            req["error_rate"] = error_rate
            req["options"] = options
        return self._keyed("CreateFilter", req)

    def drop_filter(self, name: str, *, final_checkpoint: bool = True) -> dict:
        return self._keyed(
            "DropFilter", {"name": name, "final_checkpoint": final_checkpoint}
        )

    def insert_batch(
        self,
        name: str,
        keys,
        *,
        return_presence: bool = False,
        min_replicas: Optional[int] = None,
        min_replicas_timeout_ms: Optional[int] = None,
    ):
        req = self._durability(
            {"name": name}, min_replicas, min_replicas_timeout_ms
        )
        if not return_presence:
            return self._keyed("InsertBatch", req, keys=keys)["n"]
        req["return_presence"] = True
        resp = self._keyed("InsertBatch", req, keys=keys)
        if resp.get("migrate_dup") and "presence" not in resp:
            # the write landed exactly once, but this hop was absorbed
            # by the new owner's import gate and the pre-batch presence
            # bits were computed on the migration source — surface the
            # distinction instead of a generic field-missing error
            raise protocol.BloomServiceError(
                "PRESENCE_UNAVAILABLE",
                f"insert on {name!r} applied exactly once across a slot "
                f"migration, but its pre-batch presence bits are not "
                f"reconstructable at the new owner — re-query if needed",
            )
        return BloomClient._unpack_bool(resp, "presence")

    def include_batch(self, name: str, keys):
        resp = self._keyed("QueryBatch", {"name": name}, keys=keys)
        return BloomClient._unpack_bool(resp, "hits")

    def delete_batch(
        self,
        name: str,
        keys,
        *,
        min_replicas: Optional[int] = None,
        min_replicas_timeout_ms: Optional[int] = None,
    ) -> int:
        req = self._durability(
            {"name": name}, min_replicas, min_replicas_timeout_ms
        )
        return self._keyed("DeleteBatch", req, keys=keys)["n"]

    def insert(self, name: str, key) -> None:
        self.insert_batch(name, [key])

    def include(self, name: str, key) -> bool:
        return bool(self.include_batch(name, [key])[0])

    def clear(self, name: str, **durability) -> None:
        self._keyed(
            "Clear",
            self._durability(
                {"name": name},
                durability.get("min_replicas"),
                durability.get("min_replicas_timeout_ms"),
            ),
        )

    def stats(self, name: str) -> dict:
        return self._keyed("Stats", {"name": name})["stats"]

    def checkpoint(self, name: str, *, wait: bool = True) -> dict:
        return self._keyed("Checkpoint", {"name": name, "wait": wait})

    # -- cluster-wide views ---------------------------------------------------

    def list_filters(self) -> list:
        """Union of every shard's filter list."""
        out: set = set()
        for client in self._unique_shard_clients():
            out.update(client.list_filters())
        return sorted(out)

    def health(self) -> dict:
        """Per-shard Health, keyed by shard address."""
        return {
            c.address: c.health() for c in self._unique_shard_clients()
        }

    def cluster_slots(self) -> dict:
        """The adopted map (epoch + slot ranges), client-side view."""
        with self._lock:
            return {
                "epoch": self.epoch,
                "ranges": slots_mod.ranges_of(self._slot_owner),
            }

    def trace(
        self,
        rid: Optional[str] = None,
        *,
        name: Optional[str] = None,
        slot: Optional[int] = None,
    ) -> dict:
        """Cross-shard trace assembly (ISSUE 15): merge this process's
        own client spans with ``TraceGet`` answers from every shard
        (primaries AND their configured replicas), then follow the
        trace ids the returned spans introduce — a coalescer flush span
        links the rid but its children (kernel phases, barrier) and the
        replica applies of the merged record live under the FLUSH trace
        id, one fan-out round away. Returns ``{rid, spans, roots,
        components}`` — ``components`` from :func:`tpubloom.obs.trace.
        assemble`; ONE component is the healthy single-call shape.

        ISSUE 16 satellites: pass ``name`` (the filter the call keyed)
        or ``slot`` directly and the fan-out narrows to the slot's
        owning shard — one ``TraceGet`` round trip instead of the full
        fleet, which is what a post-mortem script chasing thousands of
        rids needs. The hint degrades safely: an unmapped slot
        (CLUSTERDOWN) falls back to the full fan-out. Assembly passes
        ``rid`` through so a multi-hop MOVED/ASK/re-drive chain comes
        back as ONE tree under a synthetic ``client.call`` root (the
        synthetic span joins the returned ``spans``)."""
        rid = rid or self.last_rid
        if not rid:
            return {"rid": None, "spans": [], "roots": [], "components": []}
        if slot is None and name is not None:
            slot = slots_mod.key_slot(name)
        hinted: Optional[list] = None
        if slot is not None:
            try:
                hinted = [self._client_for(self._owner_addr(int(slot)))]
            except protocol.BloomServiceError:
                hinted = None  # no adopted map — full fan-out is the hint
        merged: dict = {
            (s.get("rid"), s.get("span")): s
            for s in trace_mod.get_trace(rid)
        }
        pending, done = {rid}, set()
        # bounded discovery: rid -> linked flush traces -> (nothing new)
        for _round in range(3):
            fresh = pending - done
            if not fresh:
                break
            for tid in sorted(fresh):
                done.add(tid)
                targets = (
                    hinted
                    if hinted is not None
                    else self._unique_shard_clients()
                )
                for client in targets:
                    for s in client.trace_get_fan(tid):
                        merged[(s.get("rid"), s.get("span"))] = s
                        if s.get("rid"):
                            pending.add(s["rid"])
                        for link in s.get("links") or ():
                            if link.get("rid"):
                                pending.add(link["rid"])
        spans = sorted(
            merged.values(), key=lambda s: (s.get("start") or 0.0)
        )
        tree = trace_mod.assemble(spans, rid=rid)
        if tree.get("synthetic"):
            spans = spans + [tree["synthetic"]]
        return {
            "rid": rid,
            "spans": spans,
            "roots": tree["roots"],
            "components": tree["components"],
        }

    def _unique_shard_clients(self) -> list:
        """One client per distinct owner address in the adopted map
        (falling back to the configured shard clients when no map)."""
        with self._lock:
            addrs = sorted(set(self._slot_owner.values()))
        if not addrs:
            return list(self._shard_clients)
        return [self._client_for(a) for a in addrs]

    def close(self) -> None:
        for c in self._shard_clients:
            c.close()
        with self._lock:
            direct = list(self._direct.values())
            self._direct.clear()
        for c in direct:
            c.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
