"""Hash-slot keyspace partitioning (ISSUE 9 — Redis Cluster parity).

Redis Cluster shards its keyspace into 16384 **hash slots**: ``slot =
CRC16(key) mod 16384``, with ``{hash tag}`` extraction so callers can
pin related keys to one slot. tpubbloom's keyed unit is the *filter
name*, so the slot of every RPC is ``key_slot(req["name"])`` — one
filter lives wholly in one slot, and a slot (with all its filters) is
the unit of ownership and migration.

:class:`SlotMap` is one node's view of WHO OWNS WHAT:

* ``owners`` — slot → shard address (the shard primary's announced
  address; a shard's replicas serve the same slots through the PR-4
  topology machinery);
* ``migrating`` / ``importing`` — slots mid-handoff (Redis ``CLUSTER
  SETSLOT MIGRATING/IMPORTING`` parity): the *source* keeps serving
  existing filters and answers ``ASK`` for missing ones, the *target*
  only serves requests flagged ``asking``;
* ``epoch`` — the map's config epoch (Redis config-epoch parity): every
  finalized handoff bumps it, and a node only adopts assignments at or
  past its current epoch, so a stale rebalancer replaying old moves
  cannot rewind ownership.

:class:`SlotStore` persists the map as a CRC32C-checked JSON file
(``cluster_slots.json`` via :mod:`tpubloom.utils.crcjson`) beside the op
log: corruption reads as "no map" — the node then refuses keyed traffic
with ``CLUSTERDOWN`` until the rebalancer re-pushes assignments, which
is the safe direction (serve nothing rather than the wrong shard's
keys).
"""

from __future__ import annotations

from typing import Optional

from tpubloom.utils import crcjson

#: Redis Cluster's slot count — kept verbatim so parity tables, hash
#: tags, and operator intuition transfer 1:1.
NUM_SLOTS = 16384

SLOTS_FILE = "cluster_slots.json"


def _crc16_table() -> list:
    """CRC16-CCITT (XMODEM: poly 0x1021, init 0) — the exact polynomial
    Redis Cluster keys slots with."""
    table = []
    for byte in range(256):
        crc = byte << 8
        for _ in range(8):
            crc = ((crc << 1) ^ 0x1021 if crc & 0x8000 else crc << 1) & 0xFFFF
        table.append(crc)
    return table


_CRC16_TABLE = _crc16_table()


def crc16(data: bytes) -> int:
    crc = 0
    for b in data:
        crc = ((crc << 8) & 0xFFFF) ^ _CRC16_TABLE[((crc >> 8) ^ b) & 0xFF]
    return crc


def key_slot(name: str | bytes) -> int:
    """Slot of one filter name, with Redis hash-tag semantics: when the
    name contains ``{...}`` with a non-empty body, only the body hashes
    — ``user:{42}:seen`` and ``user:{42}:blocked`` share a slot, so a
    tenant's filters migrate together."""
    raw = name.encode() if isinstance(name, str) else bytes(name)
    start = raw.find(b"{")
    if start >= 0:
        end = raw.find(b"}", start + 1)
        if end > start + 1:  # non-empty tag only, Redis rule
            raw = raw[start + 1 : end]
    return crc16(raw) % NUM_SLOTS


def ranges_of(owners: dict) -> list:
    """Compress ``{slot: addr}`` into sorted ``[[start, end, addr],
    ...]`` (inclusive ends) — the wire/persist form; 16384 per-slot
    entries would bloat every ClusterSlots answer."""
    out: list = []
    for slot in sorted(owners):
        addr = owners[slot]
        if out and out[-1][1] == slot - 1 and out[-1][2] == addr:
            out[-1][1] = slot
        else:
            out.append([slot, slot, addr])
    return out


def expand_ranges(ranges) -> dict:
    owners: dict = {}
    for start, end, addr in ranges or ():
        for slot in range(int(start), int(end) + 1):
            owners[slot] = addr
    return owners


class SlotMap:
    """One node's slot-ownership view (plain data + epoch discipline;
    thread-safety lives in :class:`tpubloom.cluster.node.ClusterState`,
    which owns the single instance per process)."""

    def __init__(self):
        self.epoch = 0
        #: slot -> owning shard address
        self.owners: dict = {}
        #: slot -> target address (this node is handing the slot off)
        self.migrating: dict = {}
        #: slot -> source address (this node is receiving the slot)
        self.importing: dict = {}

    def owner(self, slot: int) -> Optional[str]:
        return self.owners.get(slot)

    def assign(self, slots, addr: str) -> None:
        for slot in slots:
            self.owners[int(slot)] = addr

    def adopt_assignments(self, ranges, epoch: int) -> bool:
        """Adopt a full assignment push iff it is not older than what we
        hold (the config-epoch rule); True iff adopted."""
        if int(epoch) < self.epoch:
            return False
        self.epoch = int(epoch)
        self.owners = expand_ranges(ranges)
        return True

    def to_dict(self) -> dict:
        return {
            "epoch": self.epoch,
            "ranges": ranges_of(self.owners),
            "migrating": {str(s): a for s, a in sorted(self.migrating.items())},
            "importing": {str(s): a for s, a in sorted(self.importing.items())},
        }

    @classmethod
    def from_dict(cls, data: dict) -> "SlotMap":
        m = cls()
        m.epoch = int(data.get("epoch") or 0)
        m.owners = expand_ranges(data.get("ranges"))
        m.migrating = {int(s): a for s, a in (data.get("migrating") or {}).items()}
        m.importing = {int(s): a for s, a in (data.get("importing") or {}).items()}
        return m


class SlotStore:
    """CRC-checked persistence of the slot map (corruption = no map =
    ``CLUSTERDOWN`` until re-pushed — never the wrong shard's keys)."""

    _FIELDS = ("epoch", "ranges", "migrating", "importing")

    def __init__(self, directory: str):
        import os

        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self.path = os.path.join(directory, SLOTS_FILE)

    def load(self) -> Optional[SlotMap]:
        data = crcjson.load(self.path, self._FIELDS)
        if data is None:
            return None
        try:
            return SlotMap.from_dict(data)
        except (ValueError, TypeError):
            return None

    def store(self, slot_map: SlotMap) -> None:
        crcjson.store(self.path, slot_map.to_dict())


__all__ = [
    "NUM_SLOTS",
    "crc16",
    "key_slot",
    "ranges_of",
    "expand_ranges",
    "SlotMap",
    "SlotStore",
]
