"""Live slot migration — source-side driver (ISSUE 9 tentpole).

Moving slot S from node A (owner) to node B reuses the PR-3/5 resync
machinery node→node: each filter in S ships as one
``ckpt.snapshot_blob`` stamped with the source-log seq it covers, and
everything after that seq reaches B through **dual-write forwarding** —
the same "snapshot + tail" shape the primary→replica full resync uses,
with the op-log tail taking over when a migration resumes.

The protocol, per slot:

1. **Mark** — A sets ``migrating[S] = B`` locally and pushes
   ``importing[S] = A`` to B (``ClusterSetSlot``). From here on, A
   answers ``ASK S B`` for filters of S it does not hold, and B serves
   S only for ``asking``-flagged requests.
2. **Per filter** — under the filter's op lock A snapshots the blob,
   records ``snap_seq`` (the filter's applied source-log seq), and arms
   the dual-write forward *before releasing the lock*: every mutating
   RPC that commits after the snapshot forwards to B (original rid +
   its ``src_seq``) before it is acked, so no acked write can exist
   only on A. The blob then installs on B (``MigrateInstall``), which
   seeds B's exactly-once gate at ``snap_seq``.

   **Resume** (the SIGKILL-the-source case): if B already holds the
   filter from an interrupted migration, A probes its gate base and —
   when the source log still has that cursor — replays just the op-log
   tail for that filter instead of re-shipping the blob. Records the
   snapshot or an earlier delivery already covers are skipped by B's
   seq gate; concurrent duplicate deliveries share the original rid, so
   the rid-dedup cache keeps counting filters from double-applying.
3. **Finalize** — B adopts ownership at ``epoch+1`` (``ClusterSetSlot
   node``), then A does; A now answers ``MOVED S B`` and retires its
   local copies with logged drops (so A's shard replicas drop them
   too). Forward entries stay armed for straggling in-flight writes —
   they land on B as ordinary (owner-served) writes.

Fault points: ``cluster.migrate_send`` fires before every install/tail
send on the source; ``cluster.migrate_apply`` fires in the target's
``MigrateInstall``/gated-forward paths.

Known limitation (deliberate scope cut, tracked in ROADMAP item 1):
forwards are exactly-once (seq gate + rid dedup) but NOT commit-order
serialized — they run per-RPC outside all locks. Two concurrent writes
to the SAME key from different clients inside one migration window
(e.g. an insert racing a delete on a counting filter) can therefore
apply in opposite orders on source and target and settle differently.
This is an app-level race even without migration (the filter lock
arbitrates it invisibly); Redis sidesteps it by blocking the key during
MIGRATE, which this design trades away for a non-blocking window.
Workloads that need cross-client same-key ordering should quiesce those
keys during a rebalance.
"""

from __future__ import annotations

import logging

import grpc

from tpubloom import faults
from tpubloom.cluster import slots as slots_mod
from tpubloom.obs import counters as _counters
from tpubloom.obs import flight as obs_flight
from tpubloom.obs import trace as obs_trace
from tpubloom.server import protocol

log = logging.getLogger("tpubloom.cluster")

#: gRPC budget for one snapshot install (blobs can be filter-sized).
INSTALL_TIMEOUT_S = 120.0
FORWARD_TIMEOUT_S = 30.0


def migrate_slot(service, slot: int, target: str) -> dict:
    """Drive the migration of one slot to ``target`` (the
    ``MigrateSlot`` handler body; runs synchronously in the RPC
    thread, like Redis ``MIGRATE``)."""
    cluster = service.cluster
    if not isinstance(slot, int) or not 0 <= slot < slots_mod.NUM_SLOTS:
        raise protocol.BloomServiceError(
            "INVALID_ARGUMENT", f"slot must be in [0, {slots_mod.NUM_SLOTS})"
        )
    if service.oplog is None:
        # the exactly-once handoff is seq-gated by SOURCE-LOG seqs:
        # without a log the dual-write forwards would carry no src_seq
        # and the snapshot-overlap window could double-apply counting
        # filters — refuse, like --min-replicas-to-write does
        raise protocol.BloomServiceError(
            "UNSUPPORTED",
            "slot migration requires an op log on the source (start the "
            "server with --repl-log-dir): dual-write forwards are "
            "exactly-once only when seq-stamped from it",
        )
    if not target or target == cluster.self_addr:
        raise protocol.BloomServiceError(
            "INVALID_ARGUMENT", f"migration target {target!r} must be a "
            f"different node"
        )
    owner = cluster.owner(slot)
    if owner != cluster.self_addr:
        raise protocol.BloomServiceError(
            "MOVED" if owner else "CLUSTERDOWN",
            f"slot {slot} is owned by {owner!r}, not this node",
            details={"slot": slot, "addr": owner},
        )
    # flight recorder (ISSUE 15): migrations are exactly the lifecycle
    # events a post-mortem of a rebalance gone wrong needs sequenced
    obs_flight.note("migration", slot=int(slot), target=target,
                    stage="start")
    # 1. mark both sides (idempotent on re-drive; the epoch stamp lets
    # an up-to-date target refuse a STALE source's re-opened handoff)
    cluster.set_slot(
        {"slot": slot, "state": "migrating", "addr": target,
         "epoch": cluster.epoch()}
    )
    cluster.call(
        target,
        "ClusterSetSlot",
        {"slot": slot, "state": "importing", "addr": cluster.self_addr,
         "epoch": cluster.epoch()},
    )
    with service._lock:
        tenants = set(service._filters)
    if service.storage is not None:
        # paged tenants (ISSUE 14) belong to the slot too — an evicted
        # filter that silently stayed behind would be unreachable the
        # moment the slot finalizes at the new owner
        tenants.update(service.storage.names())
    names = sorted(n for n in tenants if slots_mod.key_slot(n) == slot)
    stats = {"snapshots": 0, "tail_records": 0}
    for name in names:
        _migrate_filter(service, name, target, stats)
    # 3. finalize: target first (Redis SETSLOT NODE order), then local —
    # between the two flips both nodes route traffic to the target
    new_epoch = cluster.epoch() + 1
    cluster.call(
        target,
        "ClusterSetSlot",
        {"slot": slot, "state": "node", "addr": target, "epoch": new_epoch},
    )
    cluster.set_slot(
        {"slot": slot, "state": "node", "addr": target, "epoch": new_epoch}
    )
    # 4. retire the local copies with LOGGED drops (shard replicas drop
    # too). Forward entries stay armed: an in-flight write that raced
    # the flip still reaches the target.
    for name in names:
        try:
            service.DropFilter({"name": name, "final_checkpoint": False})
        except protocol.BloomServiceError:
            log.exception("retiring migrated filter %r failed", name)
    _counters.incr("cluster_migrations_completed")
    _counters.incr("cluster_filters_migrated", len(names))
    obs_flight.note("migration", slot=int(slot), target=target,
                    stage="finalized", epoch=int(new_epoch),
                    filters=len(names))
    log.info(
        "slot %d migrated to %s at epoch %d (%d filter(s), %d snapshot(s), "
        "%d tail record(s))",
        slot, target, new_epoch, len(names), stats["snapshots"],
        stats["tail_records"],
    )
    return {
        "ok": True,
        "slot": slot,
        "target": target,
        "epoch": new_epoch,
        "filters_moved": len(names),
        **stats,
    }


def _migrate_filter(service, name: str, target: str, stats: dict) -> None:
    """Move one filter: resume via the op-log tail when the target
    already holds it, else snapshot + arm the dual-write."""
    from tpubloom import checkpoint as ckpt

    cluster = service.cluster
    faults.fire("cluster.migrate_send")
    base = None
    try:
        probe = cluster.call(
            target, "MigrateInstall", {"name": name, "probe": True}
        )
        base = probe.get("have")
    except (grpc.RpcError, protocol.BloomServiceError):
        base = None
    # storage-aware lookup (ISSUE 14, control plane — never quota-shed):
    # a paged tenant hydrates for its handoff — the snapshot-under-op-
    # lock + dual-write arming below need the live filter. (Hydrate-on-
    # MOVED — handing off the checkpoint POINTER for a COLD tenant
    # instead of streaming the blob — is the documented stretch, not
    # built yet.)
    mf = service._resident(name)
    if mf is None:
        return  # dropped concurrently — nothing to move
    oplog = service.oplog
    if base is not None and oplog is not None and oplog.has_cursor(int(base)):
        # resume: the target's gate says its state covers the source log
        # up to `base` and the log still holds the tail — arm the
        # dual-write FIRST (everything committed after this line
        # forwards live), then replay the gap. Overlap between the two
        # is absorbed by the target's seq gate.
        cluster.begin_forwarding(name, target)
        head = oplog.last_seq
        n = 0
        for rec in oplog.read_from(int(base)):
            if rec["seq"] > head:
                break
            if rec["req"].get("name") != name:
                continue
            if rec["method"] not in protocol.MUTATING_METHODS:
                continue
            faults.fire("cluster.migrate_send")
            _forward_record(cluster, target, rec)
            n += 1
        stats["tail_records"] += n
        _counters.incr("cluster_migrate_tail_records", n)
        return
    # snapshot path: blob + seq stamp + forward arming are one atomic
    # step under the op lock — a write serialized after the snapshot is
    # by construction a write the wrapper will forward
    with mf.lock:
        _, _, blob = ckpt.snapshot_blob(mf.filter)
        snap_seq = mf.applied_seq
        cluster.begin_forwarding(name, target)
    faults.fire("cluster.migrate_send")
    cluster.call(
        target,
        "MigrateInstall",
        {"name": name, "blob": blob, "src_seq": snap_seq},
        timeout=INSTALL_TIMEOUT_S,
    )
    stats["snapshots"] += 1
    _counters.incr("cluster_migrate_snapshots_sent")


def _forward_record(cluster, target: str, rec: dict) -> None:
    """Replay one source-log record on the target as an ``asking``
    request in the original rid, stamped with its source seq for the
    exactly-once gate."""
    req = {
        k: v
        for k, v in rec["req"].items()
        if k not in ("restored_seq", "epoch")
    }
    req["asking"] = True
    req["src_seq"] = rec["seq"]
    if rec.get("rid"):
        req["rid"] = rec["rid"]
    cluster.call(target, rec["method"], req, timeout=FORWARD_TIMEOUT_S)


def forward_op(service, method: str, req: dict, resp: dict) -> dict:
    """Dual-write hook, called by the RPC wrapper AFTER a mutating RPC
    committed (and cleared its durability barrier, outside all locks):
    when the filter is mid-migration, the op must land on the target
    BEFORE the client is acked — an acked write existing only on the
    source is exactly the loss the handoff must exclude.

    A forward failure fails the RPC with ``MIGRATE_FORWARD_FAILED``
    (``applied: true`` — Redis WAIT-style: the local apply stands). The
    client retries under the same rid: the source answers the replay
    from its dedup cache / idempotent apply and this hook forwards
    again; the target's seq gate + rid dedup make the re-delivery
    exactly-once."""
    cluster = service.cluster
    name = req.get("name")
    if cluster is None or not isinstance(name, str):
        return resp
    target = cluster.forward_target(name)
    if target is None:
        return resp
    fwd = {
        k: v
        for k, v in req.items()
        if k not in ("epoch", "min_replicas", "min_replicas_timeout_ms",
                     "asking", "src_seq", "restored_seq")
    }
    fwd["asking"] = True
    if resp.get("repl_seq") is not None:
        fwd["src_seq"] = int(resp["repl_seq"])
    try:
        # the dual-write hop is part of the request's latency story —
        # a child span names the target so "where did my write spend
        # 30ms" has an answer during migration windows (ISSUE 15)
        with obs_trace.span("cluster.forward", target=target):
            cluster.call(target, method, fwd, timeout=FORWARD_TIMEOUT_S)
    except (grpc.RpcError, protocol.BloomServiceError) as e:
        _counters.incr("cluster_forward_failures")
        details = {"applied": True, "target": target}
        if fwd.get("src_seq") is not None:
            # the re-drive needs the record's seq: if the handoff
            # finalizes mid-re-drive, the MOVED follow-up applies at
            # the new owner and MUST carry src_seq or a record the
            # snapshot already contains would apply twice
            details["src_seq"] = fwd["src_seq"]
        raise protocol.BloomServiceError(
            "MIGRATE_FORWARD_FAILED",
            f"{method} applied locally but its migration forward to "
            f"{target} failed ({e}); retry under the same rid",
            details=details,
        )
    _counters.incr("cluster_forwards")
    resp["forwarded"] = True
    return resp
