"""Counting-filter kernels: saturating scatter-add on packed 4-bit counters.

Parity: BASELINE config 4 — "Counting Bloom filter variant (4-bit counters,
m=2^30) — insert/delete/query mix, exercises scatter-add". The counting
variant restores delete support, which a plain bloom filter lacks
(SURVEY.md §2.3).

Layout: counter ``pos`` lives in word ``pos >> 3``, nibble ``pos & 7`` of a
packed ``uint32[m / 8]`` array. Semantics (ground truth in
``cpu_ref._counter_add``): increments saturate at 15, decrements floor at 0,
and duplicate positions within one batch apply their full multiplicity
(clamped once against the pre-batch value — matching a sequential
apply-then-clamp only when no mid-batch crossing occurs; both oracles use
the same one-clamp rule so they agree bit-for-bit).

Why not plain scatter-add: nibble saturation must not carry into the
neighboring counter, and duplicate indices must be combined *before*
clamping. The kernel therefore does a two-level segmented reduction over one
sort: counts per counter (runs of equal pos), clamped against the gathered
current nibble, then summed per word — contributions live in disjoint nibble
lanes, so the word-level sum cannot carry — and scatter-set uniquely.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from tpubloom.ops.bitops import segmented_scan_last


def _u32(x) -> jnp.ndarray:
    return jnp.asarray(x, jnp.uint32)


def counter_update(
    words: jnp.ndarray,
    pos: jnp.ndarray,
    valid: jnp.ndarray,
    *,
    increment: bool,
) -> jnp.ndarray:
    """Apply a saturating +1/-1 per valid position to the packed counters.

    Args:
      words: ``uint32[n_counter_words]`` packed 4-bit counters.
      pos: ``int32[N]`` counter positions (flattened batch × k); requires
        m < 2^31 (config.m for counting filters is at most 2^30 per BASELINE).
      valid: ``bool[N]`` batch-padding mask.
      increment: True for insert (+1, saturate 15), False for delete
        (-1, floor 0).
    """
    n_words = words.shape[0]
    sentinel = jnp.int32(n_words * 8)
    p = jnp.where(valid, pos, sentinel).astype(jnp.int32)
    (p,) = lax.sort((p,), num_keys=1)

    # Level 1: multiplicity of each distinct counter position.
    ones = jnp.ones_like(p, jnp.uint32)
    counts, pos_last = segmented_scan_last(p, ones, jnp.add)

    word = jnp.minimum(p >> 3, n_words - 1)
    nib = (p & 7).astype(jnp.uint32)
    shift = _u32(4) * nib
    val = (words[word] >> shift) & _u32(15)

    if increment:
        delta = jnp.minimum(counts, _u32(15) - val)
    else:
        delta = jnp.minimum(counts, val)
    # Only the last element of each counter-run contributes, in its own
    # nibble lane — lanes are disjoint within a word, so summing cannot carry.
    contrib = jnp.where(pos_last, delta << shift, _u32(0))

    # Level 2: sum contributions per word (p sorted => word sorted).
    wkey = (p >> 3).astype(jnp.int32)
    contrib_sum, word_last = segmented_scan_last(wkey, contrib, jnp.add)

    target = jnp.where(word_last & (wkey < n_words), wkey, n_words)
    current = words[word]
    merged = current + contrib_sum if increment else current - contrib_sum
    return words.at[target].set(merged, mode="drop", unique_indices=True)


def counter_get(words: jnp.ndarray, pos: jnp.ndarray) -> jnp.ndarray:
    """Gather counter values: ``uint32[...]`` in [0, 15]."""
    word = pos >> 3
    shift = _u32(4) * (pos & 7).astype(jnp.uint32)
    return (words[word] >> shift) & _u32(15)


def counting_membership(words: jnp.ndarray, pos: jnp.ndarray) -> jnp.ndarray:
    """``bool[B]``: all k counters of each key are nonzero (pos is [B, k])."""
    return jnp.all(counter_get(words, pos) > 0, axis=-1)


def blocked_counting_membership(
    blocks: jnp.ndarray, blk: jnp.ndarray, cpos: jnp.ndarray
) -> jnp.ndarray:
    """``bool[B]`` blocked-counting membership: one row gather per key +
    all-counters-nonzero over the in-block positions. The single source of
    the 4-bit (word = c >> 3, nibble = c & 7) unpacking shared by the
    single-chip and sharded query paths.

    ``blocks uint32[NB, W]``, ``blk int32[B]``, ``cpos uint32[B, k]``.
    """
    rows = blocks[blk]  # [B, W]
    word = (cpos >> jnp.uint32(3)).astype(jnp.int32)  # [B, k] in [0, W)
    nib = (cpos & jnp.uint32(7)) * jnp.uint32(4)
    vals = jnp.take_along_axis(rows, word, axis=-1)
    cnt = (vals >> nib) & _u32(15)
    return jnp.all(cnt > 0, axis=-1)


def fat_blocked_counting_membership(
    blocks_fat: jnp.ndarray, blk: jnp.ndarray, cpos: jnp.ndarray, w: int
) -> jnp.ndarray:
    """Blocked-counting membership against the FAT [NB/J, 128] counter
    view: one fat-row gather per key, then each counter's word selected
    by a lane-compare masked reduce (k dense [B, 128] passes — NOT
    take_along_axis, which scalarizes on TPU; same nibble decode as
    :func:`blocked_counting_membership`). Shared by the single-chip and
    sharded fat query paths."""
    J = 128 // w
    rf = (blk // J).astype(jnp.int32)
    lane0 = ((blk % J) * w).astype(jnp.int32)
    rows128 = blocks_fat[rf]  # [B, 128] row gather
    lane = lax.broadcasted_iota(jnp.int32, rows128.shape, 1)
    ok = None
    k = cpos.shape[-1]
    for i in range(k):
        li = lane0 + (cpos[:, i] >> jnp.uint32(3)).astype(jnp.int32)
        vi = jnp.sum(
            jnp.where(lane == li[:, None], rows128, _u32(0)),
            axis=1, dtype=jnp.uint32,
        )  # [B] — the selected word (exactly one lane matches)
        cnt = (vi >> ((cpos[:, i] & jnp.uint32(7)) * jnp.uint32(4))) & _u32(15)
        hit = cnt > 0
        ok = hit if ok is None else (ok & hit)
    return ok
