"""Single-device bit-array kernels: fused scatter-OR insert, gather-AND query.

Parity: these are the device-side replacement for the reference hot path —
``SETBIT pos 1`` per position on insert, ``GETBIT`` + AND on query
(BASELINE.json north_star: "inserts/queries are fused scatter-OR /
gather-AND reductions"; SURVEY.md §3.2-§3.3).

Design notes (TPU/XLA-first):

* The filter is a packed ``uint32[n_words]`` array resident in HBM; bit
  ``pos`` is ``words[pos >> 5] & (1 << (pos & 31))``.
* XLA's scatter supports add/mul/min/max combiners but **not bitwise OR**,
  and scatter-add is wrong for bits (duplicate positions carry into
  neighboring bits). The pure-XLA answer implemented here:

    1. sort (word, mask) pairs by word — ``lax.sort`` is well-tuned on TPU;
    2. segmented inclusive OR-scan (Hillis–Steele, log2 N dense vectorized
       steps) so the *last* element of each equal-word run holds the OR of
       the whole run;
    3. gather the current words, OR in the run masks, and scatter-set with
       ``unique_indices`` — losers' indices are redirected out of bounds and
       dropped, so every applied update targets a distinct word.

  Everything is dense, statically-shaped, and fuses well; there is no
  data-dependent control flow. A fused Pallas hash+scatter kernel is the
  escape hatch if this is the throughput wall (SURVEY.md §7).
* Batch padding: entries with ``valid == False`` (host pads batches to a
  static shape) are redirected to the out-of-bounds sentinel and dropped.
* Insert races are benign by construction — scatter-OR is commutative and
  idempotent (SURVEY.md §5 "Race detection").
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def _u32(x) -> jnp.ndarray:
    return jnp.asarray(x, jnp.uint32)


def segmented_scan_last(
    keys: jnp.ndarray, vals: jnp.ndarray, op
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Inclusive segmented scan over runs of equal (sorted) keys.

    ``vals`` may be 1-D ``[N]`` (flat scatter-OR path) or N-D ``[N, ...]``
    (blocked layout: one mask row per key) — trailing dims are combined
    elementwise within each run.

    Returns ``(scanned_vals, is_last)`` where ``scanned_vals[i]`` combines all
    ``vals[j]`` with ``j <= i`` in i's run, and ``is_last[i]`` marks the final
    element of each run (which therefore holds the full-run reduction).

    Hillis–Steele with log2(N) dense steps — each step is a shift + compare +
    select, all vectorizable on the VPU; no scatter, no dynamic shapes.
    """
    n = keys.shape[0]
    shift = 1
    while shift < n:
        prev_keys = jnp.concatenate([jnp.full((shift,), -1, keys.dtype), keys[:-shift]])
        prev_vals = jnp.concatenate(
            [jnp.zeros((shift,) + vals.shape[1:], vals.dtype), vals[:-shift]]
        )
        same = prev_keys == keys
        same = same.reshape(same.shape + (1,) * (vals.ndim - 1))
        vals = jnp.where(same, op(vals, prev_vals), vals)
        shift *= 2
    is_last = jnp.concatenate([keys[:-1] != keys[1:], jnp.ones((1,), bool)])
    return vals, is_last


def scatter_or(
    bits: jnp.ndarray, word_idx: jnp.ndarray, bit: jnp.ndarray, valid: jnp.ndarray
) -> jnp.ndarray:
    """OR ``1 << bit`` into ``bits[word_idx]`` for every valid entry.

    Args:
      bits: ``uint32[n_words]`` packed filter.
      word_idx: ``int32[N]`` word indices (flattened batch × k).
      bit: ``uint32[N]`` bit offsets in [0, 32).
      valid: ``bool[N]`` — False entries (batch padding) are dropped.

    Returns the updated ``bits`` (functionally; jit callers donate the input).
    """
    n_words = bits.shape[0]
    masks = _u32(1) << bit
    w = jnp.where(valid, word_idx, n_words).astype(jnp.int32)
    w, masks = lax.sort((w, masks), num_keys=1)
    masks, is_last = segmented_scan_last(w, masks, jnp.bitwise_or)
    target = jnp.where(is_last & (w < n_words), w, n_words)
    current = bits[jnp.minimum(w, n_words - 1)]
    merged = current | masks
    return bits.at[target].set(merged, mode="drop", unique_indices=True)


def gather_test(
    bits: jnp.ndarray, word_idx: jnp.ndarray, bit: jnp.ndarray
) -> jnp.ndarray:
    """Gather the addressed bits: returns ``uint32`` 0/1 per entry."""
    vals = bits[word_idx]
    return (vals >> bit) & _u32(1)


def query_membership(
    bits: jnp.ndarray, word_idx: jnp.ndarray, bit: jnp.ndarray
) -> jnp.ndarray:
    """AND-reduce the k bits of each key: ``bool[B]`` membership.

    ``word_idx``/``bit`` are ``[B, k]``. No short-circuit on the first zero
    bit — SIMD computes all k and reduces (SURVEY.md §3.3: the batched path
    deliberately drops the reference's scalar short-circuit).
    """
    hits = gather_test(bits, word_idx, bit)
    return jnp.all(hits == 1, axis=-1)


def popcount_fill(bits: jnp.ndarray, m: int) -> jnp.ndarray:
    """Fraction of set bits — drives estimated-FPR observability
    (fill^k ~ predicted FPR; SURVEY.md §5 metrics)."""
    set_bits = jnp.sum(jax.lax.population_count(bits).astype(jnp.float32))
    return set_bits / m
