"""Device-side ops: hashing, bit kernels, counting kernels, Pallas kernels."""
