"""Count-min sketch kernels (ISSUE 19).

A count-min sketch (Cormode & Muthukrishnan '05) is a ``[depth, width]``
counter grid: each key hashes to one counter per row; increment adds to
all ``depth`` counters, estimate takes their min. Estimates only ever
OVER-count (every counter a key touches also absorbs other keys'
increments), with error ≤ ``e/width * N`` at confidence ``1 - e^-depth``
for N total increments.

Position derivation reuses the bloom family's row machinery wholesale:
``hashing.positions(m=width, k=depth)`` — the exact double-hashing spec
every other kind uses, so the hash kernels, tests, and the Ruby parity
story stay single-source. Storage is the FLAT ``uint32[depth * width]``
array (row-major), the same 1-D uint32 shape the checkpoint/replication
planes already move around; counters saturate at 2^32-1 in the sense
that wraparound is the caller's capacity-planning problem (4 billion
increments per cell), as with Redis' CMS.

The update is ONE scatter-add over the flat array — ``.at[idx].add``
has accumulating semantics for duplicate indices, so intra-batch
duplicate keys (and row collisions between keys) are handled natively
with no sort/segment pass. The estimate is one gather + row-min.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from tpubloom.ops import hashing


def cms_positions(keys, lengths, *, width: int, depth: int, seed: int):
    """Per-row counter positions: uint32[..., depth] in [0, width).

    Thin wrapper over the shared :func:`tpubloom.ops.hashing.positions`
    spec with m=width, k=depth. width < 2^31 always holds for sketches,
    so the low word carries the whole position.
    """
    _, pos_lo = hashing.positions(keys, lengths, m=width, k=depth, seed=seed)
    return pos_lo


def _flat_indices(words, pos):
    """[B, depth] flat row-major indices into the [depth*width] array."""
    depth = pos.shape[-1]
    width = words.shape[0] // depth
    row_off = (jnp.arange(depth, dtype=jnp.uint32) * jnp.uint32(width))[None, :]
    return (row_off + pos).astype(jnp.int32)


@jax.jit
def cms_update(words, pos, valid, increments):
    """Scatter-add ``increments`` into every row's counter.

    Args:
      words: uint32[depth*width] flat counter grid.
      pos: uint32[B, depth] from :func:`cms_positions`.
      valid: bool[B] lane mask.
      increments: uint32[B] per-key deltas.

    Returns the updated flat grid.
    """
    flat = _flat_indices(words, pos).reshape(-1)
    inc = jnp.where(valid, increments, jnp.uint32(0))
    inc = jnp.broadcast_to(inc[:, None], pos.shape).reshape(-1)
    return words.at[flat].add(inc)


@jax.jit
def cms_estimate(words, pos, valid):
    """Point estimate per key: min over its row counters. uint32[B]."""
    vals = words[_flat_indices(words, pos)]  # [B, depth] gather
    est = vals.min(axis=-1)
    return jnp.where(valid, est, jnp.uint32(0))
