"""Device-side hash family: MurmurHash3_x86_32 + FNV-1a over fixed-shape keys.

This module is the framework's **bit-exactness contract**. The CPU oracle
(:mod:`tpubloom.cpu_ref`), the C++ native library (``tpubloom/native``) and
these jnp kernels must all produce identical bits; tests enforce it against
published test vectors and with hypothesis-generated keys.

Parity: the reference's hot path is "k× MurmurHash3/FNV-1a hashing followed
by SETBIT/GETBIT against the m-bit array" (BASELINE.json north_star;
SURVEY.md §2.1 "Hashing engine" — double hashing h_i = h1 + i·h2 mod m is
the standard trick to derive k positions from 2 base hashes).

THE POSITION SPEC (canonical, shared by every implementation)
-------------------------------------------------------------
Keys are byte strings of length ``len <= key_len``, zero-padded on device to
``uint8[B, key_len]`` with true lengths in ``int32[B]``. All hashing is over
the *true* bytes (padding never changes a hash — murmur3's tail construction
and fnv1a's byte loop are masked by length).

Base hashes (u32 each)::

  h_a = murmur3_32(key, seed)
  h_b = murmur3_32(key, seed XOR 0x9E3779B9)      # golden ratio
  g_a = fnv1a_32(key)
  g_b = murmur3_32(key, seed XOR 0x85EBCA6B)      # murmur fmix constant

Positions, power-of-two m (m = 2^logm, logm <= 36)::

  H1 = h_b·2^32 + h_a
  H2 = (g_b·2^32 + g_a) | 1                        # odd stride
  pos_i = (H1 + i·H2 mod 2^64) mod m,  i = 0..k-1

Positions, non-power-of-two m (m < 2^31)::

  pos_i = ((h_a + i·(g_a | 1)) mod 2^32) mod m

The 64-bit arithmetic is carried out in u32 (hi, lo) pairs on device — TPUs
have no u64 — via k-step iterative addition with carry, which is exactly
``(H1 + i·H2) mod 2^64``.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

# MurmurHash3_x86_32 constants (public domain algorithm by Austin Appleby).
_C1 = 0xCC9E2D51
_C2 = 0x1B873593
_FMIX1 = 0x85EBCA6B
_FMIX2 = 0xC2B2AE35

# FNV-1a 32-bit constants.
_FNV_OFFSET = 0x811C9DC5
_FNV_PRIME = 0x01000193

# Seed derivation constants (part of the position spec above).
SEED_XOR_HB = 0x9E3779B9
SEED_XOR_GB = 0x85EBCA6B
# Shard-routing hash seed (sharded filter array, BASELINE config 5):
# shard(key) = murmur3_32(key, seed XOR SEED_XOR_ROUTE) mod n_shards.
# Independent of the position hashes so routing doesn't correlate with
# within-shard positions.
SEED_XOR_ROUTE = 0x517CC1B7


def _u32(x) -> jnp.ndarray:
    return jnp.asarray(x, jnp.uint32)


def _rotl32(x: jnp.ndarray, r: int) -> jnp.ndarray:
    r = r % 32
    return (x << _u32(r)) | (x >> _u32(32 - r))


def murmur3_32(keys: jnp.ndarray, lengths: jnp.ndarray, seed) -> jnp.ndarray:
    """MurmurHash3_x86_32 of each key.

    Args:
      keys: ``uint8[..., L]`` zero-padded key bytes, L a multiple of 4.
        Bytes at positions >= length MUST be zero (``pack_keys`` guarantees
        this); they flow into the tail word construction, where zeros are
        exactly what the reference algorithm's partial tail load produces.
      lengths: ``int32[...]`` true byte lengths, 0 <= length <= L.
      seed: u32 seed (python int or u32 array broadcastable to lengths).

    Returns:
      ``uint32[...]`` hashes, bit-exact with the canonical C implementation.
    """
    L = keys.shape[-1]
    if L % 4 != 0:
        raise ValueError(f"key buffer length must be a multiple of 4, got {L}")
    # Little-endian 32-bit blocks: block[i] = bytes[4i] | bytes[4i+1]<<8 | ...
    # — exactly what a little-endian bitcast of 4 consecutive bytes gives
    # (XLA bitcast_convert_type is LE on every supported backend; the
    # strided-shift formulation is equivalent but costs 4 strided u8
    # relayouts per block on TPU). The astype is a no-op for the uint8
    # arrays every internal caller passes; it keeps byte values in wider
    # dtypes bit-exact rather than silently mis-bitcasting them.
    blocks = lax.bitcast_convert_type(
        keys.astype(jnp.uint8).reshape(keys.shape[:-1] + (L // 4, 4)),
        jnp.uint32,
    )
    lengths = lengths.astype(jnp.int32)
    h = jnp.broadcast_to(_u32(seed), lengths.shape)
    c1, c2 = _u32(_C1), _u32(_C2)
    for i in range(L // 4):
        blk = blocks[..., i]
        kk = blk * c1
        kk = _rotl32(kk, 15)
        kk = kk * c2
        rem = lengths - 4 * i  # bytes of the key at/after this block
        # Full block: mix + rotate + scramble. Tail (1-3 bytes): mix only.
        h_full = _rotl32(h ^ kk, 13) * _u32(5) + _u32(0xE6546B64)
        h_tail = h ^ kk
        h = jnp.where(rem >= 4, h_full, jnp.where(rem > 0, h_tail, h))
    # Finalization.
    h = h ^ lengths.astype(jnp.uint32)
    h = h ^ (h >> _u32(16))
    h = h * _u32(_FMIX1)
    h = h ^ (h >> _u32(13))
    h = h * _u32(_FMIX2)
    h = h ^ (h >> _u32(16))
    return h


def fnv1a_32(keys: jnp.ndarray, lengths: jnp.ndarray) -> jnp.ndarray:
    """FNV-1a 32-bit of each key (same shape contract as :func:`murmur3_32`).

    The byte loop is unrolled over the static buffer length and masked by the
    true length, so padding bytes never enter the hash.
    """
    L = keys.shape[-1]
    lengths = lengths.astype(jnp.int32)
    h = jnp.broadcast_to(_u32(_FNV_OFFSET), lengths.shape)
    prime = _u32(_FNV_PRIME)
    if L % 4 == 0:
        # extract bytes from bitcast u32 words (4 lanes instead of 16
        # strided u8 lanes — cheaper layout on TPU)
        words = lax.bitcast_convert_type(
            keys.astype(jnp.uint8).reshape(keys.shape[:-1] + (L // 4, 4)),
            jnp.uint32,
        )
        byte = lambda j: (words[..., j >> 2] >> _u32(8 * (j & 3))) & _u32(0xFF)
    else:
        kb = keys.astype(jnp.uint32)
        byte = lambda j: kb[..., j]
    for j in range(L):
        h_next = (h ^ byte(j)) * prime
        h = jnp.where(j < lengths, h_next, h)
    return h


def base_hashes(
    keys: jnp.ndarray, lengths: jnp.ndarray, seed: int
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """The four u32 base hashes ``(h_a, h_b, g_a, g_b)`` of the spec."""
    h_a = murmur3_32(keys, lengths, seed)
    h_b = murmur3_32(keys, lengths, seed ^ SEED_XOR_HB)
    g_a = fnv1a_32(keys, lengths)
    g_b = murmur3_32(keys, lengths, seed ^ SEED_XOR_GB)
    return h_a, h_b, g_a, g_b


def positions(
    keys: jnp.ndarray,
    lengths: jnp.ndarray,
    *,
    m: int,
    k: int,
    seed: int,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """The k filter positions of each key, as u32 (hi, lo) pairs.

    Returns:
      ``(pos_hi, pos_lo)``, each ``uint32[..., k]``, with
      position = pos_hi·2^32 + pos_lo, already reduced mod m.
      For m <= 2^32, pos_hi is all zeros.
    """
    if (m & (m - 1)) == 0:
        return _positions_pow2(keys, lengths, m=m, k=k, seed=seed)
    if m >= (1 << 31):
        raise ValueError("non-power-of-two m must be < 2^31")
    return _positions_mod(keys, lengths, m=m, k=k, seed=seed)


def _positions_pow2(keys, lengths, *, m: int, k: int, seed: int):
    logm = m.bit_length() - 1
    if logm > 36:
        # split_word_bit packs word = pos >> 5 into int32: logm <= 36 keeps
        # word < 2^31. Larger filters must shard (config 5 path).
        raise ValueError(f"m up to 2^36 supported, got 2^{logm}")
    h_a, h_b, g_a, g_b = base_hashes(keys, lengths, seed)
    g_a = g_a | _u32(1)  # odd 64-bit stride
    lo, hi = h_a, h_b
    lo_mask = _u32(0xFFFFFFFF if logm >= 32 else (1 << logm) - 1)
    hi_mask = _u32((1 << (logm - 32)) - 1 if logm > 32 else 0)
    out_hi, out_lo = [], []
    for i in range(k):
        if i > 0:
            # (hi, lo) += (g_b, g_a) mod 2^64 — carry via unsigned wrap test.
            lo_next = lo + g_a
            carry = (lo_next < lo).astype(jnp.uint32)
            hi = hi + g_b + carry
            lo = lo_next
        out_lo.append(lo & lo_mask)
        out_hi.append(hi & hi_mask)
    return jnp.stack(out_hi, axis=-1), jnp.stack(out_lo, axis=-1)


def _positions_mod(keys, lengths, *, m: int, k: int, seed: int):
    h_a = murmur3_32(keys, lengths, seed)
    g_a = fnv1a_32(keys, lengths) | _u32(1)
    out = []
    pos = h_a
    for i in range(k):
        if i > 0:
            pos = pos + g_a  # u32 wrap == mod 2^32
        out.append(pos % _u32(m))
    lo = jnp.stack(out, axis=-1)
    return jnp.zeros_like(lo), lo


def route_shards(
    keys: jnp.ndarray, lengths: jnp.ndarray, *, n_shards: int, seed: int
) -> jnp.ndarray:
    """Owning shard of each key: ``uint32[...]`` in [0, n_shards)."""
    h = murmur3_32(keys, lengths, seed ^ SEED_XOR_ROUTE)
    return h % _u32(n_shards)


def split_word_bit(
    pos_hi: jnp.ndarray, pos_lo: jnp.ndarray
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Packed-u32 bit-array coordinates of positions.

    word = pos >> 5 (int32 — valid for m <= 2^36), bit = pos & 31.
    Bit b of word w is ``(1 << b)`` — LSB-first within the word. The
    Redis-bitmap byte order conversion lives in ``tpubloom.utils.packing``.
    """
    word = ((pos_lo >> _u32(5)) | (pos_hi << _u32(27))).astype(jnp.int32)
    bit = pos_lo & _u32(31)
    return word, bit


def split_counter(
    pos_hi: jnp.ndarray, pos_lo: jnp.ndarray
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Packed 4-bit-counter coordinates: word = pos >> 3, nibble = pos & 7."""
    word = ((pos_lo >> _u32(3)) | (pos_hi << _u32(29))).astype(jnp.int32)
    nib = pos_lo & _u32(7)
    return word, nib
